"""Audit manager: periodic full-cluster sweeps, optionally incremental.

Counterpart of the reference pkg/audit/manager.go, re-designed around the
batched evaluator. The reference's hot loop lists every object of every
listable GVK and calls Review one object at a time (manager.go:250-271);
here the whole inventory goes through the driver's vectorized audit in one
batched sweep (audit-from-cache) or per-GVK batches (discovery mode), then
violations are aggregated per constraint (manager.go:337-385) and written
to constraint status with the violations cap, message truncation, and
conflict-retry loop (manager.go:428-574).

Incremental mode (--audit-incremental) replaces the per-sweep O(cluster)
re-list + re-encode with a PERSISTENT encoded inventory: a tracked mirror
of every auditable object keyed (uid, resourceVersion), fed by streaming
watches, applied to the driver's synced inventory each sweep so the
driver's journaled caches patch only the dirty rows (feature tensors,
match masks, and device buffers stay resident between sweeps). Watch gaps
fall back to a resourceVersion-diff against a paged re-list; every
--audit-full-resync-every sweeps the whole inventory re-encodes from
scratch as a self-healing backstop. Constraint-status writes are also
delta'd: a constraint whose violation set did not change since its last
written status is skipped entirely.
"""

from __future__ import annotations

import heapq
import json
import threading
import time
from typing import Any, Callable, Optional

from ..client import Client, Result
from ..utils import profiling
from ..utils.values import thaw
from . import metrics
from . import trace as gtrace
from .kube import GVK, KubeError, NotFound, ScopedKube, WatchEvent
from .logging import logger
from .util import prune_stale_by_pod

log = logger("audit")

DEFAULT_AUDIT_INTERVAL = 60  # seconds (reference manager.go:36,41)
DEFAULT_CONSTRAINT_VIOLATIONS_LIMIT = 20  # manager.go:37,42
DEFAULT_FULL_RESYNC_EVERY = 20  # incremental sweeps per full re-encode
# streaming audit (--stream-audit): debounce window after the first
# buffered watch event before a flush, and the pending-event count that
# flushes early (a burst must not wait out the window event by event)
DEFAULT_STREAM_WINDOW_S = 0.025
DEFAULT_STREAM_MAX_BATCH = 512
MSG_SIZE_LIMIT = 256  # bytes (manager.go:35,437-439)
CONSTRAINT_GROUP = "constraints.gatekeeper.sh"

# kinds never audited (cluster plumbing the reference also skips)
_SKIP_KINDS = {"Event", "ComponentStatus", "Endpoints", "EndpointSlice",
               "Lease", "SelfSubjectReview", "TokenReview",
               "SubjectAccessReview", "CustomResourceDefinition",
               "ConstraintTemplate"}


def _auditable_gvks(kube) -> list[GVK]:
    """Discovery-driven auditable GVK set (same filter as the discovery
    sweep): listable, not control-plane plumbing, not our own CRs."""
    out = []
    for r in kube.server_preferred_resources():
        if "list" not in (r.get("verbs") or []):
            continue
        if r.get("kind") in _SKIP_KINDS:
            continue
        if r.get("group") in ("templates.gatekeeper.sh", CONSTRAINT_GROUP):
            continue
        out.append((r.get("group") or "", r.get("version") or "",
                    r.get("kind") or ""))
    # Namespaces first: their labels feed namespaceSelector matching for
    # everything else, so the initial encode must see them early
    out.sort(key=lambda g: (g[2] != "Namespace", g))
    return out


def _obj_key(gvk: GVK, obj: dict) -> tuple:
    meta = obj.get("metadata") or {}
    return (tuple(gvk), meta.get("namespace") or "", meta.get("name") or "")


def _obj_ver(obj: dict) -> tuple:
    meta = obj.get("metadata") or {}
    return (meta.get("uid"), meta.get("resourceVersion"))


class InventoryTracker:
    """Persistent encoded-inventory maintenance for the incremental audit.

    Mirrors the auditable cluster state into the policy client's synced
    inventory: per-GVK streaming watches accumulate a DIRTY MAP (latest
    event per object key — bounded by the inventory size, so an event
    burst collapses instead of queueing unboundedly), and each sweep
    applies only the delta through client.add_data/remove_data, which the
    driver's patch journal turns into in-place row patches of its cached
    feature tensors. A `(uid, resourceVersion)` state map suppresses
    no-op events and detects delete-then-recreate (same name, new uid).

    GVKs whose watch cannot be established (or that signaled a gap — a
    410 Gone the client could not bridge, an overflowed stream) fall back
    to a resourceVersion-diff against a paged re-list on every sweep
    until the watch heals.
    """

    def __init__(self, kube, opa: Client, sink=None):
        self.kube = kube
        self.opa = opa
        # where applied deltas land: the client itself by default; the
        # sharded plane can substitute a routing sink (leader apply +
        # owner-shard fan-out) without the tracker knowing about shards
        self.sink = sink if sink is not None else opa
        self._lock = threading.Lock()
        self._dirty: dict[tuple, tuple] = {}   # key -> (etype, obj)
        # streaming audit: monotonic receipt time of the OLDEST pending
        # event per dirty key (coalescing keeps the first — detection
        # latency is measured from the earliest unserved change), and
        # an observer fired (outside the lock) whenever a watch event
        # lands so the stream loop can debounce-flush instead of
        # polling. Both are no-ops until the stream loop arms them.
        self._dirty_at: dict[tuple, float] = {}
        self.track_event_times = False
        self.on_event: Optional[Callable[[], None]] = None
        self._state: dict[tuple, tuple] = {}   # key -> (uid, rv)
        self._cancels: dict[GVK, Callable[[], None]] = {}
        self._poll: set[GVK] = set()   # watchless GVKs: re-list per sweep
        self._gaps: set[GVK] = set()   # one-shot resync requests
        # last event resourceVersion per GVK: persisted in the state
        # snapshot so a restarted pod's watches RESUME from where the
        # old process stopped instead of re-listing the cluster
        self._rvs: dict[GVK, str] = {}
        # warm-restart validation gate: set once restored state has been
        # re-validated against a live list (readyz consults this); a
        # cold tracker is trivially validated
        self.validated = threading.Event()
        self.validated.set()
        self._restoring = False
        # consecutive full-resyncs a tracked GVK was absent from
        # discovery: dropping (and purging its inventory) on the FIRST
        # absence would let one flaky discovery response evict whole
        # kinds from the shared inventory
        self._gvk_missing: dict[GVK, int] = {}

    # ------------------------------------------------------------- watches

    def gvks(self) -> list[GVK]:
        with self._lock:
            return sorted(set(self._cancels) | self._poll)

    def set_gvks(self, gvks: list[GVK], resync_new: bool = True) -> None:
        """Reconcile the watched set; newly added GVKs are subscribed
        FIRST and then resynced, so no event can fall between the list
        and the watch (racing duplicates are no-op'd by the state map).
        full_resync passes resync_new=False — its own re-list seeds the
        state, so the per-GVK resync here would double-list the cluster."""
        want = {tuple(g) for g in gvks}
        with self._lock:
            have = set(self._cancels) | self._poll
            drop = have - want
            add = want - have
            for g in drop:
                cancel = self._cancels.pop(g, None)
                if cancel is not None:
                    cancel()
                self._poll.discard(g)
        for g in sorted(drop):
            self._forget_gvk(g)
        for g in sorted(add):
            self._watch_gvk(g)
            if resync_new:
                self.resync(g)

    def _watch_gvk(self, gvk: GVK, quiet: bool = False) -> bool:
        def deliver(event: WatchEvent, _gvk=gvk):
            self._note_event(_gvk, event)

        with self._lock:
            resume_rv = self._rvs.get(tuple(gvk), "")
        try:
            # resume from the last-seen RV when we have one; if the
            # server rejects it (compacted while down), on_gap schedules
            # the list-diff reconcile for anything missed
            cancel = self.kube.watch(
                gvk, deliver, send_initial=False,
                resource_version=resume_rv,
                on_gap=lambda _gvk=tuple(gvk): self.note_gap(_gvk))
        except Exception as e:
            # no stream for this GVK: degrade to per-sweep re-list diff
            # (the reference's ListerWatcher would relist on 410 Gone);
            # apply_pending retries the subscription every sweep
            if not quiet:
                log.warning("watch unavailable; falling back to "
                            "per-sweep re-list diff",
                            details={"gvk": list(gvk), "error": str(e)})
            with self._lock:
                self._poll.add(tuple(gvk))
            return False
        with self._lock:
            self._cancels[tuple(gvk)] = cancel
            self._poll.discard(tuple(gvk))
        return True

    def _note_event(self, gvk: GVK, event: WatchEvent) -> None:
        obj = event.object or {}
        key = _obj_key(gvk, obj)
        rv = (obj.get("metadata") or {}).get("resourceVersion")

        def as_int(v):
            try:
                return int(v)
            except (TypeError, ValueError):
                return None

        rv_i = as_int(rv)
        notify = None
        with self._lock:
            cur = self._dirty.get(key)
            if cur is not None and rv_i is not None:
                # never let a replayed/stale event clobber a NEWER
                # pending one for the same object (a resumed watch's
                # snapshot replay can interleave behind a live event);
                # at EQUAL rv a pending DELETED wins — a deletion
                # carries the object's last rv, so an equal-rv MODIFIED
                # is the replay of the state that deletion removed.
                # Non-numeric RVs keep last-write-wins.
                cur_i = as_int((cur[1].get("metadata") or {})
                               .get("resourceVersion"))
                if cur_i is not None and \
                        (rv_i < cur_i
                         or (rv_i == cur_i and cur[0] == "DELETED"
                             and event.type != "DELETED")):
                    return
            self._dirty[key] = (event.type, obj)
            if self.track_event_times:
                # first-event time wins: a burst coalescing onto one key
                # is still one detection, measured from its oldest event
                self._dirty_at.setdefault(key, time.monotonic())
                notify = self.on_event
            if rv_i is not None:
                # stream position for watch resume: advance-only, so a
                # stale replay cannot move the snapshot point backwards
                cur_rv = as_int(self._rvs.get(tuple(gvk)))
                if cur_rv is None or rv_i > cur_rv:
                    self._rvs[tuple(gvk)] = str(rv_i)
            elif rv:
                self._rvs[tuple(gvk)] = rv
        if notify is not None:
            # outside the lock: the stream loop's condvar takes its own
            notify()

    def note_gap(self, gvk: GVK) -> None:
        """External gap signal (watch stream lost beyond the client's
        own recovery): the next sweep re-list-diffs this GVK."""
        with self._lock:
            self._gaps.add(tuple(gvk))

    def _note_list_rv(self, gvk: GVK, objs: list) -> None:
        """Advance the per-GVK resume RV from a list's object RVs (max,
        numeric servers only — FakeKube and etcd both). Lists don't
        surface deletions, but that's safe: a deletion after the newest
        listed object has a HIGHER rv, so a watch resumed from this
        point still replays it. Watch events may already have moved the
        RV further; never move it backwards."""
        best = None
        for o in objs:
            try:
                v = int((o.get("metadata") or {}).get("resourceVersion"))
            except (TypeError, ValueError):
                continue
            if best is None or v > best:
                best = v
        if best is None:
            return
        with self._lock:
            try:
                cur = int(self._rvs.get(tuple(gvk), ""))
            except ValueError:
                cur = None
            if cur is None or best > cur:
                self._rvs[tuple(gvk)] = str(best)

    def _forget_gvk(self, gvk: GVK) -> None:
        """Remove a no-longer-audited GVK's objects from the inventory."""
        gvk = tuple(gvk)
        with self._lock:
            doomed = [k for k in self._state if k[0] == gvk]
            pend = [k for k in self._dirty if k[0] == gvk]
            for k in pend:
                del self._dirty[k]
                self._dirty_at.pop(k, None)
        for key in doomed:
            self._remove_key(key)

    def _remove_key(self, key: tuple) -> None:
        gvk, ns, name = key
        group, version, kind = gvk
        api_version = version if not group else f"{group}/{version}"
        stub = {"apiVersion": api_version, "kind": kind,
                "metadata": {"name": name}}
        if ns:
            stub["metadata"]["namespace"] = ns
        try:
            self.sink.remove_data(stub)
        except Exception as e:
            # keep the key tracked and requeue the delete: forgetting it
            # here would orphan the object in the shared inventory with
            # nothing left to retry (full resyncs only delete TRACKED
            # keys, and the data tree is never wiped by design)
            with self._lock:
                self._state.setdefault(key, (None, None))
                self._dirty.setdefault(key, ("DELETED", stub))
            log.error("inventory remove failed; delete retried next "
                      "sweep", details={"key": str(key), "error": str(e)})
            return
        with self._lock:
            self._state.pop(key, None)

    # -------------------------------------------------------------- deltas

    def resync(self, gvk: GVK) -> bool:
        """resourceVersion-diff against a fresh (paged, when the client
        pages) re-list: objects whose (uid, resourceVersion) differ from
        the tracked state become dirty, tracked objects missing from the
        list become deletes. The watch-gap / 410 Gone fallback, and the
        live-list re-validation a warm restart runs before readyz opens.
        Returns False when the list failed (the gap stays pending).

        Relist semantics: pending dirty events that PREdate the list are
        superseded by it (a stale MODIFIED for an object the list shows
        deleted must not resurrect it, and vice versa); events that land
        while the list is in flight overwrite their pre-list entry, are
        detected by identity, and win over the list."""
        gvk = tuple(gvk)
        with self._lock:
            pre = {k: v for k, v in self._dirty.items() if k[0] == gvk}
        try:
            objs = self.kube.list(gvk)
        except KubeError as e:
            log.error("resync list failed; keeping stale state this "
                      "sweep", details={"gvk": list(gvk), "error": str(e)})
            with self._lock:
                self._gaps.add(gvk)  # retry next sweep, don't lose it
            return False
        self._note_list_rv(gvk, objs)
        seen = set()
        with self._lock:
            for k, v in pre.items():
                if self._dirty.get(k) is v:  # unchanged during the list
                    del self._dirty[k]
                    # the receipt time goes with it: a later event for
                    # this key must stamp its OWN time, not revive this
                    # one via record_event's setdefault (a stale stamp
                    # collapses the debounce window and fakes a huge
                    # detection-latency tail sample)
                    self._dirty_at.pop(k, None)
            for o in objs:
                key = _obj_key(gvk, o)
                seen.add(key)
                if key in self._dirty:
                    continue  # raced in mid-list: newer than the list
                if self._state.get(key) != _obj_ver(o):
                    self._dirty[key] = ("MODIFIED", o)
            for key in self._state:
                if key[0] == gvk and key not in seen and \
                        key not in self._dirty:
                    gone = {"metadata": {"namespace": key[1] or None,
                                         "name": key[2]}}
                    self._dirty[key] = ("DELETED", gone)
        return True

    # --------------------------------------------------- warm restart

    def snapshot(self) -> dict:
        """Persistable tracker state: the tracked GVK set, per-GVK
        watch-resume resourceVersions, and the per-object (uid, rv)
        state map (the encoded-inventory index; the object BODIES live
        in the driver's data tree, snapshotted separately). The GVK set
        is derived from every source — live watches, poll fallbacks,
        resume RVs, AND the state map — so the SIGTERM drain snapshot
        (taken after stop() cancelled the watches) still records what
        to resume."""
        with self._lock:
            gvks = (set(self._cancels) | self._poll | set(self._rvs)
                    | {k[0] for k in self._state})
            return {
                "gvks": [list(g) for g in sorted(gvks)],
                "rvs": {"|".join(g): rv for g, rv in self._rvs.items()},
                "state": [[list(k[0]), k[1], k[2], v[0], v[1]]
                          for k, v in sorted(self._state.items())],
                # buffered-but-unapplied events MUST ride along: the
                # resume RVs above were already advanced by them, so a
                # resumed watch will never re-deliver them — dropping
                # them here would silently lose the delta
                "dirty": [[list(k[0]), k[1], k[2], etype, obj]
                          for k, (etype, obj)
                          in sorted(self._dirty.items())],
            }

    def restore(self, snap: dict) -> int:
        """Seed the tracker from a snapshot: state map and resume RVs
        installed, watches subscribed AT the persisted RVs — no initial
        re-list, no duplicate ADDED storm; a successfully resumed
        stream replays everything missed while down, deletes included.
        A GVK whose RV was rejected (compacted — the 410 case) or whose
        watch could not be established lands in the gap set via on_gap
        / the poll fallback, and the first sweep list-diffs exactly
        those against the live cluster. readyz stays closed until that
        first sweep validates (validated Event). Returns tracked-object
        count."""
        state: dict[tuple, tuple] = {}
        for entry in snap.get("state") or []:
            gvk, ns, name, uid, rv = entry
            state[(tuple(gvk), ns, name)] = (uid, rv)
        rvs: dict[GVK, str] = {}
        for key, rv in (snap.get("rvs") or {}).items():
            parts = tuple(key.split("|"))
            if len(parts) == 3 and rv:
                rvs[parts] = str(rv)
        gvks = [tuple(g) for g in snap.get("gvks") or []]
        # a synchronous-resume client (FakeKube) settles gap detection
        # before watch() returns, so a clean resume needs no list; an
        # ASYNC client (REST streamer: a 410 arrives a round-trip after
        # subscribe) could otherwise open readyz before the gap signal
        # lands — for those, EVERY restored GVK re-validates against a
        # live (uid, rv) list-diff on the first sweep (cheap metadata
        # compare; only changed objects re-encode)
        sync_resume = getattr(self.kube, "watch_resume_synchronous",
                              False)
        dirty: dict[tuple, tuple] = {}
        for entry in snap.get("dirty") or []:
            gvk, ns, name, etype, obj = entry
            dirty[(tuple(gvk), ns, name)] = (etype, obj)
        with self._lock:
            self._state = state
            self._rvs = rvs
            self._dirty = dirty  # un-applied events from the old process
            self._restoring = True
            for g in gvks:
                # no resume point means the watch starts blind: the
                # list-diff must reconcile missed deletes either way
                if not sync_resume or g not in rvs:
                    self._gaps.add(g)
        self.validated.clear()
        for g in gvks:
            self._watch_gvk(g, quiet=True)
        log.info("inventory tracker restored",
                 details={"objects": len(state), "gvks": len(gvks)})
        return len(state)

    def pending_count(self) -> int:
        with self._lock:
            return len(self._dirty)

    def oldest_pending_age(self) -> Optional[float]:
        """Age (seconds) of the oldest buffered-but-unapplied event, or
        None when nothing is pending. Only meaningful with
        track_event_times on (the streaming flush deadline)."""
        with self._lock:
            if not self._dirty_at:
                return None
            return time.monotonic() - min(self._dirty_at.values())

    def apply_pending(self) -> dict:
        """Drain the dirty map into the client's synced inventory.
        Returns {"dirty": applied-change count, "total": tracked size,
        "event_ts": receipt times of the drained events (streaming mode
        only — the detection-latency clock starts there)}."""
        with self._lock:
            polls = sorted(self._poll)
            gaps = sorted(self._gaps | self._poll)
            self._gaps.clear()
        for g in polls:
            # retry the stream each sweep (quietly) so a transient blip
            # at subscribe time does not pin the GVK to O(cluster)
            # re-lists forever; the resync below bridges the gap up to
            # the moment the new watch attached
            self._watch_gvk(g, quiet=True)
        resyncs_ok = True
        for g in gaps:
            resyncs_ok = self.resync(g) and resyncs_ok
        if self._restoring and resyncs_ok:
            # restored state is now re-validated against live lists:
            # open the readyz gate
            self._restoring = False
            self.validated.set()
        with self._lock:
            drained = self._dirty
            self._dirty = {}
            # event receipt times ride out with the drain; anything
            # without a live dirty entry (superseded by a relist, GVK
            # dropped) is pruned so the map cannot leak
            event_ts = [self._dirty_at.pop(k) for k in drained
                        if k in self._dirty_at]
            self._dirty_at = {k: t for k, t in self._dirty_at.items()
                              if k in self._dirty}
        applied = 0
        for key, (etype, obj) in sorted(drained.items()):
            if etype == "DELETED":
                if key in self._state:
                    self._remove_key(key)
                    applied += 1
                continue
            ver = _obj_ver(obj)
            if self._state.get(key) == ver:
                continue  # no-op event (or our own resync echo)
            try:
                self.sink.add_data(obj)
            except Exception as e:
                # requeue so the NEXT sweep retries — dropping the
                # drained entry would silently lose the delta until the
                # full-resync backstop
                with self._lock:
                    self._dirty.setdefault(key, (etype, obj))
                log.error("inventory add failed; object retried next "
                          "sweep", details={"key": str(key),
                                            "error": str(e)})
                continue
            with self._lock:
                self._state[key] = ver
            applied += 1
        with self._lock:
            total = len(self._state)
        return {"dirty": applied, "total": total, "event_ts": event_ts}

    def full_resync(self, gvks: list[GVK]) -> dict:
        """From-scratch re-encode: re-list every auditable GVK (in the
        given order — Namespaces first, so selector lookups resolve as
        the rebuild progresses), overwrite every tracked object in
        place, and delete whatever the tracker knew that no list
        returned. The synced inventory is NOT wiped: other writers
        co-own it (the config controller's syncOnly kinds feed the same
        tree), and admission served from this client must never observe
        a mid-rebuild empty inventory. Divergence in anything the
        tracker tracks is healed (the --audit-full-resync-every
        backstop); foreign inventory data is left alone by design.

        A tracked GVK absent from `gvks` is only dropped after TWO
        consecutive absences: discovery is served per API group and one
        transient group failure must not purge whole kinds from the
        shared inventory for the next resync period."""
        want = {tuple(g) for g in gvks}
        keep: list[GVK] = [tuple(g) for g in gvks]
        for g in self.gvks():
            if g in want:
                continue
            misses = self._gvk_missing.get(g, 0) + 1
            if misses < 2:
                self._gvk_missing[g] = misses
                keep.append(g)  # benefit of the doubt this round
            else:
                self._gvk_missing.pop(g, None)
        for g in want:
            self._gvk_missing.pop(g, None)
        gvks = keep
        self.set_gvks(gvks, resync_new=False)
        with self._lock:
            old_state = dict(self._state)
            self._gaps.clear()
        tracked = set(self.gvks())
        state: dict[tuple, tuple] = {}
        n = 0
        for gvk in gvks:
            gvk = tuple(gvk)
            if gvk not in tracked:
                continue
            with self._lock:
                pre = {k: v for k, v in self._dirty.items()
                       if k[0] == gvk}
            try:
                objs = self.kube.list(gvk)
            except KubeError:
                # no list, no delete detection: keep this GVK's old
                # state so its objects are not orphaned in the inventory
                # (its PENDING events also survive — clearing them
                # before a successful list would lose mutations the
                # watch stream has already moved past)
                state.update({k: v for k, v in old_state.items()
                              if k[0] == gvk})
                continue
            self._note_list_rv(gvk, objs)
            with self._lock:
                # the list supersedes this GVK's pre-list event backlog
                # (same relist semantics as resync); mid-list arrivals
                # overwrote their entry and survive for the next sweep
                for k, v in pre.items():
                    if self._dirty.get(k) is v:
                        del self._dirty[k]
                        self._dirty_at.pop(k, None)
            for o in objs:
                try:
                    self.sink.add_data(o)
                except Exception:
                    # transient write failure for a live object must
                    # NOT turn into a deletion below: keep it tracked
                    # at its old version so a later event/resync
                    # re-applies it
                    key = _obj_key(gvk, o)
                    if key in old_state:
                        state[key] = old_state[key]
                    continue
                state[_obj_key(gvk, o)] = _obj_ver(o)
                n += 1
        with self._lock:
            self._state = state
            # events raced during the rebuild stay dirty and re-apply
            # next sweep; rv no-op suppression keeps that cheap
            total = len(state)
        for key in old_state:
            if key not in state:
                self._remove_key(key)
                n += 1
        return {"dirty": n, "total": total}

    def stop(self) -> None:
        with self._lock:
            cancels = list(self._cancels.values())
            self._cancels.clear()
            self._poll.clear()
        for cancel in cancels:
            cancel()


class _PublishGate:
    """Mutual exclusion for status PUBLISH passes without a Lock held
    across kube-write retry backoffs.

    The three publish sites (streamed per-kind writes, flush writes,
    the post-sweep pass) must serialize against each other — the
    generation check-and-set they perform is only atomic under mutual
    exclusion — but each pass spends most of its time in
    `_write_kind_status`, whose kube PATCHes retry with backoff sleeps.
    PR 15's lockset tracer flagged exactly that: a `threading.Lock`
    held across `retry_call`'s `time.sleep`. Holding a *Lock object*
    there is a smell (an interrupt/timeout path blocking on the lock
    stalls behind another pass's network backoff with no way to see
    why), so the exclusion is a token instead: `__enter__` waits for
    the busy flag under an internal lock that is only ever held for
    the flag hand-off itself, then RELEASES it before the publish body
    runs. Same semantics at every `with` site, but no lock is held
    while a write sleeps — which is why the internal lock can be
    promoted to a gating locktrace site."""

    def __init__(self) -> None:
        # held only for busy-flag hand-offs — never across a write or
        # a sleep; gklint gates any held-across-blocking event on it
        self._lock = threading.Lock()  # locktrace: gate
        self._cv = threading.Condition(self._lock)
        self._busy = False

    def __enter__(self) -> "_PublishGate":
        with self._cv:
            while self._busy:
                self._cv.wait()
            self._busy = True
        return self

    def __exit__(self, *exc: Any) -> None:
        with self._cv:
            self._busy = False
            self._cv.notify()


class _KindStatusWriter:
    """Streaming constraint-status publisher for one interval sweep.

    The driver fires on_kind_results as each kind's sweep completes
    (delta-served, device-consumed, or interpreter); this writer drains
    those per-kind result batches on its own thread and issues the
    kind's delta'd status PATCHes IMMEDIATELY — so status API I/O
    overlaps the remaining kinds' device sweeps instead of forming one
    post-sweep assembly pass. Kinds it publishes are excluded from the
    post-sweep write pass; anything it failed on (API error, handler
    error) is left unstreamed so the post-sweep pass covers it."""

    # sentinel: live-pod set not resolved yet (computed on the writer
    # thread — a kube.list on the sweep thread before the tracker
    # drain would widen the event-drain race window)
    _UNRESOLVED = object()

    def __init__(self, manager: "AuditManager", force: bool, gen: int = 0):
        import queue

        self.manager = manager
        self.force = force
        # this sweep's evaluation generation: every status write this
        # writer issues is check-and-set against the manager's published
        # generation, so a slow streamed write can never clobber the
        # statuses of a NEWER flush/sweep that already published
        self.gen = gen
        self.live_pods: Any = self._UNRESOLVED
        self.q: Any = queue.Queue()
        self.written = 0
        self.skipped = 0
        self.pruned = 0
        self.wall_s = 0.0
        self.kinds: set = set()     # fully published kinds
        self._seen: set = set()     # kinds already streamed once
        self._thread: Optional[threading.Thread] = None
        self._finished = False

    def on_kind(self, target: str, kind: str, results: list) -> None:
        """Driver-thread callback: enqueue only (the sweep must never
        wait on status I/O). The writer thread spawns on first use so
        an armed-but-empty sweep costs nothing."""
        if self._thread is None:
            self._thread = threading.Thread(target=self._run, daemon=True,
                                            name="audit-status-stream")
            self._thread.start()
        self.q.put((target, kind, list(results)))

    def _run(self) -> None:
        while True:
            item = self.q.get()
            if item is None:
                return
            if self.live_pods is self._UNRESOLVED:
                self.live_pods = (self.manager._live_pod_ids()
                                  if self.manager.gc_stale_statuses
                                  else None)
            target, kind, results = item
            t0 = time.monotonic()
            try:
                if kind in self._seen:
                    # a second target re-audited this kind: the first
                    # streamed write covered only its own target's
                    # results — un-stream the kind so the post-sweep
                    # pass re-writes it from the cross-target union
                    self.kinds.discard(kind)
                    continue
                self._seen.add(kind)
                handler = self.manager.opa.targets.get(target)
                if handler is not None:
                    memo: dict = {}
                    for r in results:
                        handler.handle_violation(r, memo)
                by_con = self.manager._group_by_constraint(results)
                with self.manager._status_lock:
                    if self.manager._published_gen > self.gen:
                        # a newer sweep/flush already published: its
                        # evaluation drained the inventory AFTER ours,
                        # so writing this kind now would roll statuses
                        # backwards — skip the write (this sweep's
                        # post-pass is gen-checked too, so the kind is
                        # simply owned by the newer publish)
                        continue
                    w, s, p = self.manager._write_kind_status(
                        kind, by_con, force=self.force,
                        live_pods=self.live_pods)
                    if w is None:
                        # list failed / breaker: post-sweep covers
                        continue
                    self.manager._published_gen = max(
                        self.manager._published_gen, self.gen)
                self.written += w
                self.skipped += s
                self.pruned += p
                self.kinds.add(kind)
            except Exception as e:
                # post-sweep pass repairs whatever this missed
                log.error("streamed status write failed; post-sweep "
                          "pass will cover the kind",
                          details={"kind": kind, "error": str(e)})
            finally:
                dt = time.monotonic() - t0
                self.wall_s += dt
                profiling.timers().add("status_write", dt)

    def finish(self) -> set:
        """Drain, stop, and return the fully-published kinds.
        Idempotent: the sweep's finally calls it again on the error
        path so a raising evaluation cannot leak the writer thread."""
        if self._finished or self._thread is None:
            self._finished = True
            return set(self.kinds) if self._thread is not None else set()
        self._finished = True
        self.q.put(None)
        self._thread.join(timeout=300)
        if self._thread.is_alive():
            # a wedged write must not also wedge the sweep epilogue —
            # fall back to the post-sweep pass for everything
            log.error("streamed status writer stalled; post-sweep pass "
                      "re-writes every kind")
            return set()
        return set(self.kinds)


class AuditManager:
    def __init__(self, kube, opa: Client,
                 interval: float = DEFAULT_AUDIT_INTERVAL,
                 constraint_violations_limit: int =
                 DEFAULT_CONSTRAINT_VIOLATIONS_LIMIT,
                 audit_from_cache: bool = False,
                 incremental: bool = False,
                 full_resync_every: int = DEFAULT_FULL_RESYNC_EVERY,
                 write_breaker=None, leader_check=None,
                 gc_stale_statuses: bool = True,
                 stream_audit: bool = False,
                 stream_window_s: float = DEFAULT_STREAM_WINDOW_S,
                 stream_max_batch: int = DEFAULT_STREAM_MAX_BATCH,
                 stream_status_writes: bool = True,
                 shard_plane: "Optional[ShardedAuditPlane]" = None):
        self.kube = kube
        self.opa = opa
        # sharded inventory plane: when set, sweeps evaluate on the N
        # audit shard processes (each owning a consistent-hash slice)
        # and the leader composes the per-kind results; the local
        # driver still serves admission/preview from the full inventory
        self.shard_plane = shard_plane
        self.interval = interval
        self.limit = constraint_violations_limit
        self.audit_from_cache = audit_from_cache
        self.incremental = incremental
        # HA: with leader election enabled, only the lease holder runs
        # sweeps — two replicas must not race each other's
        # constraint-status PATCHes. None = single-replica, always on
        self.leader_check = leader_check
        # prune byPod status entries whose pod no longer exists (a
        # replaced pod's statuses must be garbage-collected, not
        # accumulate across restarts)
        self.gc_stale_statuses = gc_stale_statuses
        # N <= 0 disables the PERIODIC re-encode (k8s resync-period
        # convention); the first sweep always encodes from scratch
        self.full_resync_every = full_resync_every
        # shared kube-write circuit breaker (resilience.CircuitBreaker):
        # while open, status writes are deferred for the sweep instead
        # of hot-looping retries against a down API server — the skip-
        # unchanged delta logic re-issues them once writes heal
        self.write_breaker = write_breaker
        self.tracker: Optional[InventoryTracker] = None
        self._sweeps = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.last_results: list = []
        self.last_sweep_stats: dict = {}
        # liveness heartbeat: stamped every loop iteration; healthy()
        # flags a dead/stalled audit loop for the k8s liveness probe
        self.heartbeat = time.monotonic()
        # streaming audit: evaluate dirty rows as watch events arrive
        # (debounce window + max-batch) instead of waiting out the
        # interval; the interval sweep stays as the reconciliation
        # backstop. Requires incremental mode — the whole point is the
        # persistent encoded inventory + results delta cache. Sharded
        # sweeps keep the interval cadence (the shard round-trip IS the
        # flush), so streaming is a leader-local-only mode.
        self.stream_audit = stream_audit and incremental \
            and shard_plane is None
        self.stream_window_s = max(0.0, stream_window_s)
        self.stream_max_batch = max(1, stream_max_batch)
        # streaming status publishing: interval sweeps write each
        # kind's constraint statuses AS ITS SWEEP COMPLETES (driver
        # on_kind_results hook) instead of one post-sweep pass, so
        # write I/O overlaps the remaining kinds' device sweeps
        self.stream_status_writes = stream_status_writes
        self._stream_thread: Optional[threading.Thread] = None
        self._stream_cv = threading.Condition()
        self._stream_signal = False
        # one EVALUATION at a time: the stream flush and the interval
        # backstop share the delta pipeline. Status publishing happens
        # OUTSIDE this lock (under _status_lock below) so a kube-write
        # retry backoff can never sleep while the evaluation pipeline —
        # and the follower drain, and streaming flushes — are blocked
        # behind it. gklint promotes held-across-blocking findings on
        # this allocation site from advisory to gating.
        self._sweep_lock = threading.Lock()  # locktrace: gate
        # one PUBLISH at a time, ordered by evaluation generation:
        # _eval_gen is assigned under _sweep_lock (strictly increasing
        # in evaluation order), _published_gen advances check-and-set
        # under _status_lock — a publish whose generation is older than
        # what's already published is skipped wholesale, so a slow
        # in-flight write pass cannot clobber newer statuses. The gate
        # is a token, not a Lock: kube-write retry backoffs sleep with
        # NO lock held (see _PublishGate), closing PR 15's locktrace
        # advisory on this site.
        self._status_lock = _PublishGate()
        self._eval_gen = 0
        self._published_gen = 0
        # rolling flush observability (bench + tests + /debug): counts
        # by outcome and the most recent detection-latency samples
        self.stream_stats = {"flushes": 0, "errors": 0, "skipped": 0,
                             "events": 0}
        # streaming status-write delta baseline: (kind, name) -> the
        # serialized violation entries last PUBLISHED. A flush lists +
        # compares only the kinds whose fingerprints moved, so per-event
        # write cost is O(changed constraints) in API list calls, not
        # O(all constraints) per flush. None = unknown (next flush does
        # one full live compare); never advanced on deferred writes.
        self._stream_fp: Optional[dict] = None
        # observer hook: called after each ok flush with
        # (detection_latencies_s, write_stats) — bench/tests attach here
        self.on_flush: Optional[Callable[[list, dict], None]] = None

    # ------------------------------------------------------------- lifecycle

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, name="audit",
                                        daemon=True)
        self._thread.start()
        if self.stream_audit:
            self._stream_thread = threading.Thread(
                target=self._stream_loop, name="audit-stream",
                daemon=True)
            self._stream_thread.start()

    def stop(self) -> None:
        self._stop.set()
        metrics.unregister_saturation_probe("audit-stream-pending")
        with self._stream_cv:
            self._stream_cv.notify_all()
        if self._stream_thread is not None:
            # wait the stream loop out BEFORE zeroing: an in-flight
            # flush's finally clause re-exports pending_count(), and
            # with the probe already unregistered a zero written under
            # it would be overwritten into a phantom backlog forever
            self._stream_thread.join(timeout=10.0)
        if self.stream_audit and self.tracker is not None:
            # the gauge is SET-only: a stopped stream must not export
            # its last backlog forever
            metrics.report_stream_pending(0)
        if self.tracker is not None:
            self.tracker.stop()
        if self.shard_plane is not None:
            self.shard_plane.stop()

    def _loop(self) -> None:
        while not self._stop.is_set():
            self.heartbeat = time.monotonic()
            if self.leader_check is not None and not self.leader_check():
                # follower replica: keep the heartbeat fresh (the pod is
                # healthy, just not leading) and poll for promotion at a
                # sub-lease cadence so failover costs one lease duration.
                # The tracker still DRAINS: a warm-restored follower must
                # re-validate (readyz's state-restore gate), its dirty
                # map must not grow unboundedly while following, and a
                # promoted survivor should sweep over a current
                # inventory, not a stale one.
                if self.shard_plane is not None:
                    # follower keeps the shard slices current too: a
                    # promoted survivor's first sweep must find every
                    # shard's encoded rows fresh, not an interval stale
                    try:
                        with self._sweep_lock:
                            self.shard_plane.apply_pending()
                    except Exception as e:
                        log.error("follower shard-inventory sync failed",
                                  details=str(e))
                elif self.incremental and self.tracker is not None \
                        and not self.stream_audit:
                    # with streaming on, the stream loop owns follower
                    # drains (skipped flushes) — double-draining here
                    # would race it for the same dirty entries
                    try:
                        with self._sweep_lock:
                            self.tracker.apply_pending()
                    except Exception as e:
                        log.error("follower inventory sync failed",
                                  details=str(e))
                self._stop.wait(min(self.interval, 1.0))
                continue
            try:
                self.audit_once()
            except Exception as e:
                log.error("audit failed", details=str(e))
            self.heartbeat = time.monotonic()
            self._stop.wait(self.interval)

    def healthy(self, max_stall: Optional[float] = None) -> bool:
        """Liveness: the loop thread is alive and has heartbeaten within
        max_stall (default: generous multiple of the sweep interval).
        Sweeps stamp PROGRESS heartbeats (per listed GVK, per status
        write), so a long sweep that keeps moving never trips the
        watchdog; only one that stalls for max_stall inside a single
        step does."""
        if self._thread is None:
            return True  # not started yet
        if not self._thread.is_alive():
            return self._stop.is_set()  # stopped on purpose is fine
        if max_stall is None:
            max_stall = max(10 * self.interval, 300.0)
        return time.monotonic() - self.heartbeat <= max_stall

    # ----------------------------------------------------- streaming audit

    def _stream_loop(self) -> None:
        """Event-driven violation detection: wake on the tracker's
        watch-event notification, debounce for stream_window_s (a burst
        coalesces into one flush; stream_max_batch pending events flush
        early), evaluate ONLY the dirty rows through the delta pipeline,
        and publish changed constraint statuses — event-to-status in
        milliseconds instead of up to a full --audit-interval."""
        # the tracker is built lazily by the first interval sweep (or a
        # warm restore); arm its event hooks as soon as it exists
        while not self._stop.is_set():
            tracker = self.tracker
            if tracker is not None:
                break
            self._stop.wait(0.05)
        if self._stop.is_set():
            return

        def on_event():
            with self._stream_cv:
                self._stream_signal = True
                self._stream_cv.notify()

        tracker.track_event_times = True
        tracker.on_event = on_event
        # the streaming backlog was only visible in logs: export the
        # dirty-key depth as a gauge, refreshed around every flush AND
        # on each scrape, so backlog growth (detection latency about to
        # follow) is scrapeable before it becomes a latency incident
        metrics.register_saturation_probe(
            "audit-stream-pending",
            lambda: metrics.report_stream_pending(
                tracker.pending_count()))
        log.info("streaming audit armed",
                 details={"window_ms": round(self.stream_window_s * 1e3),
                          "max_batch": self.stream_max_batch})
        while not self._stop.is_set():
            with self._stream_cv:
                while not self._stream_signal and not self._stop.is_set():
                    # periodic wake: events buffered while the flush ran
                    # (their notify landed before the wait) must not sit
                    # until the next fresh event
                    self._stream_cv.wait(0.25)
                    if self.tracker is not None and \
                            self.tracker.pending_count():
                        break
                self._stream_signal = False
            if self._stop.is_set():
                return
            # debounce: let the burst land, but flush early on a full
            # batch and never hold an event past ~2 windows — if the
            # oldest buffered event already aged (the wake-up lagged the
            # event, e.g. a flush was in flight when it landed), the
            # wait shrinks so oldest-age + wait <= 2 windows
            age = tracker.oldest_pending_age() or 0.0
            deadline = time.monotonic() + max(
                0.0, min(self.stream_window_s,
                         2 * self.stream_window_s - age))
            while time.monotonic() < deadline and not self._stop.is_set():
                if self.tracker.pending_count() >= self.stream_max_batch:
                    break
                self._stop.wait(min(0.005, self.stream_window_s or 0.005))
            if self._stop.is_set():
                return
            try:
                self._stream_flush()
            except Exception as e:
                # the interval backstop repairs whatever this flush
                # missed; the error must still be visible
                self.stream_stats["errors"] += 1
                metrics.report_stream_flush("error")
                log.error("stream flush failed; interval backstop will "
                          "reconcile", details=str(e))
            finally:
                # per-flush gauge refresh: pending drops to ~0 after a
                # healthy flush; a stuck writer leaves it growing
                metrics.report_stream_pending(tracker.pending_count())

    def _stream_flush(self) -> None:
        tracker = self.tracker
        if tracker is None or tracker.pending_count() == 0:
            return
        if self.leader_check is not None and not self.leader_check():
            # follower: keep the inventory current (a promoted survivor
            # must sweep over fresh rows) but never write statuses
            with self._sweep_lock:
                tracker.apply_pending()
            self.stream_stats["skipped"] += 1
            metrics.report_stream_flush("skipped")
            return
        # EVALUATION under _sweep_lock only: the status publish below
        # happens after the lock drops (under _status_lock), so the
        # kube-retry backoff of a flaky status write can never sleep
        # while the evaluation pipeline is blocked behind this flush
        with self._sweep_lock:
            if self._sweeps == 0:
                # cold bootstrap pending: the first interval sweep's
                # full re-encode will cover these events
                return
            t0 = time.monotonic()
            stats = tracker.apply_pending()
            event_ts = stats.pop("event_ts", None) or []
            if stats["dirty"] == 0 and not event_ts:
                return  # pure no-op events (rv echoes)
            self._eval_gen += 1
            gen = self._eval_gen
            drv = getattr(self.opa, "driver", None)
            cap_armed = hasattr(drv, "audit_violations_cap")
            if cap_armed:
                drv.audit_violations_cap = self.limit
            tr = gtrace.TRACER.start(gtrace.AUDIT)
            try:
                with tr.span("evaluate"):
                    try:
                        results = self.opa.audit().results()
                    finally:
                        if cap_armed:
                            drv.audit_violations_cap = None
            except BaseException as e:
                tr.set_status("error")
                tr.set_attr("error", str(e))
                tr.finish()
                raise
        superseded = False
        try:
            by_constraint = self._group_by_constraint(results)
            cur_fp = {k: self._status_entries(v)
                      for k, v in by_constraint.items()}
            with self._status_lock:
                if self._published_gen > gen:
                    # a newer sweep already published: skipping this
                    # flush wholesale is safe (its evaluation drained
                    # the tracker after ours) — writing would clobber
                    # the newer statuses with older ones
                    superseded = True
                    writes = {"status_writes": 0, "status_skipped": 0,
                              "status_deferred": False,
                              "status_superseded": True}
                else:
                    # delta against the last published fingerprints:
                    # only kinds whose violation sets moved get
                    # listed/compared this flush (unknown baseline =
                    # one full live pass)
                    prev_fp = self._stream_fp
                    kinds = None
                    if prev_fp is not None:
                        kinds = {key[0]
                                 for key in set(prev_fp) | set(cur_fp)
                                 if prev_fp.get(key) != cur_fp.get(key)}
                    with tr.span("status_writes"):
                        if kinds is not None and not kinds:
                            # nothing moved: the no-op verdict needs no
                            # API traffic at all
                            writes = {"status_writes": 0,
                                      "status_skipped": len(cur_fp),
                                      "status_deferred": False}
                        else:
                            writes = self._write_audit_results(
                                by_constraint, kinds=kinds)
                    if not writes.get("status_deferred"):
                        self._stream_fp = cur_fp
                        self._published_gen = max(self._published_gen,
                                                  gen)
            tr.set_status("stream")
            tr.set_attr("dirty", stats["dirty"])
        except BaseException as e:
            tr.set_status("error")
            tr.set_attr("error", str(e))
            raise
        finally:
            tr.finish()
        self.stream_stats["flushes"] += 1
        self.stream_stats["events"] += len(event_ts)
        if not superseded:
            self.last_results = results
        metrics.report_audit_sweep("stream")
        if superseded:
            # the overtaking publish covered these events' state; their
            # detection latency is attributed there, not double-counted
            self.stream_stats["skipped"] += 1
            metrics.report_stream_flush("skipped")
            lat = []
        elif writes.get("status_deferred"):
            # breaker open: statuses did NOT publish — the flush is
            # an error and these events record NO detection latency
            # (a sub-second sample here would claim a detection that
            # never reached status; the pending deltas re-issue on
            # the first healthy sweep, counted as backstop drift).
            # The fingerprint baseline does not advance either, so
            # the next flush re-lists and re-issues these kinds.
            self.stream_stats["errors"] += 1
            metrics.report_stream_flush("error")
            lat = []
        else:
            # the detection clock stops when the status writes that
            # publish the verdicts have completed (or were
            # confirmed no-ops — an unchanged violation set IS the
            # verdict)
            now = time.monotonic()
            lat = [max(0.0, now - ts) for ts in event_ts]
            for s in lat:
                metrics.report_violation_detection(s)
            metrics.report_stream_flush("ok")
        dt = time.monotonic() - t0
        if lat:
            log.info("stream flush",
                     details={"dirty": stats["dirty"],
                              "events": len(lat),
                              "violations": len(results),
                              "detect_p_max_ms":
                                  round(max(lat) * 1e3, 1),
                              "flush_s": round(dt, 4), **writes})
        cb = self.on_flush
        if cb is not None:
            try:
                cb(lat, writes)
            except Exception:
                pass  # observer only; never fail the flush

    # --------------------------------------------------------- warm restart

    def restore_state(self, snap: dict) -> int:
        """Seed the incremental tracker from a state snapshot (see
        statestore.py; the driver's data tree is restored separately,
        before this). The first sweep then runs INCREMENTAL — a live-
        list (uid, rv) re-validation plus whatever delta accumulated
        while down — instead of the forced from-scratch re-encode a
        cold boot pays."""
        if self.shard_plane is not None:
            n = self.shard_plane.restore_state(snap)
            if n:
                # sweep 0 forces a full re-encode (cold bootstrap); a
                # restored plane starts at sweep 1 so the backstop
                # cadence is kept but the boot sweep stays incremental
                self._sweeps = 1
            return n
        if not self.incremental:
            return 0
        self.tracker = InventoryTracker(self.kube, self.opa)
        n = self.tracker.restore(snap)
        # sweep 0 forces a full re-encode (cold bootstrap); a restored
        # tracker starts at sweep 1 so the backstop cadence is kept but
        # the boot sweep stays incremental
        self._sweeps = 1
        return n

    def restore_ready(self) -> bool:
        """readyz gate: restored state must be re-validated against a
        live list before the pod reports Ready (trivially true when
        nothing was restored)."""
        if self.shard_plane is not None:
            return self.shard_plane.restore_ready()
        return self.tracker is None or self.tracker.validated.is_set()

    def snapshot_state(self) -> Optional[dict]:
        """Tracker section of the state snapshot; None before the first
        incremental sweep built a tracker."""
        if self.shard_plane is not None:
            return self.shard_plane.snapshot_state()
        if self.tracker is None:
            return None
        return self.tracker.snapshot()

    # ----------------------------------------------------------------- audit

    def audit_once(self) -> list:
        t0 = time.monotonic()
        self.heartbeat = time.monotonic()
        # every sweep is traced (a handful of span objects per minute):
        # the audit plane's flight-recorder entries and per-phase
        # histograms exist regardless of the admission sample rate.
        # The driver's internals accumulate into the process-global
        # PhaseTimers; the snapshot diff below turns this sweep's
        # encode / device_sweep / materialize / interp_eval /
        # delta_serve time into trace phases.
        tr = gtrace.TRACER.start(gtrace.AUDIT, force=True)
        try:
            # evaluation is serialized with the streaming flush (both
            # drive the same delta pipeline); publishing happens AFTER
            # the lock drops, under _status_lock, so status-write retry
            # backoff never sleeps while evaluation is blocked
            with self._sweep_lock:
                pub = self._audit_once_traced(tr, t0)
            return self._publish_sweep(tr, t0, pub)
        except BaseException as e:
            # a failing sweep must still land in the flight recorder —
            # the sweeps that error (API outage, eval blowup) are
            # exactly the ones worth diagnosing after the fact
            tr.set_status("error")
            tr.set_attr("error", str(e))
            raise
        finally:
            tr.finish()

    def _audit_once_traced(self, tr, t0: float) -> dict:
        """Evaluation half of one interval sweep, under _sweep_lock.
        Returns the publish payload _publish_sweep consumes once the
        lock has dropped."""
        timers = profiling.timers()
        phases0 = timers.snapshot()
        sweep_stats: dict = {}
        # evaluation generation: assigned under _sweep_lock, strictly
        # increasing in evaluation order — the publish step's clobber
        # guard (and the streamed writer's) key off it
        self._eval_gen += 1
        gen = self._eval_gen
        # streaming status publishing: arm the driver's per-kind
        # completion hook so each kind's constraint statuses PATCH
        # while later kinds are still sweeping on the device. The
        # force decision must be made BEFORE the sweep (it matches the
        # full-resync cadence _audit_incremental computes from the
        # same counter).
        driver = getattr(self.opa, "driver", None)
        writer: Optional[_KindStatusWriter] = None
        delta_mode = self.incremental or self.shard_plane is not None
        would_force = (not delta_mode or self._sweeps == 0
                       or (self.full_resync_every > 0
                           and self._sweeps % self.full_resync_every
                           == 0))
        if (self.stream_status_writes
                and (delta_mode or self.audit_from_cache)
                and (self.shard_plane is not None
                     or hasattr(driver, "on_kind_results"))
                and (self.leader_check is None or self.leader_check())
                and not (self.write_breaker is not None
                         and self.write_breaker.is_open)):
            writer = _KindStatusWriter(self, would_force, gen=gen)
            if self.shard_plane is None:
                driver.on_kind_results = writer.on_kind
        # per-constraint violations cap, armed for THIS sweep only:
        # direct client.audit() callers and previews that share the
        # driver stay uncapped (materialize counts every pair either
        # way; past the cap only the message assembly is skipped)
        cap_armed = hasattr(driver, "audit_violations_cap")
        if cap_armed:
            driver.audit_violations_cap = self.limit
        t_ev0 = time.monotonic()
        try:
            return self._audit_evaluate(tr, t_ev0, timers, phases0,
                                        sweep_stats, writer, gen)
        finally:
            if cap_armed:
                driver.audit_violations_cap = None
            if writer is not None:
                if self.shard_plane is None:
                    driver.on_kind_results = None
                # error-path backstop: a raising evaluation must not
                # leak the writer thread (finish is idempotent)
                writer.finish()

    def _audit_evaluate(self, tr, t_ev0, timers, phases0,
                        sweep_stats, writer, gen) -> dict:
        if self.shard_plane is not None:
            results, sweep_stats = self._audit_sharded(tr, writer)
            ev_wall = sweep_stats.pop("_eval_wall_s", 0.0)
        elif self.incremental:
            results, sweep_stats = self._audit_incremental(tr)
            ev_wall = sweep_stats.pop("_eval_wall_s", 0.0)
        elif self.audit_from_cache:
            # one vectorized sweep over the synced inventory
            results = self.opa.audit().results()
            ev_wall = time.monotonic() - t_ev0
            metrics.report_audit_sweep("full")
        else:
            results = self._audit_resources()
            ev_wall = time.monotonic() - t_ev0
            metrics.report_audit_sweep("full")
        # streamed per-kind status writes ride the sweep itself: wait
        # them out first so their wall time and published-kind set are
        # final before the post-sweep pass
        streamed_kinds: set = set()
        stream_write_s = 0.0
        if writer is not None:
            streamed_kinds = writer.finish()
            stream_write_s = writer.wall_s
        # phase attribution, double-count-free: when the driver
        # instrumented its internals (encode / device_sweep /
        # materialize / interp_eval / delta_serve — all inside the
        # evaluation wall), the trace records THOSE plus the
        # uncovered remainder as evaluate_other, so stages sum to the
        # sweep. An uninstrumented driver records one aggregate
        # evaluate span instead. status_write accrues on the streaming
        # writer's OWN thread (overlapping the sweep) — it is reported
        # as its own phase, never subtracted from the eval wall.
        phases = profiling.PhaseTimers.diff(phases0, timers.snapshot())
        phases.pop("status_write", None)
        if phases:
            for name, secs in sorted(phases.items()):
                # gklint: allow(stage) reason=names originate from PhaseTimers call sites, each a checked literal
                tr.add_phase(name, secs)
            residual = ev_wall - sum(phases.values())
            if residual > 1e-6:
                tr.add_phase("evaluate_other", residual)
        elif ev_wall > 0:
            tr.add_phase("evaluate", ev_wall)
        if stream_write_s > 0:
            tr.add_phase("status_write_stream", stream_write_s)
        return {"gen": gen, "results": results,
                "sweep_stats": sweep_stats, "writer": writer,
                "streamed_kinds": streamed_kinds,
                "stream_write_s": stream_write_s}

    def _publish_sweep(self, tr, t0, pub) -> list:
        """Publishing half of one interval sweep: constraint-status
        writes under _status_lock, generation check-and-set so a stale
        publish (a newer flush/sweep already wrote) is skipped wholesale
        instead of rolling statuses backwards. Safe to skip entirely:
        generation order implies inventory-recency order — the newer
        evaluation drained the tracker AFTER this one, so its published
        statuses already cover everything this one saw."""
        gen = pub["gen"]
        results = pub["results"]
        sweep_stats = pub["sweep_stats"]
        writer = pub["writer"]
        streamed_kinds = pub["streamed_kinds"]
        stream_write_s = pub["stream_write_s"]
        by_constraint = self._group_by_constraint(results)
        # delta'd status writes are a delta-pipeline behavior: the
        # discovery and from-cache modes keep upstream semantics (every
        # sweep rewrites every status, refreshing auditTimestamp). In
        # incremental/sharded mode, full-resync sweeps force every
        # write so the timestamp still refreshes on that cadence
        force_writes = (not (self.incremental
                             or self.shard_plane is not None)
                        or sweep_stats.get("sweep") == "full_resync")
        # reuse the streamed writer's resolved live-pod set: the
        # post-sweep pass must not pay a second cluster-wide pod list
        lp = self._LIVE_PODS_UNSET
        if writer is not None and \
                writer.live_pods is not _KindStatusWriter._UNRESOLVED:
            lp = writer.live_pods
        t_w0 = time.monotonic()
        superseded = False
        with self._status_lock:
            if self._published_gen > gen:
                superseded = True
                writes = {"status_writes": 0, "status_skipped": 0,
                          "status_deferred": False,
                          "status_superseded": True}
            else:
                with tr.span("status_writes"):
                    writes = self._write_audit_results(
                        by_constraint, force=force_writes,
                        exclude_kinds=streamed_kinds or None,
                        live_pods=lp)
                if not writes.get("status_deferred"):
                    self._published_gen = max(self._published_gen, gen)
                # a full interval sweep (re)establishes the streaming
                # delta baseline — unless the breaker deferred the
                # writes, in which case what is published is unknown
                if self.stream_audit:
                    self._stream_fp = \
                        None if writes.get("status_deferred") \
                        else {k: self._status_entries(v)
                              for k, v in by_constraint.items()}
        if writer is not None:
            writes["status_writes"] = (writes.get("status_writes", 0)
                                       + writer.written)
            writes["status_skipped"] = (writes.get("status_skipped", 0)
                                        + writer.skipped)
            if writer.pruned:
                writes["status_gc"] = (writes.get("status_gc", 0)
                                       + writer.pruned)
            if streamed_kinds:
                writes["status_streamed_kinds"] = len(streamed_kinds)
        sweep_stats["status_write_s"] = round(
            stream_write_s + (time.monotonic() - t_w0), 4)
        streaming = (self.stream_audit and self._stream_thread is not None
                     and sweep_stats.get("sweep") == "incremental")
        if streaming:
            # backstop role: with the streaming path keeping statuses
            # current, any non-forced write this interval sweep had to
            # issue is drift the event pipeline missed (or an external
            # clobber it repaired) — 0 in steady state
            drift = writes.get("status_writes", 0)
            metrics.report_backstop_drift(drift)
            if drift:
                writes["backstop_drift"] = drift
                log.warning("interval backstop repaired constraint-"
                            "status drift", details={"writes": drift})
        event_ts = sweep_stats.pop("_event_ts", None) or []
        if event_ts and self.stream_audit:
            # events the BACKSTOP drained (the stream loop missed or
            # raced them): their detection latency is real — it lands
            # in the same histogram as the streaming path's, honestly
            # fattening the tail it is supposed to beat
            now = time.monotonic()
            for ts in event_ts:
                metrics.report_violation_detection(max(0.0, now - ts))
        dt = time.monotonic() - t0
        metrics.report_audit_duration(dt)
        metrics.report_audit_last_run()
        by_action: dict[str, int] = {}
        for r in results:
            by_action[r.enforcement_action] = \
                by_action.get(r.enforcement_action, 0) + 1
        for action, count in by_action.items():
            metrics.report_violations(action, count)
        if not superseded:
            # a superseded publish must not roll the observable sweep
            # state back behind the newer publish that overtook it
            self.last_results = results
            self.last_sweep_stats = sweep_stats
        details = {"violations": len(results), "duration_s": round(dt, 3),
                   **sweep_stats, **writes}
        driver = getattr(self.opa, "driver", None)
        if hasattr(driver, "quarantine_status"):
            q = driver.quarantine_status()
            if q:
                details["quarantined"] = q
        if hasattr(driver, "warm_status"):
            st = driver.warm_status()
            metrics.report_device_programs(st["warm"], st["compiling"])
            details["device_programs"] = st
            path = getattr(
                driver,
                "last_audit_path"
                if (self.audit_from_cache or self.incremental)
                else "last_review_batch_path", None)
            if path:
                details["audit_path"] = path
        tr.set_status(sweep_stats.get("sweep") or "full")
        tr.set_attr("violations", len(results))
        for k in ("dirty", "inventory"):
            if k in sweep_stats:
                tr.set_attr(k, sweep_stats[k])
        if "audit_path" in details:
            tr.set_attr("audit_path", details["audit_path"])
        # finish() runs in audit_once's finally, error or not
        log.info("audit complete", details=details)
        return results

    def _audit_sharded(self, tr, writer) -> tuple[list, dict]:
        """Sharded sweep: drain every shard slice's tracker (deltas
        route to the owning engine process, plus the join-broadcast
        columns everywhere), dispatch one capped sweep per shard over
        the backplane, and compose the per-kind results into ONE audit
        round — bit-equal to the unsharded sweep (see
        compose_shard_results). Kinds feed the streamed status writer
        as they compose, so write I/O overlaps the remaining merge."""
        plane = self.shard_plane
        full = self._sweeps == 0 or (
            self.full_resync_every > 0
            and self._sweeps % self.full_resync_every == 0)
        self._sweeps += 1
        t0 = time.monotonic()
        with tr.span("list_delta_apply"):
            if full:
                stats = plane.full_resync(_auditable_gvks(self.kube))
                metrics.report_audit_sweep("full_resync")
            else:
                stats = plane.apply_pending()
                metrics.report_audit_sweep("incremental")
        sync_s = time.monotonic() - t0
        t_ev0 = time.monotonic()
        with tr.span("shard_sweeps"):
            results, shard_stats = plane.sweep(
                self.limit, writer=writer,
                heartbeat=lambda: setattr(self, "heartbeat",
                                          time.monotonic()))
        ev_wall = time.monotonic() - t_ev0
        metrics.report_audit_dirty(stats["dirty"], stats["total"], 0)
        return results, {
            "sweep": "full_resync" if full else "incremental",
            "dirty": stats["dirty"], "inventory": stats["total"],
            "sync_s": round(sync_s, 3), "shards": plane.shard_count,
            **shard_stats,
            "_eval_wall_s": ev_wall,
            "_event_ts": stats.get("event_ts") or [],
        }

    def _audit_incremental(self, tr=gtrace.NOOP) -> tuple[list, dict]:
        """Delta sweep: apply the tracker's pending adds/updates/deletes
        to the persistent encoded inventory (the driver patches only the
        dirty rows), then run the vectorized cached audit. Every
        full_resync_every-th sweep re-encodes everything from scratch."""
        driver = getattr(self.opa, "driver", None)
        strtab = getattr(driver, "strtab", None)
        snap = strtab.snapshot() if strtab is not None else None
        if self.tracker is None:
            self.tracker = InventoryTracker(self.kube, self.opa)
        full = self._sweeps == 0 or (
            self.full_resync_every > 0
            and self._sweeps % self.full_resync_every == 0)
        self._sweeps += 1
        t0 = time.monotonic()
        with tr.span("list_delta_apply"):
            if full:
                # drop BEFORE re-adding: with warm caches every re-add
                # would run the per-object patch machinery whose work
                # the drop then discards; cold caches make each write
                # an early return
                if hasattr(driver, "drop_inventory_caches"):
                    driver.drop_inventory_caches()
                stats = self.tracker.full_resync(
                    _auditable_gvks(self.kube))
                metrics.report_audit_sweep("full_resync")
            else:
                stats = self.tracker.apply_pending()
                metrics.report_audit_sweep("incremental")
        sync_s = time.monotonic() - t0
        t_ev0 = time.monotonic()
        results = self.opa.audit().results()
        ev_wall = time.monotonic() - t_ev0
        grown = strtab.grown_since(snap) if strtab is not None else 0
        metrics.report_audit_dirty(stats["dirty"], stats["total"], grown)
        return results, {
            "sweep": "full_resync" if full else "incremental",
            "dirty": stats["dirty"], "inventory": stats["total"],
            "sync_s": round(sync_s, 3), "vocab_grown": grown,
            # evaluation wall clock for the caller's phase attribution
            # (popped before the stats reach the log line), and the
            # receipt times of any events this sweep drained (streaming
            # mode: the backstop's detections are histogrammed too)
            "_eval_wall_s": ev_wall,
            "_event_ts": stats.get("event_ts") or [],
        }

    def _audit_resources(self) -> list:
        """Discovery-driven sweep: list every listable GVK and feed the
        objects through the driver's BATCHED inventory evaluation (the
        reference reviews one object at a time here)."""
        from ..target.handler import AugmentedUnstructured

        # stage all live objects into a scratch audit client: reuse the
        # driver's vectorized audit over inventory (external data paths)
        results = []
        staged: list[dict] = []
        # listed Namespaces, sideloaded onto each namespaced review so
        # namespaceSelector constraints resolve from the live cluster
        # state — NOT just synced inventory (reference wraps every object
        # as AugmentedUnstructured{obj, ns}, manager.go:250-271);
        # _auditable_gvks (shared with the incremental tracker) lists
        # Namespaces first so the map is complete before any namespaced
        # object is staged
        ns_by_name: dict[str, dict] = {}
        saw_ns_kind = False
        for gvk in _auditable_gvks(self.kube):
            # progress heartbeat: a legitimately long discovery sweep
            # keeps beating per GVK, so the liveness watchdog only
            # trips on a sweep that stopped making progress
            self.heartbeat = time.monotonic()
            try:
                objs = self.kube.list(gvk)
            except KubeError:
                continue
            if gvk == ("", "v1", "Namespace"):
                saw_ns_kind = True
                for o in objs:
                    name = (o.get("metadata") or {}).get("name")
                    if name:
                        ns_by_name[name] = o
            staged.extend(objs)
        if not saw_ns_kind:
            # discovery may exclude Namespaces (RBAC-filtered lists);
            # fetch them explicitly — without this map every
            # namespaceSelector constraint autorejects. A FAILED listing
            # aborts the sweep: with no map, augmented() would skip every
            # namespaced object and the status write would then wipe all
            # previously-reported violations cluster-wide
            for o in self.kube.list(("", "v1", "Namespace")):
                name = (o.get("metadata") or {}).get("name")
                if name:
                    ns_by_name[name] = o

        ns_missing: set[str] = set()

        def resolve_ns(name: str) -> Optional[dict]:
            """Map hit, else a direct GET (a namespace created after the
            one-time snapshot — the reference's per-object nsCache.Get
            does the same on a cache miss). Failures are negative-cached
            for the sweep: N orphaned objects in a deleted namespace
            must cost one GET, not N."""
            ns_obj = ns_by_name.get(name)
            if ns_obj is None:
                if name in ns_missing:
                    return None
                try:
                    ns_obj = self.kube.get(("", "v1", "Namespace"), name)
                except KubeError:
                    ns_missing.add(name)
                    log.error("unable to look up object namespace; "
                              "skipping its objects this sweep",
                              details={"namespace": name})
                    return None
                ns_by_name[name] = ns_obj
            return ns_obj

        def augmented(o: dict) -> Optional[AugmentedUnstructured]:
            """Reference semantics (manager.go:250-271 + target.go:129-135):
            EVERY object gets a namespace sideload — the listed Namespace
            for namespaced objects (suppressing autoreject and giving the
            selector real labels), an EMPTY namespace for cluster-scoped
            ones (the reference's `&corev1.Namespace{}`, so selectors see
            no labels rather than autorejecting). An object whose
            namespace cannot be resolved is skipped, as the reference
            skips on a failed namespace fetch."""
            ns = (o.get("metadata") or {}).get("namespace")
            if not ns:
                return AugmentedUnstructured(o, {"metadata": {}})
            ns_obj = resolve_ns(ns)
            if ns_obj is None:
                return None
            return AugmentedUnstructured(o, ns_obj)

        # evaluate via the driver's batch review API when available,
        # falling back to per-object review
        driver = self.opa.driver
        target = "admission.k8s.gatekeeper.sh"
        if hasattr(driver, "review_batch"):
            handler = self.opa.targets[target]
            reviews = []
            for o in staged:
                aug = augmented(o)
                if aug is None:
                    continue
                handled, review = handler.handle_review(aug)
                if handled:
                    reviews.append(review)
            batches = driver.review_batch(target, reviews)
            for per_review in batches:
                for r in per_review:
                    handler.handle_violation(r)
                    results.append(r)
        else:
            for o in staged:
                aug = augmented(o)
                if aug is None:
                    continue
                results.extend(self.opa.review(aug).results())
        return results

    # ------------------------------------------------------------ aggregation

    def _group_by_constraint(self, results) -> dict[tuple, list]:
        grouped: dict[tuple, list] = {}
        for r in results:
            c = r.constraint or {}
            key = (c.get("kind") or "", (c.get("metadata") or {}).get("name")
                   or "")
            grouped.setdefault(key, []).append(r)
        return grouped

    def _write_kind_status(self, kind: str, by_constraint: dict,
                           force: bool, live_pods) -> tuple:
        """List + delta-compare + write ONE kind's constraint statuses.
        Returns (written, skipped, pruned), or (None, 0, 0) when the
        kind could not be covered (list failure / breaker open) so the
        caller leaves it for a later pass."""
        if self.write_breaker is not None and self.write_breaker.is_open:
            return (None, 0, 0)
        gvk = (CONSTRAINT_GROUP, "v1beta1", kind)
        try:
            constraints = self.kube.list(gvk)
        except KubeError:
            return (None, 0, 0)
        written = skipped = pruned = 0
        for obj in constraints:
            self.heartbeat = time.monotonic()  # progress per write
            name = (obj.get("metadata") or {}).get("name") or ""
            violations = by_constraint.get((kind, name), [])
            entries = self._status_entries(violations)
            gced = live_pods is not None and \
                prune_stale_by_pod(obj, live_pods)
            pruned += 1 if gced else 0
            cur = obj.get("status") or {}
            if not force and not gced and \
                    cur.get("totalViolations") == len(violations) \
                    and (cur.get("violations") or []) == entries:
                skipped += 1
                continue
            if self._update_constraint_status(obj, entries,
                                              len(violations)):
                written += 1
        return (written, skipped, pruned)

    _LIVE_PODS_UNSET = object()

    def _write_audit_results(self, by_constraint: dict[tuple, list],
                             force: bool = False,
                             kinds: Optional[set] = None,
                             exclude_kinds: Optional[set] = None,
                             live_pods=_LIVE_PODS_UNSET) -> dict:
        """status.byPod[audit] style update with cap + truncation + retry
        (manager.go:428-574). Constraints with no violations this run get
        their violation list cleared — but a constraint whose CURRENT
        status (fresh from the list) already carries exactly the
        violation set this sweep would publish is skipped, so a
        steady-state sweep issues O(changed constraints) PATCHes, not
        O(constraints). Comparing against the live status (not a local
        fingerprint) means an externally clobbered status self-heals on
        the next sweep. `force` writes everything (full-resync sweeps
        use it to refresh auditTimestamp periodically)."""
        if self.write_breaker is not None and self.write_breaker.is_open:
            # API-server writes are circuit-broken: defer ALL status
            # writes this sweep (no hot-loop of doomed PATCHes). The
            # violation deltas stay pending — the skip-unchanged
            # comparison below re-issues them on the first healthy sweep
            log.warning("kube-write breaker open; deferring constraint "
                        "status writes this sweep")
            return {"status_writes": 0, "status_skipped": 0,
                    "status_deferred": True}
        target_kinds = set()
        for kind in self.opa.template_kinds():
            target_kinds.add(kind)
        if kinds is not None:
            # streaming flushes restrict the list+compare to the kinds
            # whose violation fingerprints moved (the backstop sweep
            # passes None and still covers everything, so external
            # clobbers of untouched kinds heal there, as drift)
            target_kinds &= kinds
        if exclude_kinds:
            # already published mid-sweep by the streaming status
            # writer: re-listing them here would double the API load
            target_kinds -= exclude_kinds
        if live_pods is self._LIVE_PODS_UNSET:
            live_pods = (self._live_pod_ids()
                         if self.gc_stale_statuses else None)
        written = skipped = pruned = 0
        for kind in sorted(target_kinds):
            w, s, p = self._write_kind_status(kind, by_constraint,
                                              force=force,
                                              live_pods=live_pods)
            if w is None:
                continue
            written += w
            skipped += s
            pruned += p
        pruned += self._gc_template_statuses(live_pods)
        metrics.report_audit_status_writes(written, skipped)
        out = {"status_writes": written, "status_skipped": skipped}
        if pruned:
            out["status_gc"] = pruned
        return out

    def _live_pod_ids(self) -> Optional[set]:
        """Pod names of the live gatekeeper replicas in our namespace,
        for byPod status GC. None (= skip GC) when the listing fails or
        shows no labeled pods at all — partial visibility must never
        garbage-collect a living replica's status."""
        from .util import pod_name, pod_namespace

        try:
            pods = self.kube.list(("", "v1", "Pod"), pod_namespace())
        except KubeError:
            return None
        live = set()
        for p in pods:
            meta = p.get("metadata") or {}
            if "gatekeeper.sh/system" in (meta.get("labels") or {}):
                live.add(meta.get("name"))
        if not live:
            return None  # can't see replica pods (RBAC/dev): don't GC
        live.add(pod_name())
        return live

    def _gc_template_statuses(self, live_pods: Optional[set]) -> int:
        """Prune replaced pods' byPod entries from ConstraintTemplate
        statuses (the leader sweeps these once per audit)."""
        if live_pods is None:
            return 0
        template_gvk = ("templates.gatekeeper.sh", "v1beta1",
                        "ConstraintTemplate")
        pruned = 0
        try:
            templates = self.kube.list(template_gvk)
        except KubeError:
            return 0
        from .resilience import guarded_status_update

        for obj in templates:
            if not prune_stale_by_pod(obj, live_pods):
                continue

            def refresh(cur_obj, _gvk=template_gvk):
                try:
                    cur = self.kube.get(
                        _gvk, (cur_obj.get("metadata") or {})
                        .get("name") or "")
                except KubeError:
                    return None
                if not prune_stale_by_pod(cur, live_pods):
                    return None
                return cur

            if guarded_status_update(self.kube, obj, refresh):
                pruned += 1
        return pruned

    def _status_entries(self, violations: list) -> list:
        """The capped, truncated violation entries a status write
        publishes for this violation set. None-valued fields are
        OMITTED, not written as nulls: a real apiserver's structural-
        schema pruning drops nulls on write, and the skip-unchanged
        comparison must match what reads back."""
        entries = []
        for r in violations[: self.limit]:
            res = r.resource or {}
            meta = res.get("metadata") or {}
            msg = r.msg
            if len(msg.encode()) > MSG_SIZE_LIMIT:
                msg = msg.encode()[:MSG_SIZE_LIMIT].decode("utf-8",
                                                           "ignore")
            entry = {
                "message": msg,
                "enforcementAction": r.enforcement_action,
                "kind": res.get("kind"),
                "name": meta.get("name"),
                "namespace": meta.get("namespace"),
            }
            entries.append({k: v for k, v in entry.items()
                            if v is not None})
        return entries

    def _update_constraint_status(self, obj: dict, entries: list,
                                  total: int) -> bool:
        status = obj.setdefault("status", {})
        status["auditTimestamp"] = time.strftime(
            "%Y-%m-%dT%H:%M:%SZ", time.gmtime())
        status["totalViolations"] = total
        status["violations"] = entries
        from .resilience import guarded_status_update

        def refresh(cur_obj):
            try:
                meta = cur_obj.get("metadata") or {}
                cur = self.kube.get(
                    (CONSTRAINT_GROUP, "v1beta1", cur_obj.get("kind")),
                    meta.get("name") or "")
            except KubeError:
                return None
            cur["status"] = status
            return cur

        # shared breaker-aware protocol: breaker refusals and guarded-
        # client transients return immediately (the next sweep's delta
        # comparison re-issues the write); only Conflicts refresh-retry
        return guarded_status_update(self.kube, obj, refresh)


# ------------------------------------------------------- sharded inventory

def _review_sort_key(review: Optional[dict]) -> list:
    """The driver's review ordering key (client/drivers.py builds
    inventory reviews cluster-scoped first, then namespaced, each
    sorted (ns, group/version, kind, name)) — recomputed from the
    review dict so per-shard result runs carry a merge key that
    interleaves bit-equal with the unsharded order."""
    review = review or {}
    rk = review.get("kind") or {}
    group = rk.get("group") or ""
    version = rk.get("version") or ""
    gv = f"{group}/{version}" if group else version
    ns = review.get("namespace")
    if ns:
        return [1, ns, gv, rk.get("kind") or "", review.get("name") or ""]
    return [0, "", gv, rk.get("kind") or "", review.get("name") or ""]


def _result_to_wire(r: Result) -> dict:
    """JSON-able Result: the shard materialized messages and ran the
    target's violation handler already, so `resource` travels populated
    and the leader never re-derives anything."""
    return {"msg": r.msg, "metadata": thaw(r.metadata) or {},
            "constraint": thaw(r.constraint), "review": thaw(r.review),
            "resource": thaw(r.resource),
            "enforcement_action": r.enforcement_action}


def _result_from_wire(d: dict) -> Result:
    return Result(msg=d.get("msg") or "", metadata=d.get("metadata") or {},
                  constraint=d.get("constraint"), review=d.get("review"),
                  resource=d.get("resource"),
                  enforcement_action=d.get("enforcement_action") or "deny")


def compose_shard_results(per_shard: dict, writer=None,
                          target: str = "admission.k8s.gatekeeper.sh"
                          ) -> list:
    """Merge per-shard sweep payloads into ONE ordered result list,
    bit-equal to the unsharded sweep. Kinds iterate sorted (the
    driver's template-kind-major order); within a kind each shard's
    run is already review-major in review sort order (every audit path
    — delta-serve, device consume, interpreter — emits row-major), so
    a heap-merge on the review key interleaves them exactly. A review's
    rows live on ONE shard (consistent hash of (GVK, namespace)), so
    ties never span shards and the merge is a true interleave, never a
    reorder. Composed kinds feed `writer.on_kind` as they finish so
    streamed status writes overlap the remaining merge."""
    kinds = sorted({k for p in per_shard.values()
                    for k in (p.get("kinds") or {})})
    out: list = []
    for kind in kinds:
        runs = [(p.get("kinds") or {}).get(kind) or []
                for _, p in sorted(per_shard.items())]
        merged = heapq.merge(*runs, key=lambda e: tuple(e[0]))
        kr = [_result_from_wire(e[1]) for e in merged]
        if writer is not None:
            writer.on_kind(target, kind, kr)
        out.extend(kr)
    return out


class AuditSliceServer:
    """The shard-process end of the sharded audit plane: serves
    /v1/auditslice on an audit engine's backplane socket. One request =
    one capped sweep of THIS process's slice — the driver's review
    filter (set_audit_shard) scopes candidates to owned objects while
    broadcast copies stay visible to joins — returning per-kind result
    runs keyed for the leader's bit-equal merge."""

    def __init__(self, client, shard_id: int = 0, shard_count: int = 1,
                 ready: Optional[Callable[[], bool]] = None):
        self.client = client
        self.shard_id = shard_id
        self.shard_count = shard_count
        # armed by the engine to the library sink's synced flag: a
        # freshly respawned shard must answer 503 (leader retries after
        # the supervisor's slice resync), never an empty-library sweep
        # that would silently drop this partition's violations
        self.ready = ready

    def handle_http(self, body: bytes) -> tuple:
        try:
            req = json.loads(body or b"{}")
        except ValueError:
            return 400, b'{"error":"bad json"}'
        if (req.get("op") or "sweep") != "sweep":
            return 400, b'{"error":"unknown op"}'
        if self.ready is not None and not self.ready():
            return 503, b'{"error":"shard not synced"}'
        cap = req.get("cap")
        driver = getattr(self.client, "driver", None)
        cap_armed = hasattr(driver, "audit_violations_cap")
        if cap_armed:
            driver.audit_violations_cap = cap
        t0 = time.monotonic()
        try:
            results = self.client.audit().results()
        finally:
            if cap_armed:
                driver.audit_violations_cap = None
        eval_s = time.monotonic() - t0
        kinds: dict = {}
        for r in results:
            kind = (r.constraint or {}).get("kind") or ""
            kinds.setdefault(kind, []).append(
                [_review_sort_key(r.review), _result_to_wire(r)])
        n_reviews = 0
        try:
            n_reviews = len(driver._inventory_reviews(
                "admission.k8s.gatekeeper.sh"))
        except Exception:
            pass
        out = {"shard": self.shard_id, "kinds": kinds,
               "stats": {"violations": len(results),
                         "reviews": n_reviews,
                         "eval_s": round(eval_s, 4)}}
        return 200, json.dumps(out).encode("utf-8")


class ShardedAuditPlane:
    """Leader-side orchestration of the sharded audit inventory.

    Consistent-hashes the auditable inventory by (GVK, namespace)
    across N audit engine processes (an AuditShardSupervisor's
    children). Each shard owns its slice end to end — the encoded
    feature rows, delta cache and incremental-sweep state live in that
    process, scoped by its driver's review filter — while the leader:

      * runs one InventoryTracker per shard over a ScopedKube view, so
        watches, resume RVs and the (uid, rv) state map persist per
        slice (and snapshot/restore per slice);
      * applies every delta to its OWN full-inventory client too
        (admission and preview still serve the whole cluster) and,
        riding the client's on_change notifications, routes the object
        to its owner shard plus a column-PRUNED broadcast copy to every
        other shard when the kind can be a join partner (the driver's
        audit_broadcast_spec — the sik join-key columns);
      * dispatches per-shard sweeps over the backplane and composes
        the per-kind runs into one bit-equal audit round;
      * rides shard death on the supervisor's respawn + per-shard sync
        (the slice rebuilds from the leader's tree) and re-sweeps ONLY
        the orphaned partition — the surviving shards' runs are
        already in hand.
    """

    TARGET = "admission.k8s.gatekeeper.sh"

    def __init__(self, kube, opa: Client, supervisor, shard_count: int,
                 vnodes: int = 64, sweep_timeout_s: float = 600.0):
        from .shardmap import ShardMap

        self.kube = kube
        self.opa = opa
        self.supervisor = supervisor
        self.shard_count = int(shard_count)
        self.map = ShardMap(self.shard_count, vnodes=vnodes)
        self.sweep_timeout_s = sweep_timeout_s
        self._stop = threading.Event()
        self._bcast: tuple = (None, None)  # (cache key, spec)
        self.trackers = [
            InventoryTracker(ScopedKube(kube, self._owns_pred(k)), opa)
            for k in range(self.shard_count)]
        metrics.report_audit_shard_map(self.map.version,
                                       self.shard_count)

    def _owns_pred(self, k: int) -> Callable:
        return lambda gvk, ns, _k=k: self.map.owner(gvk, ns) == _k

    def stop(self) -> None:
        self._stop.set()
        for t in self.trackers:
            t.stop()

    # ------------------------------------------------------- replication

    def attach(self) -> None:
        """Chain onto the leader client's on_change feed: data deltas
        route to their owner shard (+ the broadcast set), every other
        library op replicates to ALL shards (each shard's client
        evaluates the full template/constraint library over its
        slice). Chained, not replaced — the admission-engine fan-out
        installed before us keeps firing."""
        prev = self.opa.on_change

        def fan_out(op, obj, _prev=prev):
            if _prev is not None:
                _prev(op, obj)
            self.on_library_change(op, obj)

        self.opa.on_change = fan_out

    def on_library_change(self, op: str, obj) -> None:
        if self.supervisor is None:
            return
        if op == "add_data":
            self.route_add(obj)
        elif op == "remove_data":
            self.route_remove(obj)
        else:
            # template/constraint/mutator ops invalidate the broadcast
            # column spec (a new join template can widen it) and
            # replicate everywhere
            self._bcast = (None, None)
            self.supervisor.replicate(op, obj)

    def broadcast_spec(self) -> dict:
        """Join-relevant column spec from the leader driver, cached
        until a library (non-data) change invalidates it; the template-
        kind set double-keys the cache against restores that bypass
        on_change."""
        driver = getattr(self.opa, "driver", None)
        try:
            key = tuple(sorted(self.opa.template_kinds()))
        except Exception:
            key = None
        cached_key, spec = self._bcast
        if spec is not None and cached_key == key:
            return spec
        if hasattr(driver, "audit_broadcast_spec"):
            spec = driver.audit_broadcast_spec()
        else:
            # a driver that cannot prove column sets degrades to
            # whole-inventory broadcast: sharding must never change a
            # verdict
            spec = {"full": True, "kinds": {}}
        self._bcast = (key, spec)
        return spec

    _NO_BCAST = object()

    def _bcast_cols(self, kind: str):
        """Column subtrees a non-owner shard's copy of `kind` must
        carry: None = whole object, _NO_BCAST = not a join partner
        (owner-only), else a list of path tuples (kind-specific and
        wildcard-join columns unioned)."""
        spec = self.broadcast_spec()
        if spec.get("full"):
            return None
        kinds = spec.get("kinds") or {}
        entries = []
        if kind in kinds:
            entries.append(kinds[kind])
        if "*" in kinds:
            entries.append(kinds["*"])
        if not entries:
            return self._NO_BCAST
        cols: list = []
        for e in entries:
            if e is None:
                return None
            for c in e:
                if tuple(c) not in cols:
                    cols.append(tuple(c))
        return cols

    @staticmethod
    def _prune(obj: dict, cols: list) -> dict:
        """Broadcast skeleton: identity + the join-key column subtrees.
        Labels ride along (namespaceSelector / label joins read them);
        resourceVersion keeps shard-side (uid, rv) no-op dedupe
        working."""
        meta = obj.get("metadata") or {}
        out_meta = {k: v for k, v in
                    (("name", meta.get("name")),
                     ("namespace", meta.get("namespace")),
                     ("uid", meta.get("uid")),
                     ("resourceVersion", meta.get("resourceVersion")),
                     ("labels", meta.get("labels")))
                    if v is not None}
        out = {"apiVersion": obj.get("apiVersion"),
               "kind": obj.get("kind"), "metadata": out_meta}
        for path in cols:
            src: Any = obj
            ok = True
            for seg in path:
                if isinstance(src, dict) and seg in src:
                    src = src[seg]
                else:
                    ok = False
                    break
            if not ok:
                continue
            dst = out
            for seg in path[:-1]:
                nxt = dst.get(seg)
                if not isinstance(nxt, dict):
                    nxt = {}
                    dst[seg] = nxt
                dst = nxt
            dst[path[-1]] = src
        return out

    def route_add(self, obj: dict) -> None:
        from .kube import gvk_of

        sup = self.supervisor
        if sup is None:
            return
        gvk = gvk_of(obj)
        owner = self.map.owner_of_obj(gvk, obj)
        sup.send(owner, {"op": "add_data", "obj": obj})
        cols = self._bcast_cols(obj.get("kind") or "")
        if cols is self._NO_BCAST:
            return
        pruned = obj if cols is None else self._prune(obj, cols)
        for k in range(self.shard_count):
            if k != owner:
                sup.send(k, {"op": "add_data", "obj": pruned})

    def route_remove(self, obj: dict) -> None:
        from .kube import gvk_of

        sup = self.supervisor
        if sup is None:
            return
        gvk = gvk_of(obj)
        owner = self.map.owner_of_obj(gvk, obj)
        sup.send(owner, {"op": "remove_data", "obj": obj})
        if self._bcast_cols(obj.get("kind") or "") is self._NO_BCAST:
            return
        for k in range(self.shard_count):
            if k != owner:
                # removing a never-broadcast copy is a no-op shard-side
                # (delete_data of a missing path returns False)
                sup.send(k, {"op": "remove_data", "obj": obj})

    # ------------------------------------------------------ sync snapshot

    def sync_snapshot(self, shard: int) -> dict:
        """The supervisor's per-shard resync payload: full library +
        this shard's inventory slice REBUILT from the leader's tree
        (owned objects whole, join partners pruned) — a respawned
        shard heals without any cluster re-list; the tracker state
        never left the leader."""
        op = {"op": "sync", "library": self.opa.snapshot_library(),
              "mutators": []}
        driver = getattr(self.opa, "driver", None)
        tree = driver.inventory_snapshot() \
            if hasattr(driver, "inventory_snapshot") else None
        op["data"] = self._prune_tree_for(shard, tree) if tree else None
        return op

    def _prune_tree_for(self, shard: int, tree: dict) -> dict:
        from .kube import gvk_of

        out: dict = {}
        for target, scopes in tree.items():
            if not isinstance(scopes, dict):
                continue
            t_out: dict = {}
            for scope, sub in scopes.items():
                if scope == "cluster":
                    buckets = [("", sub)]
                elif scope == "namespace" and isinstance(sub, dict):
                    buckets = list(sub.items())
                else:
                    continue
                for ns, by_gv in buckets:
                    if not isinstance(by_gv, dict):
                        continue
                    for gv, by_kind in by_gv.items():
                        if not isinstance(by_kind, dict):
                            continue
                        for kind, by_name in by_kind.items():
                            if not isinstance(by_name, dict):
                                continue
                            group, _, version = gv.rpartition("/")
                            gvk = (group, version, kind)
                            owned = self.map.owner(gvk, ns) == shard
                            cols = None if owned \
                                else self._bcast_cols(kind)
                            if cols is self._NO_BCAST:
                                continue
                            for name, o in by_name.items():
                                if not isinstance(o, dict):
                                    continue
                                keep = o if (owned or cols is None) \
                                    else self._prune(o, cols)
                                if scope == "cluster":
                                    dst = t_out.setdefault(
                                        "cluster", {}).setdefault(
                                        gv, {}).setdefault(kind, {})
                                else:
                                    dst = t_out.setdefault(
                                        "namespace", {}).setdefault(
                                        ns, {}).setdefault(
                                        gv, {}).setdefault(kind, {})
                                dst[name] = keep
            out[target] = t_out
        return out

    # ----------------------------------------------------------- tracking

    def apply_pending(self) -> dict:
        agg = {"dirty": 0, "total": 0, "event_ts": []}
        for k, t in enumerate(self.trackers):
            st = t.apply_pending()
            agg["dirty"] += st["dirty"]
            agg["total"] += st["total"]
            agg["event_ts"].extend(st.get("event_ts") or [])
            metrics.report_audit_shard_ownership(k, st["total"])
        return agg

    def full_resync(self, gvks: list) -> dict:
        driver = getattr(self.opa, "driver", None)
        if hasattr(driver, "drop_inventory_caches"):
            driver.drop_inventory_caches()
        agg = {"dirty": 0, "total": 0, "event_ts": []}
        for k, t in enumerate(self.trackers):
            st = t.full_resync(gvks)
            agg["dirty"] += st["dirty"]
            agg["total"] += st["total"]
            metrics.report_audit_shard_ownership(k, st["total"])
        return agg

    # -------------------------------------------------------- warm restart

    def snapshot_state(self) -> dict:
        return {"shard_count": self.shard_count,
                "map_version": self.map.version,
                "shards": [t.snapshot() for t in self.trackers]}

    def restore_state(self, snap: Optional[dict]) -> int:
        """Per-slice warm restore. A snapshot taken under a DIFFERENT
        shard count is discarded (cold start): the hash ring moved, so
        the saved slices no longer line up with the live predicates —
        restoring watches against the wrong slice would silently leak
        objects between shards."""
        snap = snap or {}
        shards = snap.get("shards")
        if not shards or snap.get("shard_count") != self.shard_count \
                or len(shards) != self.shard_count:
            if shards:
                log.info("audit shard snapshot discarded (shard count "
                         "changed)",
                         details={"snapshot": snap.get("shard_count"),
                                  "configured": self.shard_count})
            return 0
        n = 0
        for t, s in zip(self.trackers, shards):
            n += t.restore(s)
        return n

    def restore_ready(self) -> bool:
        return all(t.validated.is_set() for t in self.trackers)

    # --------------------------------------------------------- rebalancing

    def rebalance(self, shard_count: int) -> dict:
        """Recompute the hash ring for a new shard count and report how
        much of the tracked inventory moved (the consistent-hashing
        contract: ~|new-old|/max(new,old), not ~all of it). The caller
        owns restarting the supervisor with the matching process count;
        trackers are rebuilt cold — their slices no longer match."""
        keys = set()
        for t in self.trackers:
            with t._lock:
                keys.update((k[0], k[1]) for k in t._state)
        for t in self.trackers:
            t.stop()
        stats = self.map.rebalance(shard_count, sorted(keys))
        self.shard_count = int(shard_count)
        self.trackers = [
            InventoryTracker(ScopedKube(self.kube, self._owns_pred(k)),
                             self.opa)
            for k in range(self.shard_count)]
        metrics.report_audit_shard_map(self.map.version,
                                       self.shard_count)
        metrics.report_audit_shard_rebalanced(stats["moved"])
        log.info("audit shard map rebalanced",
                 details={"shards": self.shard_count, **stats})
        return stats

    # -------------------------------------------------------------- sweeps

    def sweep(self, cap: Optional[int], writer=None,
              heartbeat: Optional[Callable[[], None]] = None
              ) -> tuple[list, dict]:
        """One composed audit round: every shard sweeps its slice
        concurrently; a shard that dies mid-sweep is retried alone
        after the supervisor's respawn + slice resync (the surviving
        shards' runs are kept). Returns (results, stats)."""
        body = json.dumps({"op": "sweep", "cap": cap}).encode("utf-8")
        per_shard: dict = {}
        resweeps = [0] * self.shard_count
        errors: list = []
        lock = threading.Lock()

        def one(k: int) -> None:
            deadline = time.monotonic() + self.sweep_timeout_s
            while True:
                try:
                    status, out = self.supervisor.sweep(
                        k, body,
                        timeout_s=max(1.0,
                                      deadline - time.monotonic()))
                    if status != 200:
                        raise KubeError(
                            f"shard {k} sweep HTTP {status}: "
                            f"{out[:200]!r}")
                    payload = json.loads(out.decode("utf-8"))
                    with lock:
                        per_shard[k] = payload
                    if heartbeat is not None:
                        heartbeat()
                    return
                except Exception as e:
                    if self._stop.is_set() or \
                            time.monotonic() >= deadline:
                        with lock:
                            errors.append((k, e))
                        return
                    # the supervisor's monitor respawns the child and
                    # restores its slice from sync_snapshot; only THIS
                    # partition re-sweeps
                    resweeps[k] += 1
                    metrics.report_audit_shard_resync(k)
                    log.warning("audit shard sweep failed; waiting "
                                "for respawn + slice resync",
                                details={"shard": k, "error": str(e)})
                    if heartbeat is not None:
                        heartbeat()
                    self._stop.wait(0.5)

        threads = [threading.Thread(target=one, args=(k,),
                                    name=f"audit-shard-sweep-{k}",
                                    daemon=True)
                   for k in range(self.shard_count)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        if errors:
            k, e = errors[0]
            raise KubeError(f"audit shard {k} sweep failed after "
                            f"retries: {e}")
        eval_max = 0.0
        violations = 0
        for k in sorted(per_shard):
            st = (per_shard[k].get("stats") or {})
            eval_s = float(st.get("eval_s") or 0.0)
            eval_max = max(eval_max, eval_s)
            violations += int(st.get("violations") or 0)
            metrics.report_audit_shard_sweep(
                k, eval_s, int(st.get("reviews") or 0))
        results = compose_shard_results(per_shard, writer=writer,
                                        target=self.TARGET)
        stats = {"shard_eval_max_s": round(eval_max, 4)}
        if any(resweeps):
            stats["shard_resweeps"] = sum(resweeps)
        return results, stats
