"""Audit manager: periodic full-cluster sweeps.

Counterpart of the reference pkg/audit/manager.go, re-designed around the
batched evaluator. The reference's hot loop lists every object of every
listable GVK and calls Review one object at a time (manager.go:250-271);
here the whole inventory goes through the driver's vectorized audit in one
batched sweep (audit-from-cache) or per-GVK batches (discovery mode), then
violations are aggregated per constraint (manager.go:337-385) and written
to constraint status with the violations cap, message truncation, and
conflict-retry loop (manager.go:428-574).
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from ..client import Client
from . import metrics
from .kube import KubeError, NotFound
from .logging import logger

log = logger("audit")

DEFAULT_AUDIT_INTERVAL = 60  # seconds (reference manager.go:36,41)
DEFAULT_CONSTRAINT_VIOLATIONS_LIMIT = 20  # manager.go:37,42
MSG_SIZE_LIMIT = 256  # bytes (manager.go:35,437-439)
CONSTRAINT_GROUP = "constraints.gatekeeper.sh"

# kinds never audited (cluster plumbing the reference also skips)
_SKIP_KINDS = {"Event", "ComponentStatus", "Endpoints", "EndpointSlice",
               "Lease", "SelfSubjectReview", "TokenReview",
               "SubjectAccessReview", "CustomResourceDefinition",
               "ConstraintTemplate"}


class AuditManager:
    def __init__(self, kube, opa: Client,
                 interval: float = DEFAULT_AUDIT_INTERVAL,
                 constraint_violations_limit: int =
                 DEFAULT_CONSTRAINT_VIOLATIONS_LIMIT,
                 audit_from_cache: bool = False):
        self.kube = kube
        self.opa = opa
        self.interval = interval
        self.limit = constraint_violations_limit
        self.audit_from_cache = audit_from_cache
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.last_results: list = []

    # ------------------------------------------------------------- lifecycle

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, name="audit",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.audit_once()
            except Exception as e:
                log.error("audit failed", details=str(e))
            self._stop.wait(self.interval)

    # ----------------------------------------------------------------- audit

    def audit_once(self) -> list:
        t0 = time.time()
        if self.audit_from_cache:
            # one vectorized sweep over the synced inventory
            results = self.opa.audit().results()
        else:
            results = self._audit_resources()
        by_constraint = self._group_by_constraint(results)
        self._write_audit_results(by_constraint)
        dt = time.time() - t0
        metrics.report_audit_duration(dt)
        metrics.report_audit_last_run()
        by_action: dict[str, int] = {}
        for r in results:
            by_action[r.enforcement_action] = \
                by_action.get(r.enforcement_action, 0) + 1
        for action, count in by_action.items():
            metrics.report_violations(action, count)
        self.last_results = results
        details = {"violations": len(results), "duration_s": round(dt, 3)}
        driver = getattr(self.opa, "driver", None)
        if hasattr(driver, "warm_status"):
            st = driver.warm_status()
            metrics.report_device_programs(st["warm"], st["compiling"])
            details["device_programs"] = st
            path = getattr(
                driver,
                "last_audit_path" if self.audit_from_cache
                else "last_review_batch_path", None)
            if path:
                details["audit_path"] = path
        log.info("audit complete", details=details)
        return results

    def _audit_resources(self) -> list:
        """Discovery-driven sweep: list every listable GVK and feed the
        objects through the driver's BATCHED inventory evaluation (the
        reference reviews one object at a time here)."""
        from ..target.handler import AugmentedUnstructured

        resources = [r for r in self.kube.server_preferred_resources()
                     if "list" in (r.get("verbs") or [])
                     and r.get("kind") not in _SKIP_KINDS
                     and r.get("group") not in ("templates.gatekeeper.sh",
                                                CONSTRAINT_GROUP)]
        resources.sort(key=lambda r: (r.get("kind") != "Namespace",
                                      r.get("group") or "", r.get("kind")))
        # stage all live objects into a scratch audit client: reuse the
        # driver's vectorized audit over inventory (external data paths)
        results = []
        staged: list[dict] = []
        # listed Namespaces, sideloaded onto each namespaced review so
        # namespaceSelector constraints resolve from the live cluster
        # state — NOT just synced inventory (reference wraps every object
        # as AugmentedUnstructured{obj, ns}, manager.go:250-271); the
        # sort above lists Namespaces first so the map is complete before
        # any namespaced object is staged
        ns_by_name: dict[str, dict] = {}
        saw_ns_kind = False
        for res in resources:
            gvk = (res["group"], res["version"], res["kind"])
            try:
                objs = self.kube.list(gvk)
            except KubeError:
                continue
            if gvk == ("", "v1", "Namespace"):
                saw_ns_kind = True
                for o in objs:
                    name = (o.get("metadata") or {}).get("name")
                    if name:
                        ns_by_name[name] = o
            staged.extend(objs)
        if not saw_ns_kind:
            # discovery may exclude Namespaces (RBAC-filtered lists);
            # fetch them explicitly — without this map every
            # namespaceSelector constraint autorejects. A FAILED listing
            # aborts the sweep: with no map, augmented() would skip every
            # namespaced object and the status write would then wipe all
            # previously-reported violations cluster-wide
            for o in self.kube.list(("", "v1", "Namespace")):
                name = (o.get("metadata") or {}).get("name")
                if name:
                    ns_by_name[name] = o

        ns_missing: set[str] = set()

        def resolve_ns(name: str) -> Optional[dict]:
            """Map hit, else a direct GET (a namespace created after the
            one-time snapshot — the reference's per-object nsCache.Get
            does the same on a cache miss). Failures are negative-cached
            for the sweep: N orphaned objects in a deleted namespace
            must cost one GET, not N."""
            ns_obj = ns_by_name.get(name)
            if ns_obj is None:
                if name in ns_missing:
                    return None
                try:
                    ns_obj = self.kube.get(("", "v1", "Namespace"), name)
                except KubeError:
                    ns_missing.add(name)
                    log.error("unable to look up object namespace; "
                              "skipping its objects this sweep",
                              details={"namespace": name})
                    return None
                ns_by_name[name] = ns_obj
            return ns_obj

        def augmented(o: dict) -> Optional[AugmentedUnstructured]:
            """Reference semantics (manager.go:250-271 + target.go:129-135):
            EVERY object gets a namespace sideload — the listed Namespace
            for namespaced objects (suppressing autoreject and giving the
            selector real labels), an EMPTY namespace for cluster-scoped
            ones (the reference's `&corev1.Namespace{}`, so selectors see
            no labels rather than autorejecting). An object whose
            namespace cannot be resolved is skipped, as the reference
            skips on a failed namespace fetch."""
            ns = (o.get("metadata") or {}).get("namespace")
            if not ns:
                return AugmentedUnstructured(o, {"metadata": {}})
            ns_obj = resolve_ns(ns)
            if ns_obj is None:
                return None
            return AugmentedUnstructured(o, ns_obj)

        # evaluate via the driver's batch review API when available,
        # falling back to per-object review
        driver = self.opa.driver
        target = "admission.k8s.gatekeeper.sh"
        if hasattr(driver, "review_batch"):
            handler = self.opa.targets[target]
            reviews = []
            for o in staged:
                aug = augmented(o)
                if aug is None:
                    continue
                handled, review = handler.handle_review(aug)
                if handled:
                    reviews.append(review)
            batches = driver.review_batch(target, reviews)
            for per_review in batches:
                for r in per_review:
                    handler.handle_violation(r)
                    results.append(r)
        else:
            for o in staged:
                aug = augmented(o)
                if aug is None:
                    continue
                results.extend(self.opa.review(aug).results())
        return results

    # ------------------------------------------------------------ aggregation

    def _group_by_constraint(self, results) -> dict[tuple, list]:
        grouped: dict[tuple, list] = {}
        for r in results:
            c = r.constraint or {}
            key = (c.get("kind") or "", (c.get("metadata") or {}).get("name")
                   or "")
            grouped.setdefault(key, []).append(r)
        return grouped

    def _write_audit_results(self, by_constraint: dict[tuple, list]) -> None:
        """status.byPod[audit] style update with cap + truncation + retry
        (manager.go:428-574). Constraints with no violations this run get
        their violation list cleared."""
        target_kinds = set()
        for kind in self.opa.template_kinds():
            target_kinds.add(kind)
        for kind in sorted(target_kinds):
            gvk = (CONSTRAINT_GROUP, "v1beta1", kind)
            try:
                constraints = self.kube.list(gvk)
            except KubeError:
                continue
            for obj in constraints:
                name = (obj.get("metadata") or {}).get("name") or ""
                violations = by_constraint.get((kind, name), [])
                self._update_constraint_status(obj, violations)

    def _update_constraint_status(self, obj: dict, violations: list) -> None:
        entries = []
        for r in violations[: self.limit]:
            res = r.resource or {}
            meta = res.get("metadata") or {}
            msg = r.msg
            if len(msg.encode()) > MSG_SIZE_LIMIT:
                msg = msg.encode()[:MSG_SIZE_LIMIT].decode("utf-8", "ignore")
            entries.append({
                "message": msg,
                "enforcementAction": r.enforcement_action,
                "kind": res.get("kind"),
                "name": meta.get("name"),
                "namespace": meta.get("namespace"),
            })
        status = obj.setdefault("status", {})
        status["auditTimestamp"] = time.strftime(
            "%Y-%m-%dT%H:%M:%SZ", time.gmtime())
        status["totalViolations"] = len(violations)
        status["violations"] = entries
        for attempt in range(5):
            try:
                self.kube.update(obj, subresource="status")
                return
            except NotFound:
                return
            except KubeError:
                time.sleep(0.01 * (2 ** attempt))
                try:
                    meta = obj.get("metadata") or {}
                    cur = self.kube.get(
                        (CONSTRAINT_GROUP, "v1beta1", obj.get("kind")),
                        meta.get("name") or "")
                    cur["status"] = status
                    obj = cur
                except KubeError:
                    return
