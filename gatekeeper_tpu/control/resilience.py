"""Kube write resilience: circuit breaker, retry budget, guarded client.

Gatekeeper's control loops write to the API server from several places
(audit constraint-status PATCHes, cert secret/CA-bundle updates,
controller byPod statuses and CRD applies — Runtime hands them all the
guarded client). During an API-server brownout every one of
those callers used to retry independently — N loops x M constraints of
synchronized hammering at the worst possible moment. This module gives
them one shared failure discipline, mirroring the reference's reliance on
client-go rate limiting + workqueue backoff:

  * CircuitBreaker — closed -> open after `failure_threshold` consecutive
    write failures; open -> half-open after `reset_timeout` (one probe
    in flight at a time); a probe success closes, a probe failure
    re-opens. Transitions are logged and exported as metrics, and the
    open state is surfaced through /readyz (wired in main.py).
  * RetryBudget — token bucket shared by every retrying writer: retries
    spend a token, steady time refills them. When an outage burns the
    budget, writers fail fast instead of amplifying the storm.
  * GuardedKube — transparent proxy over a kube client (Fake or REST)
    that routes the MUTATING verbs (create/update/apply/delete) through
    exponential-backoff-with-jitter retries under the shared breaker +
    budget. Reads and watches pass straight through. Fault-injection
    points "kube.write" and "kube.watch" live here so chaos suites storm
    any backing client.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Callable, Optional

from ..utils import faults
from . import metrics
from .kube import Conflict, KubeError, NotFound
from .logging import logger

log = logger("resilience")

# server-side statuses worth retrying (429/5xx); code=None means a
# transport-level failure (connection refused, reset), also transient
RETRYABLE_CODES = (429, 500, 502, 503, 504)


class BreakerOpen(KubeError):
    """Write refused locally: the breaker is open (no API call made)."""

    def __init__(self, message: str):
        super().__init__(message, code=503)


class NotLeader(KubeError):
    """Write refused locally: this replica does not hold the leader
    lease (no API call made). Raised by a GuardedKube whose write_gate
    says no — a deposed leader's in-flight status writes abort here
    instead of racing the new leader's writes. Status writers treat it
    like a breaker refusal: return immediately, the next sweep/reconcile
    on the actual leader re-issues the write."""

    def __init__(self, message: str):
        super().__init__(message, code=409)


class CircuitBreaker:
    """Consecutive-failure breaker with half-open probing."""

    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half_open"

    def __init__(self, name: str = "kube-writes",
                 failure_threshold: int = 5,
                 reset_timeout: float = 30.0):
        self.name = name
        self.failure_threshold = max(1, failure_threshold)
        self.reset_timeout = reset_timeout
        self._lock = threading.Lock()
        self._state = self.CLOSED
        self._fails = 0
        self._opened_at = 0.0
        self._probe_inflight = False
        self._probe_claimed_at = 0.0
        metrics.report_breaker(name, self.CLOSED)

    # ------------------------------------------------------------- state

    @property
    def state(self) -> str:
        with self._lock:
            self._tick()
            return self._state

    @property
    def is_open(self) -> bool:
        return self.state == self.OPEN

    def _tick(self) -> None:
        """open -> half-open once the reset timeout elapsed (lock held).

        Also expires a stale half-open probe LEASE: allow() hands out
        one probe slot, and the claimant is obligated to resolve it —
        but a claimant that dies without a verdict (its thread torn
        down mid-write, a BaseException skipping the caller's failure
        handling) would otherwise wedge the breaker in half-open with
        the slot held forever, refusing every write while the server
        may be perfectly healthy. A claim older than reset_timeout is
        treated as abandoned and the slot re-opens."""
        now = time.monotonic()
        if self._state == self.OPEN and \
                now - self._opened_at >= self.reset_timeout:
            self._transition(self.HALF_OPEN)
            self._probe_inflight = False
        elif self._state == self.HALF_OPEN and self._probe_inflight and \
                now - self._probe_claimed_at >= self.reset_timeout:
            log.info("circuit breaker %s: half-open probe lease expired; "
                     "releasing the slot" % self.name)
            self._probe_inflight = False

    def _transition(self, state: str) -> None:
        if state == self._state:
            return
        log.info("circuit breaker %s: %s -> %s"
                 % (self.name, self._state, state))
        self._state = state
        metrics.report_breaker(self.name, state)

    # ----------------------------------------------------------- calls

    def allow(self) -> bool:
        """May a write be attempted now? A True in half-open claims the
        single probe slot; the caller MUST follow with record_success or
        record_failure."""
        with self._lock:
            self._tick()
            if self._state == self.CLOSED:
                return True
            if self._state == self.HALF_OPEN and not self._probe_inflight:
                self._probe_inflight = True
                self._probe_claimed_at = time.monotonic()
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            self._probe_inflight = False
            self._fails = 0
            if self._state != self.CLOSED:
                self._transition(self.CLOSED)

    def record_failure(self) -> None:
        with self._lock:
            self._probe_inflight = False
            if self._state == self.HALF_OPEN:
                # probe failed: back to open for another reset period
                self._opened_at = time.monotonic()
                self._transition(self.OPEN)
                return
            self._fails += 1
            if self._state == self.CLOSED and \
                    self._fails >= self.failure_threshold:
                self._opened_at = time.monotonic()
                self._transition(self.OPEN)

    def abandon(self) -> None:
        """Release a claimed probe slot with NO health verdict.

        For callers cancelled before their write resolved
        (KeyboardInterrupt, SystemExit, executor teardown): the aborted
        attempt says nothing about the server, so the state machine and
        failure count stay untouched — half-open simply waits for the
        next real probe instead of wedging on the leaked slot."""
        with self._lock:
            self._probe_inflight = False


class RetryBudget:
    """Token bucket bounding RETRIES (first attempts are free): each
    retry spends one token; tokens refill at `refill_per_s`. A shared
    budget keeps a cluster-wide outage from turning into N independent
    exponential retry storms."""

    def __init__(self, budget: float = 10.0, refill_per_s: float = 1.0):
        self._cap = max(0.0, budget)
        self._tokens = self._cap
        self._refill = refill_per_s
        self._last = time.monotonic()
        self._lock = threading.Lock()

    def try_spend(self, n: float = 1.0) -> bool:
        with self._lock:
            now = time.monotonic()
            self._tokens = min(self._cap,
                               self._tokens + (now - self._last)
                               * self._refill)
            self._last = now
            if self._tokens >= n:
                self._tokens -= n
                return True
            return False


def retry_call(fn: Callable, breaker: Optional[CircuitBreaker] = None,
               budget: Optional[RetryBudget] = None, attempts: int = 4,
               base: float = 0.05, cap: float = 2.0,
               verb: str = "write"):
    """Run `fn` with exponential-backoff-with-jitter retries on transient
    KubeErrors, under the breaker and retry budget. NotFound/Conflict are
    semantic outcomes, not faults: they re-raise immediately and count as
    the server being alive.

    The breaker sees ONE verdict per retry_call — allow() once up
    front, record_success/record_failure once at the end — so
    --kube-breaker-threshold counts failed WRITES (as documented), not
    attempts, and a half-open probe's own retries never trip over the
    probe slot they hold."""
    if breaker is not None and not breaker.allow():
        metrics.report_kube_write("breaker_open")
        raise BreakerOpen(f"kube {verb} refused: circuit open")
    last: Optional[KubeError] = None
    for attempt in range(max(1, attempts)):
        try:
            out = fn()
        except (NotFound, Conflict):
            if breaker is not None:
                breaker.record_success()
            raise
        except KubeError as e:
            retryable = e.code is None or e.code in RETRYABLE_CODES
            if not retryable:
                # deterministic client error (403 RBAC, 422 schema...):
                # the server ANSWERED — it must neither trip the shared
                # breaker (that would escalate a config mistake into a
                # serving outage) nor be retried
                if breaker is not None:
                    breaker.record_success()
                metrics.report_kube_write("failed")
                raise
            last = e
            if attempt + 1 >= max(1, attempts):
                if breaker is not None:
                    breaker.record_failure()
                metrics.report_kube_write("failed")
                raise
            if budget is not None and not budget.try_spend():
                if breaker is not None:
                    breaker.record_failure()
                metrics.report_kube_write("budget_exhausted")
                raise
            # full jitter on the exponential step: synchronized callers
            # must desynchronize, not re-collide every 2^k
            time.sleep(min(cap, base * (2 ** attempt))
                       * (0.5 + random.random()))
            continue
        except Exception:
            # non-KubeError garbage (e.g. an LB answering with HTML
            # that fails json.loads): count it as a failure so a
            # claimed half-open probe slot is ALWAYS released —
            # otherwise the breaker wedges with _probe_inflight stuck
            # and no write ever goes through again
            if breaker is not None:
                breaker.record_failure()
            metrics.report_kube_write("failed")
            raise
        except BaseException:
            # cancellation (KeyboardInterrupt, SystemExit, interpreter
            # teardown) skips `except Exception` — it is not a health
            # verdict either way, so release the probe slot with no
            # state transition instead of leaking it
            if breaker is not None:
                breaker.abandon()
            raise
        if breaker is not None:
            breaker.record_success()
        metrics.report_kube_write("retried_ok" if attempt else "ok")
        return out
    raise last  # unreachable; defensive


def guarded_status_update(kube, obj: dict, refresh: Callable,
                          attempts: int = 5) -> bool:
    """Shared status-write retry protocol for every controller/audit
    writer: NotFound and a breaker refusal return immediately (the next
    reconcile/sweep re-issues the write), Conflicts refresh via
    `refresh(obj) -> obj | None` and retry without sleeping, and other
    KubeErrors retry with backoff ONLY on an unguarded client — a
    resilience.GuardedKube already retried transients under the shared
    breaker/budget, and stacking loops would multiply to attempts^2 of
    synchronized hammering. Returns True when the write landed."""
    guarded = getattr(kube, "breaker", None) is not None
    for i in range(attempts):
        try:
            kube.update(obj, subresource="status")
            return True
        except NotFound:
            return False
        except (BreakerOpen, NotLeader):
            return False
        except Conflict:
            pass  # resourceVersion raced another writer: refresh below
        except KubeError:
            if guarded:
                return False
            time.sleep(0.01 * (2 ** i))
        obj = refresh(obj)
        if obj is None:
            return False
    return False


class GuardedKube:
    """Transparent kube proxy: mutating verbs ride retry_call under the
    shared breaker + budget; everything else (reads, watches, discovery,
    FakeKube extras like register_kind/calls) delegates untouched."""

    def __init__(self, inner, breaker: Optional[CircuitBreaker] = None,
                 budget: Optional[RetryBudget] = None, attempts: int = 4,
                 write_gate: Optional[Callable[[], bool]] = None):
        self.inner = inner
        self.breaker = breaker
        self.budget = budget
        self.attempts = attempts
        # leadership fence: when set and False, mutating verbs raise
        # NotLeader BEFORE any API call — a deposed leader's in-flight
        # status writes abort at the proxy instead of racing the new
        # leader (wired to LeaseElector.is_leader by Runtime)
        self.write_gate = write_gate

    def __getattr__(self, name):
        return getattr(self.inner, name)

    def _guard(self, verb: str, fn: Callable):
        if self.write_gate is not None and not self.write_gate():
            metrics.report_kube_write("not_leader")
            raise NotLeader(f"kube {verb} refused: not the leader")

        def call():
            try:
                faults.fire("kube.write", verb=verb)
            except faults.FaultError as e:
                raise KubeError(str(e), code=e.code(503)) from None
            return fn()

        return retry_call(call, breaker=self.breaker, budget=self.budget,
                          attempts=self.attempts, verb=verb)

    def create(self, obj: dict) -> dict:
        return self._guard("create", lambda: self.inner.create(obj))

    def update(self, obj: dict, subresource: str = "") -> dict:
        return self._guard("update",
                           lambda: self.inner.update(obj, subresource))

    def apply(self, obj: dict) -> dict:
        return self._guard("apply", lambda: self.inner.apply(obj))

    def delete(self, gvk, name: str, namespace: str = "") -> None:
        return self._guard("delete",
                           lambda: self.inner.delete(gvk, name, namespace))

    def watch(self, gvk, callback, send_initial: bool = True,
              resource_version: str = "", on_gap=None):
        try:
            faults.fire("kube.watch", gvk=tuple(gvk))
        except faults.FaultError as e:
            raise KubeError(str(e), code=e.code(500)) from None
        return self.inner.watch(gvk, callback, send_initial=send_initial,
                                resource_version=resource_version,
                                on_gap=on_gap)
