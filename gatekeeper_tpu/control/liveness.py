"""Crash-loop backoff shared by the process supervisors.

The serving plane's three supervisors (frontends, engine children,
audit shards) respawn dead children from a 0.5s monitor loop. Before
this module that respawn was immediate and unconditional — a child
that dies during boot (bad flag, broken device, poisoned snapshot)
hot-loops the supervisor: spawn, crash, spawn, crash, each cycle
burning a fork + JAX init and spamming the log. `Backoff` rate-limits
the loop with jittered exponential delays and exports the state as two
supervisor-labeled gauges:

    gatekeeper_tpu_respawn_backoff_seconds{supervisor}  the delay the
        supervisor is currently holding before the next respawn
        attempt (0 = healthy / no delay pending)
    gatekeeper_tpu_crashloop_breaker{supervisor}  1 once a child has
        died `trip_after` consecutive times without ever surviving
        past `healthy_after` seconds — the alerting read for "this
        child will not come back on its own". Respawns CONTINUE at the
        capped delay; the breaker is a signal, not a stop.

A child that stays up past `healthy_after` resets its slot's count
(and the breaker, once no slot is tripped): a one-off chaos kill pays
no delay, only a sustained crash loop does.
"""

from __future__ import annotations

import random
import threading

from . import metrics


class Backoff:
    """Per-supervisor jittered exponential respawn backoff + crash-loop
    breaker. Thread-safe; one instance per supervisor, tracking every
    child slot."""

    def __init__(self, supervisor: str, base: float = 0.25,
                 factor: float = 2.0, cap: float = 30.0,
                 healthy_after: float = 30.0, trip_after: int = 5,
                 rng: random.Random = None):
        self.supervisor = supervisor
        self.base = base
        self.factor = factor
        self.cap = cap
        self.healthy_after = healthy_after
        self.trip_after = trip_after
        self._rng = rng if rng is not None else random.Random()
        self._lock = threading.Lock()
        self._consecutive: dict = {}   # slot -> deaths without a
        #                                healthy_after-long uptime
        self._tripped: set = set()

    def delay_for(self, slot, uptime_s: float) -> float:
        """Record one child death and return the delay to hold before
        its respawn. The first death of a healthy child (uptime past
        `healthy_after`, or the first ever) respawns immediately;
        consecutive fast deaths climb base * factor^n, jittered to
        [0.5x, 1.5x) so N children crashing together don't respawn in
        lockstep, capped at `cap`."""
        with self._lock:
            if uptime_s >= self.healthy_after:
                self._consecutive[slot] = 0
                self._tripped.discard(slot)
            n = self._consecutive.get(slot, 0) + 1
            self._consecutive[slot] = n
            if n >= self.trip_after:
                self._tripped.add(slot)
            tripped = bool(self._tripped)
            if n <= 1:
                delay = 0.0
            else:
                delay = min(self.cap, self.base * self.factor ** (n - 2))
                delay = min(self.cap,
                            delay * (0.5 + self._rng.random()))
        metrics.report_respawn_backoff(self.supervisor, delay)
        metrics.report_crashloop_breaker(self.supervisor, tripped)
        return delay

    def respawned(self, slot) -> None:
        """The slot's replacement is up: no delay is held any more (the
        crash count persists — only a healthy_after-long uptime, seen
        by note_healthy or the next delay_for, clears it)."""
        metrics.report_respawn_backoff(self.supervisor, 0.0)

    def pending(self, slot) -> bool:
        """True while the slot carries crash-loop state (a non-zero
        consecutive count or a tripped breaker) that a healthy uptime
        observation should clear."""
        with self._lock:
            return bool(self._consecutive.get(slot)) \
                or slot in self._tripped

    def note_healthy(self, slot) -> None:
        """The supervisor observed this slot's child alive past
        `healthy_after`: clear its crash count and, once no slot is
        tripped, the breaker gauge."""
        with self._lock:
            if not self._consecutive.get(slot) \
                    and slot not in self._tripped:
                return
            self._consecutive[slot] = 0
            self._tripped.discard(slot)
            tripped = bool(self._tripped)
        metrics.report_crashloop_breaker(self.supervisor, tripped)

    def close(self) -> None:
        """Supervisor teardown: a stopped supervisor must not export
        its last backoff/breaker state forever."""
        metrics.report_respawn_backoff(self.supervisor, 0.0)
        metrics.report_crashloop_breaker(self.supervisor, False)
