"""Offline fleet scan: saturate the engine with clusterless manifests.

The "millions of users" traffic shape for shift-left policy: every CI
run of every team scanning its GitOps repo tree, pipeline payload, or
multi-cluster inventory export through the same engine that serves
admission — no cluster attached. Everything below the Driver boundary
is a pure batch evaluator, so the scan problem is a LOADER problem:
keep the PR 14 bulk paths (`MicroBatcher.submit_many` in-process,
backplane B frames or the pipelined gRPC ``ReviewStream`` cross-
process) fed at device rate from a host-side parse of millions of
YAML/JSON documents.

Pipeline shape (bounded at every hop — a 10M-manifest tree must not
become a 10M-entry list anywhere):

  walk/shard -> N loader processes -> dedupe -> double-buffered feed
  (parse + envelope synth      (content-hash     (batch k+1 encodes
   off the hot path)            tier)             while batch k
                                                  evaluates)
                    -> streaming reporter (JSONL out as each bulk
                       batch returns; verdicts never accumulate)

Dedupe: repo trees repeat identical objects heavily (one base
manifest kustomized into dozens of overlays, chart defaults vendored
per service). The content key is the decision-cache recipe — a
blake2b-16 over the canonical synthesized request minus ``uid``/
``timeoutSeconds`` — computed in the loader processes; only the first
occurrence of a key crosses the wire, later occurrences rejoin that
key's verdict on the way out (outcome="dedup" in the record, so the
report still carries one line per manifest). The rejoin cache is a
bounded LRU: an evicted key simply re-evaluates, correctness does not
depend on the cap.

Verdict shape: every tier normalizes to the webhook's own response
construction (`webhook.verdict_response`), so a scan verdict is
bit-equal to what `/v1/admit` (or a per-manifest ``Client.review``)
would have answered for the same object — the conformance oracle
tests/test_scan.py enforces, dedupe path included.

Exit-code contract (CI):
  0  every manifest scanned, no denials, no error records
  1  at least one deny verdict (policy violations found)
  2  at least one error record (malformed manifest, shed/timeout/
     engine failure for some manifests) — takes precedence over 1
  3  the scan itself could not run (bad arguments, no policies,
     engine unreachable at startup)
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import queue
import sys
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Iterable, Iterator, Optional

from . import jsonio, metrics
from .logging import logger

log = logger("scan")

MANIFEST_EXTS = (".yaml", ".yml", ".json")
SCAN_USERNAME = "fleet-scan"
CHUNK = 128          # records per loader->feeder queue item
QUEUE_CHUNKS = 32    # loader queue depth (bounds parsed-but-unfed work)


class ScanFatal(Exception):
    """The scan cannot run/continue at all (exit code 3) — distinct
    from per-manifest error records, which never abort the scan."""


# --------------------------------------------------------------- loading


def synthesize_request(obj: dict) -> dict:
    """One clusterless AdmissionRequest for a raw manifest: the same
    review the API server would have sent for `kubectl create` of this
    object (no uid — per-attempt noise; no namespace sideload — there
    is no cluster to fetch it from)."""
    api = obj.get("apiVersion") or ""
    group, _, version = api.rpartition("/")
    meta = obj.get("metadata") or {}
    req = {
        "uid": "",
        "kind": {"group": group, "version": version,
                 "kind": obj.get("kind") or ""},
        "name": meta.get("name") or "",
        "operation": "CREATE",
        "userInfo": {"username": SCAN_USERNAME},
        "object": obj,
    }
    if meta.get("namespace"):
        req["namespace"] = meta["namespace"]
    return req


def content_key(request: dict) -> str:
    """Dedupe key: the decision-cache request hash recipe
    (webhook.DecisionCache.request_key) — canonical JSON of the
    request minus uid/timeoutSeconds. Duplicated here so loader
    processes never import the serving stack."""
    slim = {k: v for k, v in request.items()
            if k not in ("uid", "timeoutSeconds")}
    return hashlib.blake2b(jsonio.canonical_bytes(slim),
                           digest_size=16).hexdigest()


def is_k8s_manifest(doc: Any) -> bool:
    """A scannable document: apiVersion + kind present (gator's own
    bar). Helm values files, kustomization fragments, CI configs and
    the like fall out here as SKIPPED, not errors."""
    return (isinstance(doc, dict)
            and isinstance(doc.get("apiVersion"), str)
            and bool(doc.get("apiVersion"))
            and isinstance(doc.get("kind"), str)
            and bool(doc.get("kind")))


def walk_tree(root: str) -> tuple[list[str], int]:
    """(manifest file paths, non-manifest files skipped) under `root`,
    sorted for deterministic sharding. Dot-directories (.git, ...)
    are pruned."""
    files: list[str] = []
    skipped = 0
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames
                             if not d.startswith("."))
        for fn in sorted(filenames):
            if fn.startswith("."):
                continue
            if fn.lower().endswith(MANIFEST_EXTS):
                files.append(os.path.join(dirpath, fn))
            else:
                skipped += 1
    return files, skipped


def _expand(doc: Any) -> Iterator[Any]:
    """v1 List objects expand to their items (inventory exports and
    `kubectl get -o json` dumps ship them)."""
    if isinstance(doc, dict) and doc.get("kind") == "List" \
            and isinstance(doc.get("items"), list):
        for item in doc["items"]:
            yield item
    else:
        yield doc


def parse_file(path: str) -> Iterator[tuple[str, Any]]:
    """Yield ("ok"|"skip"|"err", payload) per document in one manifest
    file. Multi-doc YAML (``---`` separators) yields one entry per
    document; a parse failure is ONE error entry for the file (the
    stream position past a YAML error is undefined), never a raise."""
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except OSError as e:
        yield "err", (f"{path}: unreadable: {e}", path)
        return
    docs: Iterable[Any]
    if path.lower().endswith(".json"):
        try:
            docs = [json.loads(raw)]
        except ValueError as e:
            yield "err", (f"{path}: invalid JSON: {e}", path)
            return
    else:
        try:
            import yaml
        except ImportError:
            yield "err", (f"{path}: pyyaml unavailable in this "
                          "environment", path)
            return
        try:
            docs = list(yaml.safe_load_all(raw))
        except yaml.YAMLError as e:
            yield "err", (f"{path}: invalid YAML: "
                          f"{str(e).splitlines()[0]}", path)
            return
    i = 0
    for top in docs:
        for doc in _expand(top):
            if doc is None:
                continue  # blank document between --- separators
            origin = f"{path}#{i}"
            i += 1
            if not is_k8s_manifest(doc):
                yield "skip", origin
            else:
                yield "ok", (origin, doc)


def parse_jsonl(path: str, shard: int = 0, nshards: int = 1,
                lines: Optional[Iterable[bytes]] = None,
                ) -> Iterator[tuple[str, Any]]:
    """Inventory-export loader: one JSON object per line. Sharding is
    by line number so N loaders split one large export; every loader
    still streams the file (reading is cheap next to parsing)."""
    close = None
    if lines is None:
        try:
            f = open(path, "rb")
        except OSError as e:
            if shard == 0:
                yield "err", (f"{path}: unreadable: {e}", path)
            return
        lines, close = f, f.close
    try:
        for n, line in enumerate(lines):
            if n % nshards != shard:
                continue
            if not line.strip():
                continue
            origin = f"{path}:{n + 1}"
            try:
                doc = json.loads(line)
            except ValueError as e:
                yield "err", (f"{origin}: invalid JSON line: {e}",
                              origin)
                continue
            for item in _expand(doc):
                if not is_k8s_manifest(item):
                    yield "skip", origin
                else:
                    yield "ok", (origin, item)
    finally:
        if close is not None:
            close()


def _records(entries: Iterator[tuple[str, Any]],
             encode: bool) -> Iterator[tuple]:
    """Map parse entries to wire-ready records:
      ("ok", origin, key, request, payload|None)
      ("err", origin, message) / ("skip", origin)
    `encode` pre-serializes the AdmissionReview envelope bytes for the
    backplane tier inside the loader process — the whole point of
    taking parse+synth off the hot path."""
    for state, payload in entries:
        if state == "ok":
            origin, doc = payload
            request = synthesize_request(doc)
            body = None
            if encode:
                body = jsonio.dumps_bytes(
                    {"apiVersion": "admission.k8s.io/v1beta1",
                     "kind": "AdmissionReview", "request": request})
            yield "ok", origin, content_key(request), request, body
        elif state == "skip":
            yield "skip", payload
        else:
            msg, origin = payload
            yield "err", origin, msg


def _loader_entries(fmt: str, paths: list[str], shard: int,
                    nshards: int) -> Iterator[tuple[str, Any]]:
    if fmt == "jsonl":
        for path in paths:
            yield from parse_jsonl(path, shard, nshards)
    else:
        # tree / yaml: `paths` is the pre-walked manifest file list;
        # shard by file index
        for path in paths[shard::nshards]:
            yield from parse_file(path)


def _loader_main(fmt: str, paths: list[str], shard: int, nshards: int,
                 encode: bool, outq) -> None:
    """One loader process: parse this shard, push CHUNK-sized record
    lists onto the bounded queue, then a ("done", shard) sentinel.
    Never imports jax or the serving stack."""
    chunk: list[tuple] = []
    try:
        for rec in _records(_loader_entries(fmt, paths, shard, nshards),
                            encode):
            chunk.append(rec)
            if len(chunk) >= CHUNK:
                outq.put(chunk)
                chunk = []
        if chunk:
            outq.put(chunk)
    finally:
        outq.put(("done", shard))


class LoaderPool:
    """N parallel loader processes (0 = parse inline in the caller's
    thread) feeding one bounded queue of record chunks."""

    def __init__(self, fmt: str, paths: list[str], n: int,
                 encode: bool):
        self.n = max(0, int(n))
        self._inline = None
        self._procs: list = []
        if self.n == 0:
            self._inline = _records(
                _loader_entries(fmt, paths, 0, 1), encode)
            return
        import multiprocessing as mp

        ctx = mp.get_context("spawn")
        self._q = ctx.Queue(maxsize=QUEUE_CHUNKS)
        for k in range(self.n):
            p = ctx.Process(target=_loader_main,
                            args=(fmt, paths, k, self.n, encode,
                                  self._q),
                            daemon=True)
            p.start()
            self._procs.append(p)

    def chunks(self) -> Iterator[list[tuple]]:
        if self._inline is not None:
            chunk: list[tuple] = []
            for rec in self._inline:
                chunk.append(rec)
                if len(chunk) >= CHUNK:
                    yield chunk
                    chunk = []
            if chunk:
                yield chunk
            return
        finished: set = set()
        while len(finished) < self.n:
            try:
                item = self._q.get(timeout=1.0)
            except queue.Empty:
                # a loader that died (OOM, import failure in a broken
                # environment) must surface as an error record, never
                # hang the scan waiting on a sentinel that won't come
                for k, p in enumerate(self._procs):
                    if k in finished or p.exitcode is None:
                        continue
                    try:  # one last drain: exit vs flush can race
                        item = self._q.get(timeout=0.5)
                    except queue.Empty:
                        finished.add(k)
                        yield [("err", f"loader[{k}]",
                                f"loader process {k} died "
                                f"(exit {p.exitcode}) before finishing "
                                "its shard")]
                        continue
                    break
                else:
                    continue
            if isinstance(item, tuple) and item and item[0] == "done":
                finished.add(item[1])
                continue
            yield item

    def close(self) -> None:
        for p in self._procs:
            p.join(timeout=10)
            if p.is_alive():
                p.terminate()


# ----------------------------------------------------------------- tiers


def _verdict_from_response(resp: dict) -> dict:
    """Normalize one AdmissionReview `response` object into the scan
    verdict record. Stance answers (shed 429 / timeout 504 / internal
    500) become error records — an unevaluated manifest must not be
    reported as allowed; 403 (deny) and gatekeeper-resource validation
    codes pass through as verdicts."""
    status = resp.get("status") or {}
    code = status.get("code")
    if code in (429, 500, 504):
        return {"error": status.get("message")
                or f"admission status {code}"}
    v: dict = {"allowed": bool(resp.get("allowed"))}
    reason = status.get("reason") or status.get("message")
    if reason:
        v["reason"] = reason
    if resp.get("warnings"):
        v["warnings"] = list(resp["warnings"])
    return v


class InprocTier:
    """In-process feed: records go straight into
    ValidationHandler.handle_bulk — one submit_many enqueue per batch
    against this process's own engine. A 2-thread executor gives the
    double buffer: batch k+1's envelope synth and dedupe overlap batch
    k's device evaluation."""

    name = "inproc"
    wants_bytes = False

    def __init__(self, validation, timeout_s: float):
        from concurrent.futures import ThreadPoolExecutor

        self.validation = validation
        self.timeout_s = timeout_s
        self._pool = ThreadPoolExecutor(max_workers=2,
                                        thread_name_prefix="scan-feed")

    def begin(self, batch: list[tuple]):
        reviews = [{"request": rec[3]} for rec in batch]
        deadline = time.monotonic() + self.timeout_s
        return self._pool.submit(self.validation.handle_bulk, reviews,
                                 deadline)

    def finish(self, token) -> list[dict]:
        return [_verdict_from_response((env or {}).get("response")
                                       or {})
                for env in token.result()]

    def close(self) -> None:
        self._pool.shutdown(wait=False)
        self.validation.batcher.stop()


class BackplaneTier:
    """Cross-process feed over the backplane's length-prefixed B
    frames: pre-serialized AdmissionReview bytes from the loaders go
    out as one vectored frame per batch. review_bulk_begin/finish
    split the round trip so the next batch encodes while this one
    evaluates in the engine process — the double buffer costs no
    thread per in-flight frame."""

    name = "backplane"
    wants_bytes = True

    def __init__(self, socket_path: str, timeout_s: float):
        from .backplane import BackplaneClient, BackplaneError

        self._err_cls = BackplaneError
        self.timeout_s = timeout_s
        self.client = BackplaneClient(
            socket_path, worker_id=f"scan-{os.getpid()}")

    def begin(self, batch: list[tuple]):
        payloads = [rec[4] for rec in batch]
        try:
            return self.client.review_bulk_begin(
                payloads, timeout_s=self.timeout_s)
        except self._err_cls as e:
            return e  # failed batch: finish() maps it to error records

    def finish(self, token) -> list[dict]:
        if isinstance(token, Exception):
            raise token
        return [_verdict_from_response(
                    (jsonio.loads(env) or {}).get("response") or {})
                for env in self.client.review_bulk_finish(token)]

    def close(self) -> None:
        self.client.close()


class GrpcTier:
    """Cross-process feed over the pipelined gRPC ReviewStream: one
    bidirectional HTTP/2 stream, batches pipelined with no per-RPC
    round trip. raw=True skips client-side Responses object
    construction (a million Result dataclasses is pure overhead when
    the next step flattens them to verdict pairs anyway). A mid-stream
    batch error fails every batch still in flight and the stream is
    rebuilt for the remainder of the scan."""

    name = "grpc"
    wants_bytes = False

    def __init__(self, address: str, timeout_s: float):
        from ..service.client import RemoteClient

        self.rc = RemoteClient(address)
        self.timeout_s = timeout_s
        self._resp = None
        self._q: Optional[queue.Queue] = None
        self._out = 0
        self._dead = 0
        self._err = ""

    def _reset(self) -> None:
        self._q = queue.Queue()
        self._resp = self.rc.review_stream(iter(self._q.get, None),
                                           raw=True)

    def begin(self, batch: list[tuple]):
        if self._resp is None:
            self._reset()
        self._out += 1
        self._q.put([rec[3] for rec in batch])
        return batch

    @staticmethod
    def _verdict(wire: dict) -> dict:
        from .webhook import verdict_response

        pairs = []
        for resp in (wire.get("byTarget") or {}).values():
            for r in resp.get("results") or []:
                pairs.append((r.get("enforcementAction") or "deny",
                              r.get("msg") or ""))
        return _verdict_from_response(verdict_response(pairs))

    def finish(self, token) -> list[dict]:
        self._out -= 1
        if self._dead:
            # a prior batch's stream error already doomed this one
            self._dead -= 1
            return [{"error": self._err} for _ in token]
        try:
            wire = next(self._resp)
        except StopIteration:
            wire = None
        except Exception as e:  # per-batch server error or transport
            self._err = f"stream batch failed: {e}"
            self._dead = self._out
            self._resp = None
            return [{"error": self._err} for _ in token]
        if wire is None or len(wire) != len(token):
            self._err = "review stream answered short"
            self._dead = self._out
            self._resp = None
            return [{"error": self._err} for _ in token]
        return [self._verdict(d) for d in wire]

    def close(self) -> None:
        if self._q is not None:
            self._q.put(None)  # ends the request generator
        try:
            for _ in self._resp or ():
                pass
        except Exception:
            pass
        self.rc.close()


# ----------------------------------------------------- dedupe + reporter


class DedupeTier:
    """Content-hash dedupe IN FRONT of the wire (and of the engine's
    decision cache): first occurrence of a key goes out, duplicates
    wait on that key's verdict (rejoined when its batch returns) or
    hit the bounded verdict LRU. size=0 disables."""

    def __init__(self, size: int):
        self.size = max(0, int(size))
        self._verdicts: "OrderedDict[str, dict]" = OrderedDict()
        self._inflight: dict[str, list[str]] = {}
        self.hits = 0

    def check(self, key: str, origin: str) -> Optional[dict]:
        """None -> caller must send this record; a verdict dict ->
        served from cache; ... queued behind an in-flight key returns
        the _PENDING sentinel."""
        if not self.size:
            return None
        v = self._verdicts.get(key)
        if v is not None:
            self._verdicts.move_to_end(key)
            self.hits += 1
            return v
        waiters = self._inflight.get(key)
        if waiters is not None:
            waiters.append(origin)
            self.hits += 1
            return _PENDING
        self._inflight[key] = []
        return None

    def resolve(self, key: str, verdict: dict) -> list[str]:
        """Record the verdict for `key`; returns the origins that were
        queued behind it (the caller emits their records)."""
        if not self.size:
            return []
        waiters = self._inflight.pop(key, [])
        if "error" not in verdict:
            # an error verdict (shed/timeout) must not be replayed to
            # later duplicates — let them re-evaluate
            self._verdicts[key] = verdict
            while len(self._verdicts) > self.size:
                self._verdicts.popitem(last=False)
        return waiters


_PENDING = {"__pending__": True}


class Reporter:
    """Streaming JSONL sink + counters. One line per manifest, written
    as its batch returns — a 10M-manifest scan holds one batch of
    records in memory, never the verdict set."""

    def __init__(self, out):
        self.out = out
        self.counts = {"allow": 0, "deny": 0, "error": 0, "dedup": 0,
                       "skip": 0}
        self.denied = 0
        self.manifests = 0

    def emit(self, origin: str, verdict: dict, outcome: str) -> None:
        rec = {"origin": origin}
        if "error" in verdict:
            outcome = "error"
            rec["error"] = verdict["error"]
        else:
            rec.update(verdict)
            if not verdict.get("allowed"):
                self.denied += 1
        self.counts[outcome] = self.counts.get(outcome, 0) + 1
        if outcome != "skip":
            self.manifests += 1
            rec["outcome"] = outcome
            self.out.write(jsonio.dumps_bytes(rec).decode() + "\n")

    def skip(self, origin: str) -> None:
        self.counts["skip"] += 1

    def flush_metrics(self) -> None:
        for outcome, n in self.counts.items():
            if n:
                metrics.report_scan_manifests(outcome, n)
        # counters, not deltas: flush once at scan end (this process
        # exits with the scan; nothing scrapes mid-run by default)


# ---------------------------------------------------------------- engine


def run_scan(tier, loader: LoaderPool, out, batch_size: int = 256,
             depth: int = 2, dedupe_size: int = 65536,
             ) -> dict:
    """Drive the pipeline to completion; returns the summary dict.
    `tier` is one of the three feed tiers, `loader` an initialized
    LoaderPool, `out` a text stream for JSONL records."""
    rep = Reporter(out)
    dedupe = DedupeTier(dedupe_size)
    inflight: deque = deque()   # (token, [(key, origin), ...])
    batch: list[tuple] = []
    sent_unique = 0
    t_start = time.monotonic()

    def complete_one() -> None:
        token, items = inflight.popleft()
        t0 = time.monotonic()
        try:
            verdicts = tier.finish(token)
        except Exception as e:
            verdicts = [{"error": f"bulk batch failed: {e}"}
                        for _ in items]
        dt = time.monotonic() - t0
        metrics.report_scan_batch(tier.name, dt)
        metrics.report_stage("scan", "scan_feed", dt)
        t1 = time.monotonic()
        if len(verdicts) != len(items):
            verdicts = [{"error": "bulk batch answered short"}
                        for _ in items]
        for (key, origin), verdict in zip(items, verdicts):
            rep.emit(origin, verdict,
                     "error" if "error" in verdict else
                     ("allow" if verdict.get("allowed") else "deny"))
            for dup_origin in dedupe.resolve(key, verdict):
                rep.emit(dup_origin, verdict,
                         "error" if "error" in verdict else "dedup")
        metrics.report_stage("scan", "scan_report",
                             time.monotonic() - t1)

    def flush() -> None:
        nonlocal batch, sent_unique
        if not batch:
            return
        while len(inflight) >= depth:
            complete_one()
        sent_unique += len(batch)
        inflight.append((tier.begin(batch),
                         [(rec[2], rec[1]) for rec in batch]))
        batch = []

    t_wait = time.monotonic()
    for chunk in loader.chunks():
        metrics.report_stage("scan", "scan_load",
                             time.monotonic() - t_wait)
        t0 = time.monotonic()
        for rec in chunk:
            state = rec[0]
            if state == "ok":
                _, origin, key, _request, _body = rec
                hit = dedupe.check(key, origin)
                if hit is None:
                    batch.append(rec)
                elif hit is not _PENDING:
                    rep.emit(origin, hit, "dedup")
            elif state == "skip":
                rep.skip(rec[1])
            else:
                rep.emit(rec[1], {"error": rec[2]}, "error")
        metrics.report_stage("scan", "scan_dedupe",
                             time.monotonic() - t0)
        if len(batch) >= batch_size:
            flush()
        t_wait = time.monotonic()
    flush()
    while inflight:
        complete_one()
    # keys whose first occurrence errored leave waiters behind only if
    # resolve() was never reached — the zip above always reaches it,
    # so every manifest has exactly one record by here
    loader.close()
    wall = time.monotonic() - t_start
    rep.flush_metrics()
    done = rep.manifests
    summary = {
        "tier": tier.name,
        "manifests": done,
        "unique_evaluated": sent_unique,
        "deduped": rep.counts.get("dedup", 0),
        "allowed": rep.counts.get("allow", 0),
        "denied": rep.denied,
        "errors": rep.counts.get("error", 0),
        "skipped_docs": rep.counts.get("skip", 0),
        "wall_s": round(wall, 3),
        "manifests_per_sec": round(done / wall) if wall > 0 else 0,
        "dedupe_hits": dedupe.hits,
    }
    return summary


def exit_code(summary: dict) -> int:
    if summary.get("errors"):
        return 2
    if summary.get("denied"):
        return 1
    return 0


# ------------------------------------------------ in-process policy load


def iter_policy_docs(paths: list[str]) -> Iterator[tuple[str, dict]]:
    for p in paths:
        files = [p]
        if os.path.isdir(p):
            files, _ = walk_tree(p)
        for f in files:
            for state, payload in parse_file(f):
                if state == "err":
                    raise ScanFatal(f"policy source: {payload[0]}")
                if state == "ok":
                    yield payload


def ingest_policies(client, paths: list[str]) -> dict:
    """Load ConstraintTemplates + constraints from files/dirs into the
    scan's private client. Templates ingest before constraints so file
    order never matters."""
    templates, constraints = [], []
    for origin, doc in iter_policy_docs(paths):
        if doc.get("kind") == "ConstraintTemplate":
            templates.append((origin, doc))
        elif str(doc.get("apiVersion", "")).startswith(
                "constraints.gatekeeper.sh"):
            constraints.append((origin, doc))
        # other kinds in a policy dir (e.g. sync configs) are ignored
    for origin, doc in templates:
        try:
            client.add_template(doc)
        except Exception as e:
            raise ScanFatal(f"{origin}: template rejected: {e}") from e
    for origin, doc in constraints:
        try:
            client.add_constraint(doc)
        except Exception as e:
            raise ScanFatal(f"{origin}: constraint rejected: {e}") \
                from e
    return {"templates": len(templates), "constraints": len(constraints)}


def ingest_candidate(client, template: Optional[dict],
                     constraint: dict) -> str:
    """Preview mode: ingest ONE candidate template+constraint under the
    PR 9 content-hashed alias kind (`<Kind>PV<sha12>`), so candidate
    program identity matches what a server-side /v1/preview of the same
    template content compiles — the AOT store and XLA cache serve both.
    Returns the alias kind."""
    import copy

    kind = constraint.get("kind") or ""
    if template is not None:
        names = (((template.get("spec") or {}).get("crd") or {})
                 .get("spec") or {}).get("names") or {}
        kind = kind or names.get("kind") or ""
        content = template.get("spec")
        sha = hashlib.sha256(json.dumps(
            content, sort_keys=True,
            default=str).encode()).hexdigest()[:12]
        alias = f"{kind}PV{sha}"
        t2 = copy.deepcopy(template)
        ((t2.setdefault("spec", {}).setdefault("crd", {})
          .setdefault("spec", {}).setdefault("names", {})
          )["kind"]) = alias
        t2.setdefault("metadata", {})["name"] = alias.lower()
        try:
            client.add_template(t2)
        except Exception as e:
            raise ScanFatal(f"candidate template rejected: {e}") from e
    else:
        # constraint against an already-ingested template kind: no
        # alias needed, the candidate IS just a constraint
        if not kind:
            raise ScanFatal("candidate constraint has no kind")
        alias = kind
    c2 = copy.deepcopy(constraint)
    c2["kind"] = alias
    c2.setdefault("apiVersion", "constraints.gatekeeper.sh/v1beta1")
    c2.setdefault("metadata", {}).setdefault("name", "scan-preview")
    try:
        client.add_constraint(c2)
    except Exception as e:
        raise ScanFatal(f"candidate constraint rejected: {e}") from e
    return alias


def build_inproc_tier(policy_paths: list[str], aot_dir: str = "",
                      compile_cache_dir: str = "",
                      decision_cache: int = 4096,
                      timeout_s: float = 300.0,
                      preview_template: Optional[dict] = None,
                      preview_constraint: Optional[dict] = None,
                      client=None) -> InprocTier:
    """The self-contained engine for cluster-free CI: a private client
    + MicroBatcher + ValidationHandler in this process. With --aot-dir
    the run populates (cold) or deserializes from (warm) the AOT
    store — PR 8's short-lived-invocation story."""
    if client is None:
        if compile_cache_dir:
            os.environ["GATEKEEPER_TPU_COMPILE_CACHE"] = \
                compile_cache_dir
        from ..client import Backend
        from ..ir import TpuDriver
        from ..target import K8sValidationTarget

        driver = TpuDriver(aot_dir=aot_dir) if aot_dir else TpuDriver()
        if aot_dir and hasattr(driver, "aot"):
            # like warm-cache: mint durable executables so the NEXT
            # scan boots warm even when the XLA cache answered this one
            driver.aot.force_durable = True
        client = Backend(driver).new_client([K8sValidationTarget()])
        if preview_constraint is not None:
            alias = ingest_candidate(client, preview_template,
                                     preview_constraint)
            log.info("scan preview candidate ingested",
                     details={"alias": alias})
        elif policy_paths:
            counts = ingest_policies(client, policy_paths)
            if not counts["templates"] and not counts["constraints"]:
                raise ScanFatal("no templates/constraints found under "
                                f"--policies {policy_paths}")
        else:
            raise ScanFatal("in-process scan needs --policies (or "
                            "--preview-constraint), or point at a "
                            "running engine with --backplane/--grpc")
    from .webhook import MicroBatcher, ValidationHandler

    batcher = MicroBatcher(client, max_wait=0.002, max_batch=256)
    validation = ValidationHandler(
        client, kube=None, batcher=batcher,
        decision_cache_size=decision_cache)
    return InprocTier(validation, timeout_s)


# ------------------------------------------------------------------- CLI


def _load_manifest_file(path: str) -> dict:
    docs = [d for s, d in
            ((s, p[1] if s == "ok" else p) for s, p in parse_file(path))
            if s == "ok"]
    if len(docs) != 1:
        raise ScanFatal(f"{path}: expected exactly one manifest "
                        f"(found {len(docs)})")
    return docs[0]


def build_scan_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="gatekeeper-tpu scan",
        description="offline fleet scan: evaluate a repo tree / YAML "
                    "stream / JSONL inventory export against policy, "
                    "no cluster attached")
    p.add_argument("paths", nargs="+",
                   help="manifest sources: directories (repo trees), "
                        "multi-doc YAML files, or .jsonl inventory "
                        "exports")
    p.add_argument("--format", choices=("auto", "tree", "yaml",
                                        "jsonl"), default="auto",
                   help="source format (auto: directories walk as "
                        "trees, *.jsonl as JSONL, anything else as "
                        "multi-doc YAML)")
    p.add_argument("--policies", action="append", default=[],
                   help="template/constraint file or directory for the "
                        "in-process engine (repeatable)")
    p.add_argument("--backplane", default="",
                   help="scan through a running engine's backplane "
                        "socket (B-frame bulk ingest)")
    p.add_argument("--grpc", default="",
                   help="scan through a policy service address "
                        "(pipelined ReviewStream)")
    p.add_argument("--loaders", type=int,
                   default=min(4, os.cpu_count() or 1),
                   help="parallel loader processes (0 = parse inline)")
    p.add_argument("--batch", type=int, default=256,
                   help="manifests per bulk wire batch")
    p.add_argument("--depth", type=int, default=2,
                   help="bulk batches in flight (2 = double buffer)")
    p.add_argument("--dedupe", type=int, default=65536,
                   help="content-hash dedupe LRU size (0 disables)")
    p.add_argument("--decision-cache", type=int, default=4096,
                   help="in-process engine decision-cache size "
                        "(0 disables; cross-process tiers use the "
                        "serving engine's own)")
    p.add_argument("--aot-dir", default="",
                   help="AOT program store for the in-process engine "
                        "(cold run populates, warm run deserializes)")
    p.add_argument("--compile-cache-dir", default="",
                   help="persistent XLA compile cache dir")
    p.add_argument("--timeout", type=float, default=300.0,
                   help="per-batch verdict deadline seconds (a cold "
                        "first batch may wait out one XLA compile)")
    p.add_argument("--output", default="-",
                   help="JSONL verdict records ('-' = stdout)")
    p.add_argument("--summary", default="",
                   help="also write the JSON summary to this file")
    p.add_argument("--preview-constraint", default="",
                   help="what-if mode: scan against ONLY this "
                        "candidate constraint (in-process tier)")
    p.add_argument("--preview-template", default="",
                   help="candidate ConstraintTemplate for "
                        "--preview-constraint (compiled under its "
                        "content-hashed alias kind)")
    p.add_argument("--log-level", default="WARNING")
    return p


def _resolve_sources(paths: list[str], fmt: str
                     ) -> tuple[str, list[str], int]:
    """(resolved format, loader path list, files skipped in walk)."""
    if fmt == "auto":
        if all(os.path.isdir(p) for p in paths):
            fmt = "tree"
        elif all(p.lower().endswith(".jsonl") for p in paths):
            fmt = "jsonl"
        elif any(os.path.isdir(p) for p in paths):
            fmt = "tree"
        else:
            fmt = "yaml"
    skipped_files = 0
    if fmt == "tree":
        files: list[str] = []
        for p in paths:
            if os.path.isdir(p):
                got, skipped = walk_tree(p)
                files.extend(got)
                skipped_files += skipped
            elif p.lower().endswith(MANIFEST_EXTS):
                files.append(p)
            else:
                skipped_files += 1
        return "tree", files, skipped_files
    for p in paths:
        if not os.path.exists(p):
            raise ScanFatal(f"source not found: {p}")
    return fmt, list(paths), 0


def scan_main(argv=None) -> int:
    from . import logging as glog

    args = build_scan_parser().parse_args(argv)
    glog.setup(args.log_level)
    try:
        fmt, src_paths, skipped_files = _resolve_sources(args.paths,
                                                         args.format)
        if not src_paths:
            raise ScanFatal("no manifest files found under "
                            f"{args.paths}")
        tiers_given = sum(1 for t in (args.backplane, args.grpc) if t)
        if tiers_given > 1:
            raise ScanFatal("--backplane and --grpc are exclusive")
        if args.preview_constraint and tiers_given:
            raise ScanFatal("--preview-constraint runs on the "
                            "in-process tier only (the candidate must "
                            "be compiled locally)")
        if args.backplane:
            tier = BackplaneTier(args.backplane, args.timeout)
        elif args.grpc:
            tier = GrpcTier(args.grpc, args.timeout)
        else:
            tier = build_inproc_tier(
                args.policies, aot_dir=args.aot_dir,
                compile_cache_dir=args.compile_cache_dir,
                decision_cache=args.decision_cache,
                timeout_s=args.timeout,
                preview_template=(
                    _load_manifest_file(args.preview_template)
                    if args.preview_template else None),
                preview_constraint=(
                    _load_manifest_file(args.preview_constraint)
                    if args.preview_constraint else None))
    except ScanFatal as e:
        print(json.dumps({"error": str(e)}), file=sys.stderr)
        return 3
    loader = LoaderPool(fmt, src_paths, args.loaders,
                        encode=tier.wants_bytes)
    out = sys.stdout if args.output == "-" else open(args.output, "w")
    try:
        summary = run_scan(tier, loader, out,
                           batch_size=max(1, args.batch),
                           depth=max(1, args.depth),
                           dedupe_size=args.dedupe)
    finally:
        tier.close()
        if out is not sys.stdout:
            out.close()
    summary["format"] = fmt
    summary["skipped_files"] = skipped_files
    if args.preview_constraint:
        summary["preview"] = True
    if args.summary:
        with open(args.summary, "w") as f:
            json.dump(summary, f)
    rate = summary["manifests_per_sec"]
    print(f"fleet scan: {summary['manifests']} manifests "
          f"({summary['unique_evaluated']} unique evaluated, "
          f"{summary['deduped']} deduped) in {summary['wall_s']}s "
          f"[{rate}/s] via {summary['tier']} — "
          f"{summary['denied']} denied, {summary['errors']} errors, "
          f"{summary['skipped_docs']} non-k8s docs skipped",
          file=sys.stderr)
    print(json.dumps(summary), file=sys.stderr)
    return exit_code(summary)
