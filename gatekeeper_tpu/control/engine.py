"""One admission ENGINE process of the N-engine serving plane.

`python -m gatekeeper_tpu.control.engine --socket S --engine-id K
--device K ...` builds a full evaluation stack — TpuDriver pinned to
ONE chip, Client, MicroBatcher, validation/mutation handlers — behind a
BackplaneEngine on its own Unix socket. Frontends route reviews across
all engines (least-load, request-hash fallback), so `admission_rps`
scales with chips instead of saturating one GIL + one device queue.

The engine owns no kube connection and no controllers: the PRIMARY
process (engine 0) watches the cluster and replicates every library
mutation here over L frames — templates, constraints, synced data,
mutators — applied through this process's own Client, which bumps its
own generation per op, keeping the decision cache's generation keys
coherent with the library this engine actually evaluates. A fresh or
healed engine receives a full `sync` op first (library snapshot +
inventory tree + mutator sources, with stale extras diffed away).

The PR 3-6 serving contracts hold unchanged because the serving path IS
BackplaneEngine: deadlines pin at frame receipt, `--admission-max-queue`
arrives pre-divided by the engine count (the bound stays global), shed
and decision metrics accumulate in this process's registry and relay to
the primary over M-frame polls, and SIGTERM drains the batcher.
"""

from __future__ import annotations

import argparse
import signal
import sys
import threading

from . import logging as glog
from . import metrics
from . import trace as gtrace
from .backplane import BackplaneEngine
from .webhook import (
    DEFAULT_WEBHOOK_TIMEOUT_S,
    MicroBatcher,
    MutationHandler,
    NamespaceLabelHandler,
    ValidationHandler,
)

log = glog.logger("engine")


def _template_kind(tpl: dict) -> str:
    spec = tpl.get("spec") or {}
    names = ((spec.get("crd") or {}).get("spec") or {}).get("names") or {}
    return names.get("kind") or (tpl.get("metadata") or {}).get("name", "")


class LibrarySink:
    """Applies replicated library ops to this engine's Client (and
    MutationSystem). Ops arrive in send order on the primary's one
    control connection; `sync` reconciles the full state — replaying
    the snapshot through normal ingestion (semantic-equal dedupe makes
    it idempotent) and removing templates/constraints/mutators the
    primary no longer carries."""

    def __init__(self, client, mutation_system=None):
        self.client = client
        self.mutation_system = mutation_system
        # flips on the first full sync: the backplane answers admission
        # Q frames NOT_READY until then, so a respawned engine never
        # issues verdicts from its empty pre-sync library
        self.synced = False
        # serving-knob receiver (adaptive controller fan-out): main()
        # binds this to the batcher's set_knobs so the primary's
        # actuations keep every engine's batch economics coherent
        self.on_knobs = None

    def __call__(self, op: dict) -> None:
        kind = op.get("op")
        obj = op.get("obj")
        client = self.client
        if kind == "sync":
            self._sync(op)
        elif kind == "knobs":
            if self.on_knobs is not None:
                self.on_knobs(obj or {})
        elif kind == "add_template":
            client.add_template(obj)
        elif kind == "remove_template":
            client.remove_template(obj)
        elif kind == "add_constraint":
            client.add_constraint(obj)
        elif kind == "remove_constraint":
            client.remove_constraint(obj)
        elif kind == "add_data":
            client.add_data(obj)
        elif kind == "remove_data":
            client.remove_data(obj)
        elif kind == "upsert_mutator":
            if self.mutation_system is not None:
                self.mutation_system.upsert(obj)
        elif kind == "remove_mutator":
            if self.mutation_system is not None:
                self.mutation_system.remove(
                    (obj.get("kind"), (obj.get("metadata") or {})
                     .get("name")))
        else:
            raise ValueError(f"unknown library op {kind!r}")

    def _sync(self, op: dict) -> None:
        client = self.client
        lib = op.get("library") or {}
        snap_kinds = {_template_kind(t)
                      for t in lib.get("templates") or []}
        snap_cons = {((c.get("kind") or ""),
                      ((c.get("metadata") or {}).get("name") or ""))
                     for c in lib.get("constraints") or []}
        # drop extras FIRST (a removed template must stop enforcing
        # even though the snapshot replay would never mention it)
        index = client.library_index()
        for tk, names in index.items():
            if tk not in snap_kinds:
                try:
                    client.remove_template(client.get_template(tk))
                except Exception:
                    pass
                continue
            for name in names:
                if (tk, name) not in snap_cons:
                    try:
                        client.remove_constraint(
                            client.get_constraint(tk, name))
                    except Exception:
                        pass
        out = client.restore_library(lib)
        data = op.get("data")
        n_data = 0
        driver = getattr(client, "driver", None)
        if data and hasattr(driver, "inventory_restore"):
            n_data = driver.inventory_restore(data)
        ms = self.mutation_system
        if ms is not None:
            keep = set()
            for m in op.get("mutators") or []:
                ms.upsert(m)
                keep.add(((m.get("kind") or ""),
                          ((m.get("metadata") or {}).get("name") or "")))
            for mut in ms.mutators():
                if tuple(mut.id) not in keep:
                    ms.remove(mut.id)
        self.synced = True
        log.info("library synced",
                 details={**out, "data_objects": n_data})


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="gatekeeper-tpu-engine")
    p.add_argument("--socket", required=True)
    p.add_argument("--engine-id", default="1")
    p.add_argument("--device", type=int, default=-1,
                   help="index into jax.devices() this engine pins its "
                        "evaluation to; -1 = the platform default")
    p.add_argument("--serve", default="admit,admitlabel",
                   help="operations this engine evaluates "
                        "(admit,admitlabel,mutate,auditslice)")
    p.add_argument("--audit-shard-id", type=int, default=-1,
                   help="this process's slice of the sharded audit "
                        "plane (with --serve auditslice); -1 = unsharded")
    p.add_argument("--audit-shard-count", type=int, default=1)
    p.add_argument("--log-level", default="INFO")
    p.add_argument("--log-denies", action="store_true")
    p.add_argument("--fail-closed", action="store_true")
    p.add_argument("--mutation-fail-closed", default="unset",
                   choices=["true", "false", "unset"])
    p.add_argument("--mutation-max-iterations", type=int, default=10)
    p.add_argument("--mutation-batch-max-wait", type=float, default=0.005)
    p.add_argument("--admission-max-queue", type=int, default=4096,
                   help="THIS engine's share of the global bound (the "
                        "primary divides --admission-max-queue by the "
                        "engine count)")
    p.add_argument("--admission-default-timeout", type=float,
                   default=DEFAULT_WEBHOOK_TIMEOUT_S)
    p.add_argument("--admission-decision-cache", type=int, default=4096)
    p.add_argument("--exempt-namespace", action="append", default=[])
    p.add_argument("--trace-sample-rate", type=float, default=0.0)
    p.add_argument("--trace-slow-threshold", type=float, default=1.0)
    p.add_argument("--fault-injection", default="")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    glog.setup(args.log_level)
    metrics.set_engine_id(args.engine_id)
    gtrace.TRACER.configure(args.trace_sample_rate,
                            args.trace_slow_threshold)
    if args.fault_injection:
        from ..utils.faults import FAULTS

        FAULTS.configure(args.fault_injection)
    from ..client import Backend
    from ..ir import TpuDriver
    from ..target import K8sValidationTarget

    serve = frozenset(s for s in args.serve.split(",") if s)
    driver = TpuDriver(device=args.device if args.device >= 0 else None)
    client = Backend(driver).new_client([K8sValidationTarget()])
    fail_closed = args.fail_closed
    validation = ns_label = mutation = mutation_system = None
    if "admit" in serve:
        batcher = MicroBatcher(client,
                               max_queue=args.admission_max_queue)
        validation = ValidationHandler(
            client, kube=None, batcher=batcher,
            log_denies=args.log_denies, fail_closed=fail_closed,
            default_timeout=args.admission_default_timeout,
            decision_cache_size=args.admission_decision_cache)
        ns_label = NamespaceLabelHandler(tuple(args.exempt_namespace))
    if "mutate" in serve:
        from ..mutation import MutationSystem

        mutation_system = MutationSystem(
            max_iterations=args.mutation_max_iterations)
        mutation = MutationHandler(
            mutation_system, kube=None,
            fail_closed=(fail_closed if args.mutation_fail_closed
                         == "unset"
                         else args.mutation_fail_closed == "true"),
            batch_max_wait=args.mutation_batch_max_wait,
            max_queue=args.admission_max_queue,
            default_timeout=args.admission_default_timeout)
    auditor = None
    if "auditslice" in serve:
        from .audit import AuditSliceServer

        # scope this driver's review building to its consistent-hash
        # slice; the leader feeds it owned objects + the broadcast set
        if args.audit_shard_id >= 0 and args.audit_shard_count > 1:
            driver.set_audit_shard(args.audit_shard_id,
                                   args.audit_shard_count)
        auditor = AuditSliceServer(client,
                                   shard_id=max(args.audit_shard_id, 0),
                                   shard_count=args.audit_shard_count)
    sink = LibrarySink(client, mutation_system)
    if validation is not None:
        # replicated serving-knob ops land on this engine's batcher
        # (unknown keys dropped: a version-skewed primary must not
        # TypeError the control stream)
        sink.on_knobs = lambda kn: validation.batcher.set_knobs(
            **{key: v for key, v in (kn or {}).items()
               if key in ("max_wait", "max_batch", "max_queue")})
    if auditor is not None:
        # a respawned shard must 503 sweeps until its slice resync
        # lands — an empty-library sweep would silently drop this
        # partition's violations from the composed round
        auditor.ready = lambda: sink.synced
    # saturation probes, same set the primary registers: this child has
    # no /metrics server, so the probes refresh on each M-frame stats
    # poll instead and the gauges relay to the primary (engine-labeled
    # series — per-chip duty cycle / queue depth must be readable off
    # the primary's one scrape, not just for engine 0)
    if validation is not None:
        metrics.register_saturation_probe(
            "admission-queue",
            lambda b=validation.batcher: metrics.report_queue_depth(
                "admission", b.pending(), engine=args.engine_id))
    if mutation is not None:
        metrics.register_saturation_probe(
            "mutation-queue",
            lambda b=mutation.batcher: metrics.report_queue_depth(
                "mutation", b.pending(), engine=args.engine_id))
    if hasattr(driver, "duty_cycle"):
        metrics.register_saturation_probe(
            "engine-duty-cycle",
            lambda: metrics.report_duty_cycle(driver.duty_cycle()))

    def stats_source():
        metrics.run_saturation_probes()
        return metrics.engine_stats_snapshot()

    engine = BackplaneEngine(
        args.socket, validation=validation, ns_label=ns_label,
        mutation=mutation,
        default_timeout=args.admission_default_timeout,
        engine_id=args.engine_id,
        library_sink=sink,
        stats_source=stats_source,
        auditor=auditor)
    # refuse admission until the supervisor's first full sync lands:
    # the frontends' router fails those requests over to synced engines
    engine.ready_check = lambda: sink.synced
    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())
    engine.start()
    # long-lived-server GC tuning, same rationale as the frontends
    import gc

    gc.collect()
    gc.freeze()
    print("READY", flush=True)
    try:
        stop.wait()
    finally:
        engine.stop()
        # mirror teardown for the probes registered above: a final
        # stats poll racing shutdown must relay zeros, not the last
        # burst's depth/duty (the supervisor also zeroes the relayed
        # series on child death — this covers the graceful path)
        for probe in ("admission-queue", "mutation-queue",
                      "engine-duty-cycle"):
            metrics.unregister_saturation_probe(probe)
        if validation is not None:
            metrics.report_queue_depth("admission", 0,
                                       engine=args.engine_id)
        if mutation is not None:
            metrics.report_queue_depth("mutation", 0,
                                       engine=args.engine_id)
        metrics.report_duty_cycle(0.0)
    return 0


if __name__ == "__main__":
    sys.exit(main())
