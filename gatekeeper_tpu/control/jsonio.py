"""Admission hot-path JSON: orjson when available, stdlib fallback.

The serving plane decodes and encodes one JSON document per admission
request; on the reference line that cost is Go's encoding/json, here it
is the difference between ~50us (orjson's C encoder) and ~250us
(stdlib) per review at webhook payload sizes. orjson is OPTIONAL — the
container image may not carry it — so every entry point degrades to the
stdlib implementation with identical semantics:

  loads(bytes|str)      -> obj        (raises ValueError subtypes)
  dumps_bytes(obj)      -> bytes      (compact separators)
  canonical_bytes(obj)  -> bytes      (sorted keys, compact — the
                                       decision-cache request hash must
                                       not depend on dict insert order)
"""

from __future__ import annotations

import json
from typing import Any

try:  # pragma: no cover - exercised only where orjson is installed
    import orjson as _orjson
except ImportError:  # the baked image has no orjson; stdlib serves
    _orjson = None

BACKEND = "orjson" if _orjson is not None else "stdlib"


if _orjson is not None:  # pragma: no cover - image-dependent
    def loads(data) -> Any:
        return _orjson.loads(data)

    def dumps_bytes(obj: Any) -> bytes:
        return _orjson.dumps(obj)

    def canonical_bytes(obj: Any) -> bytes:
        return _orjson.dumps(obj, default=str,
                             option=_orjson.OPT_SORT_KEYS)
else:
    def loads(data) -> Any:
        if isinstance(data, (bytes, bytearray, memoryview)):
            data = bytes(data).decode("utf-8")
        return json.loads(data)

    def dumps_bytes(obj: Any) -> bytes:
        return json.dumps(obj, separators=(",", ":")).encode("utf-8")

    def canonical_bytes(obj: Any) -> bytes:
        return json.dumps(obj, separators=(",", ":"), sort_keys=True,
                          default=str).encode("utf-8")
