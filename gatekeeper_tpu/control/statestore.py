"""Durable state snapshots: warm restart without a cluster re-scan.

Every restart used to be a full cold start — re-list the whole cluster,
re-encode the inventory, re-ingest and re-compile every template. The
reference Gatekeeper survives pod churn because its state is cheap to
rebuild; here the expensive-to-rebuild states are snapshotted to disk and
restored on boot:

  * ``vocab``     — the strtab intern table (ops/strtab.py). Restoring it
    keeps interned string ids — and therefore the vocab-capacity buckets
    that XLA program shapes depend on — stable across restarts, so the
    persistent compilation cache hits instead of recompiling.
  * ``library``   — the ingested template / constraint / mutator SOURCES
    (raw CRs). Re-ingested on boot so admission serves immediately
    instead of waiting for the first watch delivery; the controllers'
    level-triggered replay then dedupes via semantic-equal.
  * ``inventory`` — the audit's synced-inventory subtree (the driver's
    ``external`` data tree), the InventoryTracker's (uid, resourceVersion)
    state map, and the per-GVK watch-resume resourceVersions.
  * ``rows``      — the driver's encoded feature tensors per template
    kind (binary sidecar, numpy): adopted on the first warm audit when
    the candidate set still matches, skipping re-extraction entirely.

Snapshot files are versioned, checksummed, and written atomically
(write-to-temp + fsync + rename + directory fsync). Restore validates the
schema version and checksum; ANY corruption, staleness, or version skew
falls back to today's cold path — a bad snapshot must never crash-loop
the pod. The ``state.snapshot`` fault point (utils/faults.py) tears,
corrupts, or errors these files so the chaos suite can prove that.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from typing import Any, Callable, Optional

from ..utils import faults
from . import metrics
from .logging import logger

log = logger("statestore")

SCHEMA_VERSION = 1

# a snapshot older than this is treated as unusable (the cluster has
# drifted too far for the resume RVs to mean anything; the 410-gap diff
# would re-list everything anyway, i.e. a cold start with extra steps)
DEFAULT_MAX_AGE_S = 7 * 24 * 3600.0


class SnapshotError(Exception):
    pass


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:  # platforms without directory fds
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _apply_file_fault(mode: str, path: str) -> None:
    """Simulate on-disk damage for an armed state.snapshot fault."""
    if not os.path.exists(path):
        return
    size = os.path.getsize(path)
    if mode == "truncate":
        with open(path, "r+b") as f:
            f.truncate(max(0, size // 2))
    elif mode == "corrupt":
        with open(path, "r+b") as f:
            f.seek(max(0, size // 2))
            b = f.read(1) or b"\x00"
            f.seek(max(0, size // 2))
            f.write(bytes([b[0] ^ 0xFF]))


class StateStore:
    """Versioned, checksummed, atomically-written snapshot files in one
    state directory (``--state-dir``). JSON sections ride `save`/`load`;
    binary payloads (encoded rows, the inventory tree) ride
    `save_blob`/`load_blob` with the checksum in a JSON sidecar."""

    def __init__(self, state_dir: str, max_age_s: float = DEFAULT_MAX_AGE_S):
        self.dir = state_dir
        self.max_age_s = max_age_s
        os.makedirs(state_dir, exist_ok=True)

    def path(self, section: str) -> str:
        return os.path.join(self.dir, f"{section}.snapshot.json")

    def aot_dir(self) -> str:
        """Where the AOT serialized-program store (ir/aot.py) lives:
        colocated under the state dir so one volume carries both the
        warm-restart snapshots and the warm-boot device programs (the
        full deserialize-and-go restart path). The store itself manages
        the per-(backend, device-count, jax-version) subdirs."""
        return os.path.join(self.dir, "aot")

    def blob_path(self, section: str) -> str:
        return os.path.join(self.dir, f"{section}.snapshot.blob")

    # ----------------------------------------------------------------- save

    def _write_atomic(self, path: str, data: bytes) -> None:
        # disk-pressure chaos: state.disk simulates the volume itself
        # failing under us — ENOSPC (full) or EIO (device error) — as
        # the kernel would raise it, so every save path exercises its
        # previous-snapshot-kept contract against real errno shapes
        flt = faults.consume("state.disk", path=path)
        if flt is not None:
            errno_ = 5 if (flt[1] or "enospc") == "eio" else 28
            raise OSError(errno_, os.strerror(errno_), path + ".tmp")
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        _fsync_dir(self.dir)

    def _header(self, section: str, data: bytes,
                codec: Optional[str] = None) -> bytes:
        head = {
            "schema": SCHEMA_VERSION,
            "section": section,
            "created": time.time(),
            "sha256": hashlib.sha256(data).hexdigest(),
        }
        if codec:
            head["codec"] = codec
        return json.dumps(head).encode()

    def save(self, section: str, payload: Any) -> bool:
        """Atomically persist one JSON section as a header line (schema
        + checksum over the body bytes) followed by the body — one
        serialization pass, not a payload-inside-envelope double encode.
        Returns True when the file landed; a failed save leaves the
        previous snapshot intact (the temp file is never the live
        name)."""
        try:
            f = faults.consume("state.snapshot", op="save", section=section)
            if f is not None and f[0] in ("io-error", "raise", "error"):
                raise OSError(f"injected fault at state.snapshot ({f[0]})")
            body = json.dumps(payload, separators=(",", ":")).encode()
            self._write_atomic(self.path(section),
                               self._header(section, body) + b"\n" + body)
            if f is not None and f[0] in ("truncate", "corrupt"):
                _apply_file_fault(f[0], self.path(section))
        except Exception as e:
            metrics.report_snapshot("save", "error")
            log.error("snapshot save failed; previous snapshot kept",
                      details={"section": section, "error": str(e)})
            return False
        metrics.report_snapshot("save", "ok")
        metrics.report_snapshot_age(0.0)
        return True

    def save_blob(self, section: str, payload: Any,
                  codec: str = "pickle") -> bool:
        """Persist a serialized payload + checksum sidecar. The blob
        path exists for payloads JSON cannot carry efficiently: encoded
        feature tensors (numpy arrays; pickle, highest protocol) and
        the O(cluster) inventory tree (codec="marshal": ~2x faster
        C-native load, and restore latency IS the warm boot). marshal
        is OPT-IN because it silently flattens buffer objects like
        ndarrays to raw bytes — only callers whose payload is plain
        JSON-ish containers by construction may pass it; a cross-
        version marshal skew surfaces as a load error -> cold fallback.
        Trust note: the state dir is this pod's own volume, written
        only by this process — the checksum guards against corruption,
        not adversaries."""
        try:
            import marshal
            import pickle

            f = faults.consume("state.snapshot", op="save", section=section)
            if f is not None and f[0] in ("io-error", "raise", "error"):
                raise OSError(f"injected fault at state.snapshot ({f[0]})")
            if codec == "marshal":
                data = marshal.dumps(payload)
            else:
                codec = "pickle"
                data = pickle.dumps(payload,
                                    protocol=pickle.HIGHEST_PROTOCOL)
            self._write_atomic(self.blob_path(section), data)
            self._write_atomic(self.path(section),
                               self._header(section, data, codec=codec))
            if f is not None and f[0] in ("truncate", "corrupt"):
                _apply_file_fault(f[0], self.blob_path(section))
        except Exception as e:
            metrics.report_snapshot("save", "error")
            log.error("snapshot blob save failed; previous snapshot kept",
                      details={"section": section, "error": str(e)})
            return False
        metrics.report_snapshot("save", "ok")
        return True

    # ----------------------------------------------------------------- load

    def _read(self, section: str) -> Optional[tuple]:
        """(header, body_bytes) with schema/age validation; body is None
        for blob sidecars. Raises SnapshotError on anything that must
        route to the cold path."""
        path = self.path(section)
        f = faults.consume("state.snapshot", op="load", section=section)
        if f is not None:
            if f[0] in ("io-error", "raise", "error"):
                raise SnapshotError(
                    f"injected fault at state.snapshot ({f[0]})")
            _apply_file_fault(f[0], path)
            _apply_file_fault(f[0], self.blob_path(section))
        if not os.path.exists(path):
            return None
        try:
            with open(path, "rb") as fp:
                raw = fp.read()
        except OSError as e:
            raise SnapshotError(f"unreadable snapshot: {e}") from None
        head, sep, body = raw.partition(b"\n")
        try:
            header = json.loads(head)
        except ValueError as e:
            raise SnapshotError(f"corrupt snapshot header: {e}") from None
        if not isinstance(header, dict) or \
                header.get("schema") != SCHEMA_VERSION:
            raise SnapshotError(
                f"schema {header.get('schema') if isinstance(header, dict) else header!r} != {SCHEMA_VERSION}")
        # gklint: allow(clock) reason=created is a persisted epoch from another process lifetime; monotonic cannot span it
        age = time.time() - float(header.get("created") or 0)
        if self.max_age_s and age > self.max_age_s:
            raise SnapshotError(f"snapshot stale ({age:.0f}s old)")
        return header, (body if sep else None)

    def load(self, section: str) -> Optional[Any]:
        """Validated payload, or None when absent. Raises SnapshotError
        on corruption/staleness/skew — callers turn that into the cold
        path (and the `fallback` restore outcome), never a crash."""
        out = self._read(section)
        if out is None:
            return None
        header, body = out
        if body is None:
            raise SnapshotError("snapshot body missing")
        if hashlib.sha256(body).hexdigest() != header.get("sha256"):
            raise SnapshotError("checksum mismatch")
        try:
            return json.loads(body)
        except ValueError as e:
            raise SnapshotError(f"corrupt snapshot body: {e}") from None

    def load_blob(self, section: str) -> Optional[Any]:
        out = self._read(section)
        if out is None:
            return None
        header, _ = out
        path = self.blob_path(section)
        if not os.path.exists(path):
            raise SnapshotError("blob sidecar present but blob missing")
        with open(path, "rb") as fp:
            data = fp.read()
        if hashlib.sha256(data).hexdigest() != header.get("sha256"):
            raise SnapshotError("blob checksum mismatch")
        import marshal
        import pickle

        codec = header.get("codec") or "pickle"
        try:
            if codec == "marshal":
                return marshal.loads(data)
            return pickle.loads(data)
        except Exception as e:
            raise SnapshotError(f"blob unreadable: {e}") from None

    def age_s(self, section: str) -> Optional[float]:
        try:
            with open(self.path(section), "rb") as fp:
                head = fp.readline()
            # gklint: allow(clock) reason=persisted epoch stamp from a prior process lifetime; wall clock is the only shared base
            return time.time() - float(json.loads(head).get("created") or 0)
        except Exception:
            return None


class SnapshotManager:
    """Periodic + on-demand snapshotting over a StateStore.

    Providers are ``{section: callable -> payload | None}`` (None skips
    the section this round); ``blob_providers`` use the binary path.
    Snapshots run periodically (``--snapshot-interval``), on SIGTERM
    drain (Runtime.stop), and immediately on SIGHUP (save_now)."""

    def __init__(self, store: StateStore,
                 providers: dict[str, Callable[[], Any]],
                 blob_providers: Optional[dict] = None,
                 interval_s: float = 60.0,
                 blob_codecs: Optional[dict] = None):
        self.store = store
        self.providers = providers
        self.blob_providers = blob_providers or {}
        # per-section blob codec overrides (e.g. inventory -> marshal;
        # see save_blob for why marshal is opt-in)
        self.blob_codecs = blob_codecs or {}
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._kick = threading.Event()
        self._lock = threading.Lock()  # one snapshot pass at a time
        self._thread: Optional[threading.Thread] = None
        self.last_saved: Optional[float] = None

    def add_provider(self, section: str, fn: Callable[[], Any],
                     blob: bool = False,
                     codec: Optional[str] = None) -> None:
        """Register a section after construction (subsystems built
        later than the manager — e.g. the sharded-audit plane — attach
        their sections here instead of threading providers through
        Runtime.__init__ ordering)."""
        if blob:
            self.blob_providers[section] = fn
            if codec:
                self.blob_codecs[section] = codec
        else:
            self.providers[section] = fn

    def start(self) -> None:
        if self.interval_s <= 0:
            return
        self._thread = threading.Thread(target=self._loop,
                                        name="snapshots", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._kick.set()

    def kick(self) -> None:
        """Request an immediate snapshot (SIGHUP handler); safe from a
        signal context — the loop thread does the work."""
        self._kick.set()

    def save_now(self) -> int:
        """Run one snapshot pass synchronously; returns sections saved.
        Sections are captured one by one — vocab is captured LAST, after
        every other section INCLUDING the blobs (the encoded rows hold
        interned ids): the intern table is append-only, so a later
        capture is always a superset of whatever ids earlier sections
        reference; captured any earlier, rows re-extracted by a
        concurrent audit could reference ids the persisted vocab lacks
        and silently decode wrong after restore."""
        saved = 0

        def one(name, fn, save):
            try:
                payload = fn()
            except Exception as e:
                metrics.report_snapshot("save", "error")
                log.error("snapshot provider failed",
                          details={"section": name, "error": str(e)})
                return 0
            if payload is None:
                return 0
            return 1 if save(name, payload) else 0

        with self._lock:
            for name in sorted(self.providers):
                if name == "vocab":
                    continue
                saved += one(name, self.providers[name], self.store.save)
            for name in sorted(self.blob_providers):
                saved += one(
                    name, self.blob_providers[name],
                    lambda n, p: self.store.save_blob(
                        n, p, codec=self.blob_codecs.get(n, "pickle")))
            if "vocab" in self.providers:
                saved += one("vocab", self.providers["vocab"],
                             self.store.save)
        if saved:
            self.last_saved = time.monotonic()
            metrics.report_snapshot_age(0.0)
            log.info("state snapshot saved",
                     details={"sections": saved, "dir": self.store.dir})
        return saved

    def _loop(self) -> None:
        while not self._stop.is_set():
            self._kick.wait(self.interval_s)
            if self._stop.is_set():
                return
            self._kick.clear()
            try:
                self.save_now()
            except Exception as e:  # the snapshot loop must never die
                log.error("snapshot pass failed", details=str(e))
            if self.last_saved is not None:
                metrics.report_snapshot_age(
                    time.monotonic() - self.last_saved)


def restore_section(store: StateStore, section: str,
                    apply: Callable[[Any], Any],
                    blob: bool = False) -> bool:
    """Shared restore protocol: load one section, hand it to `apply`,
    and map every failure mode onto the restore metric — `ok` when
    applied, `missing` when no snapshot exists, `fallback` when the
    snapshot is corrupt/stale/unusable (the caller proceeds down the
    cold path; never raises)."""
    try:
        payload = store.load_blob(section) if blob else store.load(section)
    except SnapshotError as e:
        metrics.report_snapshot("restore", "fallback")
        log.warning("snapshot unusable; falling back to cold start",
                    details={"section": section, "error": str(e)})
        return False
    except Exception as e:
        metrics.report_snapshot("restore", "fallback")
        log.error("snapshot restore failed; falling back to cold start",
                  details={"section": section, "error": str(e)})
        return False
    if payload is None:
        metrics.report_snapshot("restore", "missing")
        return False
    try:
        apply(payload)
    except Exception as e:
        metrics.report_snapshot("restore", "fallback")
        log.error("snapshot apply failed; falling back to cold start",
                  details={"section": section, "error": str(e)})
        return False
    metrics.report_snapshot("restore", "ok")
    age = store.age_s(section)
    if age is not None:
        metrics.report_snapshot_age(age)
    log.info("snapshot restored", details={"section": section})
    return True
