from .audit import AuditManager
from .controllers import ControllerManager
from .kube import FakeKube, RestKubeClient
from .watch import WatchManager
from .webhook import (
    MicroBatcher,
    NamespaceLabelHandler,
    ValidationHandler,
    WebhookServer,
)

__all__ = [
    "AuditManager",
    "ControllerManager",
    "FakeKube",
    "MicroBatcher",
    "NamespaceLabelHandler",
    "RestKubeClient",
    "ValidationHandler",
    "WatchManager",
    "WebhookServer",
]
