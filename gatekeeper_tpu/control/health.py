"""Health endpoints.

Counterpart of the reference's healthz/readyz wiring (main.go:205-212:
a ping checker on /healthz and default-ready /readyz served on
--health-addr). /healthz answers 200 as soon as the server is up (the
process is alive); /readyz consults the registered readiness checks and
answers 503 with the failing check names until they all pass.
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

from .logging import logger

log = logger("health")


def parse_addr(addr: str) -> Optional[tuple[str, int]]:
    """":9090" / "0.0.0.0:9090" -> (host, port); None when disabled
    (empty or "0") or unparseable. Port 0 binds an EPHEMERAL port
    (tests use ":0" to avoid collisions)."""
    if not addr or addr == "0":
        return None
    host, _, port_s = addr.rpartition(":")
    try:
        port = int(port_s)
    except ValueError:
        return None
    if port < 0:
        return None
    return (host or "0.0.0.0", port)


class HealthServer:
    """/healthz + /readyz on --health-addr."""

    def __init__(self, host: str, port: int):
        self._checks: dict[str, Callable[[], bool]] = {}
        self._lock = threading.Lock()
        checks = self._checks
        lock = self._lock

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def do_GET(self):
                path = self.path.split("?", 1)[0].rstrip("/")
                if path == "/healthz":
                    self._reply(200, b"ok")
                    return
                if path == "/readyz":
                    with lock:
                        items = list(checks.items())
                    failing = []
                    for name, fn in items:
                        try:
                            if not fn():
                                failing.append(name)
                        except Exception:
                            failing.append(name)
                    if failing:
                        self._reply(503, ("not ready: "
                                          + ", ".join(failing)).encode())
                    else:
                        self._reply(200, b"ok")
                    return
                self._reply(404, b"not found")

            def _reply(self, code: int, body: bytes):
                self.send_response(code)
                self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def add_readiness(self, name: str, check: Callable[[], bool]) -> None:
        with self._lock:
            self._checks[name] = check

    def start(self) -> None:
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="health", daemon=True)
        self._thread.start()
        log.info("health endpoints serving",
                 details={"port": self.port})

    def shutdown(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
