"""Health endpoints.

Counterpart of the reference's healthz/readyz wiring (main.go:205-212:
a ping checker on /healthz and default-ready /readyz served on
--health-addr). /healthz consults the registered LIVENESS watchdogs
(none registered = plain ping): a wedged micro-batch flusher or a dead
audit loop fails liveness so k8s restarts the pod. /readyz consults the
registered readiness checks (including the kube-write circuit breaker)
and answers 503 with the failing check names until they all pass.
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

from .logging import logger

log = logger("health")


def parse_addr(addr: str) -> Optional[tuple[str, int]]:
    """":9090" / "0.0.0.0:9090" -> (host, port); None when disabled
    (empty or "0") or unparseable. Port 0 binds an EPHEMERAL port
    (tests use ":0" to avoid collisions)."""
    if not addr or addr == "0":
        return None
    host, _, port_s = addr.rpartition(":")
    try:
        port = int(port_s)
    except ValueError:
        return None
    if port < 0:
        return None
    return (host or "0.0.0.0", port)


class HealthServer:
    """/healthz + /readyz on --health-addr."""

    def __init__(self, host: str, port: int):
        self._checks: dict[str, Callable[[], bool]] = {}
        self._live: dict[str, Callable[[], bool]] = {}
        # /debug/<name> providers (shared registry with the metrics
        # server — an audit-only pod without a scrape port still
        # exposes its flight recorder through the health port)
        self._debug: dict[str, Callable] = {}
        self._lock = threading.Lock()
        checks = self._checks
        live = self._live
        debug = self._debug
        lock = self._lock

        def failing(items) -> list[str]:
            out = []
            for name, fn in items:
                try:
                    if not fn():
                        out.append(name)
                except Exception:
                    out.append(name)
            return out

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def do_GET(self):
                path, _, query = self.path.partition("?")
                path = path.rstrip("/")
                if path.startswith("/debug/"):
                    with lock:
                        providers = dict(debug)
                    if providers:
                        from .metrics import render_debug
                        body, code = render_debug(
                            providers, path[len("/debug/"):], query)
                        self._reply(code, body, "application/json")
                        return
                if path == "/healthz":
                    # liveness watchdog: a wedged flusher/audit loop
                    # fails liveness so k8s restarts the pod (a process
                    # that is up but not serving is NOT alive)
                    with lock:
                        items = list(live.items())
                    bad = failing(items)
                    if bad:
                        log.error("liveness check failing",
                                  details={"checks": bad})
                        self._reply(503, ("not alive: "
                                          + ", ".join(bad)).encode())
                    else:
                        self._reply(200, b"ok")
                    return
                if path == "/readyz":
                    with lock:
                        items = list(checks.items())
                    bad = failing(items)
                    if bad:
                        self._reply(503, ("not ready: "
                                          + ", ".join(bad)).encode())
                    else:
                        self._reply(200, b"ok")
                    return
                self._reply(404, b"not found")

            def _reply(self, code: int, body: bytes,
                       ctype: str = "text/plain"):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def add_readiness(self, name: str, check: Callable[[], bool]) -> None:
        with self._lock:
            self._checks[name] = check

    def add_liveness(self, name: str, check: Callable[[], bool]) -> None:
        """Register a liveness watchdog: /healthz answers 503 while any
        registered check fails, so the kubelet restarts a wedged pod."""
        with self._lock:
            self._live[name] = check

    def add_debug(self, name: str, provider: Callable) -> None:
        """Mount a /debug/<name> provider (same callable contract as
        metrics.serve's debug_providers: raw query string in, JSON-
        serializable object out)."""
        with self._lock:
            self._debug[name] = provider

    def start(self) -> None:
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="health", daemon=True)
        self._thread.start()
        log.info("health endpoints serving",
                 details={"port": self.port})

    def shutdown(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
