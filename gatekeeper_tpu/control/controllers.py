"""Reconcile control plane: template / constraint / config / sync.

Counterparts of the reference pkg/controller/* reconcilers, level-
triggered over the watch manager:

  * TemplateController (constrainttemplate_controller.go:176-388): on
    upsert — CreateCRD + AddTemplate into the Client, create/update the
    per-template constraint CRD in-cluster, register a dynamic watch for
    the generated constraint kind; on delete — remove watch then template;
    byPod status + finalizer handling; TearDownState at shutdown
    (:466-556).
  * ConstraintController (constraint_controller.go:155-278): events for
    any generated kind arrive via the shared registrar with the GVK packed
    into the request (util/pack.go); AddConstraint/RemoveConstraint with
    semantic-equal dedupe inside the Client, byPod status, per-action
    constraint-count metrics.
  * ConfigController (config_controller.go:165-287): singleton
    gatekeeper-system/config; computes the syncOnly GVK set, wipes driver
    data, ReplaceWatch on the sync registrar, replays cached objects.
  * SyncController (sync_controller.go:128-210): AddData/RemoveData per
    event into the driver inventory with sync metrics.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Optional

from ..client import Client, ClientError
from ..target.handler import WipeData
from . import metrics
from .kube import GVK, FakeKube, KubeError, NotFound, WatchEvent, gvk_of
from .logging import logger
from .resilience import NotLeader, guarded_status_update
from .util import (
    DEFAULT_ENFORCEMENT_ACTION,
    VALID_ENFORCEMENT_ACTIONS,
    by_pod_status_unchanged,
    set_by_pod_status,
)
from .watch import WatchManager

TEMPLATE_GVK = ("templates.gatekeeper.sh", "v1beta1", "ConstraintTemplate")
CONFIG_GVK = ("config.gatekeeper.sh", "v1alpha1", "Config")
CRD_GVK = ("apiextensions.k8s.io", "v1beta1", "CustomResourceDefinition")
CONSTRAINT_GROUP = "constraints.gatekeeper.sh"
MUTATOR_GROUP = "mutations.gatekeeper.sh"
MUTATOR_GVKS = tuple((MUTATOR_GROUP, "v1alpha1", kind)
                     for kind in ("Assign", "AssignMetadata", "ModifySet"))
FINALIZER = "finalizers.gatekeeper.sh/constrainttemplate"

log = logger("controller")


def _retry_status_update(kube, obj: dict, attempts: int = 5) -> None:
    """Status write with conflict retry (reference retry loops, e.g.
    constrainttemplate_controller.go:548-555), riding the shared
    breaker-aware protocol in resilience.guarded_status_update."""

    def refresh(cur_obj):
        try:
            cur = kube.get(gvk_of(cur_obj),
                           (cur_obj.get("metadata") or {}).get("name")
                           or "",
                           (cur_obj.get("metadata") or {}).get("namespace")
                           or "")
        except KubeError:
            return None
        cur["status"] = cur_obj.get("status")
        return cur

    guarded_status_update(kube, obj, refresh, attempts)


class _Worker:
    """Queue-draining reconcile loop shared by all controllers."""

    def __init__(self, name: str, registrar, handle) -> None:
        self.name = name
        self.registrar = registrar
        self.handle = handle
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop,
                                        name=f"ctrl-{name}", daemon=True)

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def join(self, timeout=2.0):
        self._thread.join(timeout)

    def idle(self) -> bool:
        """No queued events AND no popped-but-unhandled event:
        unfinished_tasks increments at put() and only decrements at the
        loop's task_done() after handle() returns, so there is no
        window where an event is in flight but invisible."""
        return self.registrar.events.unfinished_tasks == 0

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                event = self.registrar.events.get(timeout=0.1)
            except Exception:
                continue
            try:
                self.handle(event)
            except Exception as e:  # reconcile must never die
                log.error(f"{self.name}: reconcile error: {e}",
                          event_type=event.type)
            finally:
                self.registrar.events.task_done()


# ------------------------------------------------------------------ template


class TemplateController:
    def __init__(self, kube, opa: Client, wm: WatchManager,
                 constraint_ctrl: "ConstraintController"):
        self.kube = kube
        self.opa = opa
        self.wm = wm
        self.constraint_ctrl = constraint_ctrl
        self.registrar = wm.registrar("constrainttemplate")
        self.worker = _Worker("constrainttemplate", self.registrar,
                              self.reconcile)
        self._tracked: dict[str, GVK] = {}  # template name -> constraint gvk

    def start(self) -> None:
        self.registrar.add_watch(TEMPLATE_GVK)
        self.worker.start()

    def reconcile(self, event: WatchEvent) -> None:
        obj = event.object
        name = (obj.get("metadata") or {}).get("name") or ""
        if event.type == "DELETED":
            self._handle_delete_by_name(name)
            return
        try:
            obj = self.kube.get(TEMPLATE_GVK, name)
        except NotFound:
            self._handle_delete_by_name(name)
            return
        if (obj.get("metadata") or {}).get("deletionTimestamp"):
            self._handle_delete_by_name(name)
            self._remove_finalizer(obj)
            return
        t0 = time.monotonic()
        try:
            crd = self.opa.create_crd(obj)
            self.opa.add_template(obj)
        except ClientError as e:
            log.error("template ingestion failed", template_name=name,
                      details=str(e))
            metrics.report_template_ingestion("error", time.monotonic() - t0)
            self._write_status(obj, created=False, errors=[str(e)])
            return
        kind = crd["spec"]["names"]["kind"]
        self._ensure_finalizer(obj)
        # create/update the generated constraint CRD in-cluster
        try:
            self.kube.apply(crd)
        except NotLeader:
            # defensive: controllers normally ride the UNGATED guard
            # (byPod slots are pod-owned, CRD applies idempotent), but
            # tolerate an operator wiring a fenced client
            pass
        except KubeError as e:
            log.warning("constraint CRD apply failed", template_name=name,
                        details=str(e))
        gvk = (CONSTRAINT_GROUP, "v1beta1", kind)
        # unwrap a resilience.GuardedKube proxy for the fake check
        if isinstance(getattr(self.kube, "inner", self.kube), FakeKube):
            self.kube.register_kind(gvk, namespaced=False)
        self._tracked[name] = gvk
        self.constraint_ctrl.registrar.add_watch(gvk)
        metrics.report_template_ingestion("ok", time.monotonic() - t0)
        metrics.report_constraint_templates("active", len(self._tracked))
        self._write_status(obj, created=True)

    def note_quarantine(self, kind: str, reason: Optional[str]) -> None:
        """Driver callback (TpuDriver.on_quarantine): surface a device-
        path quarantine — or its recovery (reason=None) — on the owning
        ConstraintTemplate's byPod status, so `kubectl get` shows WHY a
        template's reviews run degraded."""
        # snapshot: this runs on a driver notification thread while the
        # controller worker mutates _tracked (dict-changed-size race)
        name = next((n for n, g in list(self._tracked.items())
                     if g[2] == kind), None)
        if name is None:
            return
        try:
            obj = self.kube.get(TEMPLATE_GVK, name)
        except KubeError:
            return
        errors = [f"device path quarantined: {reason} (interpreter "
                  "fallback serving reviews)"] if reason else None
        self._write_status(obj, created=True, errors=errors)

    def _handle_delete_by_name(self, name: str) -> None:
        gvk = self._tracked.pop(name, None)
        if gvk is not None:
            self.constraint_ctrl.registrar.remove_watch(gvk)
            templ = {
                "apiVersion": "templates.gatekeeper.sh/v1beta1",
                "kind": "ConstraintTemplate",
                "metadata": {"name": name},
                "spec": {"crd": {"spec": {"names": {"kind": gvk[2]}}},
                         "targets": [{"target":
                                      "admission.k8s.gatekeeper.sh",
                                      "rego": "package x\nviolation[{\"msg\": \"\"}] { false }"}]},
            }
            try:
                self.opa.remove_template(templ)
            except ClientError:
                pass
            metrics.report_constraint_templates("active", len(self._tracked))

    def _ensure_finalizer(self, obj: dict) -> None:
        meta = obj.setdefault("metadata", {})
        fins = meta.setdefault("finalizers", [])
        if FINALIZER not in fins:
            fins.append(FINALIZER)
            try:
                self.kube.update(obj)
            except KubeError:
                pass

    def _remove_finalizer(self, obj: dict) -> None:
        meta = obj.setdefault("metadata", {})
        fins = [f for f in meta.get("finalizers") or [] if f != FINALIZER]
        meta["finalizers"] = fins
        try:
            self.kube.update(obj)
        except KubeError:
            pass

    def _write_status(self, obj: dict, created: bool,
                      errors: Optional[list] = None) -> None:
        entry: dict[str, Any] = {"observedGeneration":
                                 (obj.get("metadata") or {}).get("generation", 0)}
        if errors:
            entry["errors"] = [{"message": e} for e in errors]
        if (by_pod_status_unchanged(obj, entry)
                and (obj.get("status") or {}).get("created") == created):
            return
        set_by_pod_status(obj, entry)
        obj.setdefault("status", {})["created"] = created
        _retry_status_update(self.kube, obj)

    def teardown(self) -> None:
        """Scrub finalizers at shutdown (reference TearDownState)."""
        try:
            for obj in self.kube.list(TEMPLATE_GVK):
                self._remove_finalizer(obj)
        except KubeError:
            pass


# ---------------------------------------------------------------- constraint


class ConstraintController:
    def __init__(self, kube, opa: Client, wm: WatchManager,
                 validate_actions: bool = True):
        self.kube = kube
        self.opa = opa
        self.wm = wm
        self.registrar = wm.registrar("constraint")
        self.worker = _Worker("constraint", self.registrar, self.reconcile)
        self.validate_actions = validate_actions
        self._counts: dict[str, set] = {a: set()
                                        for a in VALID_ENFORCEMENT_ACTIONS}
        self._counts["unrecognized"] = set()

    def start(self) -> None:
        self.worker.start()

    def reconcile(self, event: WatchEvent) -> None:
        obj = event.object
        kind = obj.get("kind") or ""
        name = (obj.get("metadata") or {}).get("name") or ""
        uid = f"{kind}/{name}"
        if event.type != "DELETED":
            # Level-triggered: act on the watch cache (informer-cache
            # analog, constraint_controller.go:174-188), never the possibly
            # stale event payload — a MODIFIED drained after DELETED must
            # not resurrect the constraint. The cache is always at least as
            # new as any drained event and costs no API round-trip.
            ns = (obj.get("metadata") or {}).get("namespace") or ""
            cur = self.wm.cached_get(gvk_of(obj), name, ns)
            if cur is None:
                event = WatchEvent("DELETED", obj)
            else:
                obj = cur
        if event.type == "DELETED":
            try:
                self.opa.remove_constraint(obj)
            except ClientError:
                pass
            for bucket in self._counts.values():
                bucket.discard(uid)
            self._report()
            log.info("constraint deleted", constraint_kind=kind,
                     constraint_name=name)
            return
        spec = obj.get("spec") or {}
        action = spec.get("enforcementAction") or DEFAULT_ENFORCEMENT_ACTION
        recognized = action in VALID_ENFORCEMENT_ACTIONS
        if not recognized and self.validate_actions:
            for bucket in self._counts.values():
                bucket.discard(uid)
            self._counts["unrecognized"].add(uid)
            self._report()
            self._status(obj, enforced=False,
                         errors=[f"unrecognized enforcement action {action}"])
            return
        try:
            self.opa.add_constraint(obj)
        except ClientError as e:
            self._status(obj, enforced=False, errors=[str(e)])
            return
        for bucket in self._counts.values():
            bucket.discard(uid)
        self._counts.setdefault(action, set()).add(uid)
        self._report()
        self._status(obj, enforced=True)
        log.info("constraint added", constraint_kind=kind,
                 constraint_name=name, constraint_action=action)

    def _report(self) -> None:
        for action, bucket in self._counts.items():
            metrics.report_constraints(action, len(bucket))

    def _status(self, obj: dict, enforced: bool,
                errors: Optional[list] = None) -> None:
        entry: dict[str, Any] = {"enforced": enforced,
                                 "observedGeneration":
                                 (obj.get("metadata") or {}).get("generation",
                                                                 0)}
        if errors:
            entry["errors"] = [{"message": e} for e in errors]
        # Skip no-op writes: an unconditional update emits a MODIFIED event
        # back into our own queue and loops forever.
        if by_pod_status_unchanged(obj, entry):
            return
        set_by_pod_status(obj, entry)
        _retry_status_update(self.kube, obj)


# -------------------------------------------------------------------- config


class ConfigController:
    CONFIG_NAME = "config"
    CONFIG_NAMESPACE = "gatekeeper-system"

    def __init__(self, kube, opa: Client, wm: WatchManager,
                 sync_ctrl: "SyncController"):
        self.kube = kube
        self.opa = opa
        self.wm = wm
        self.sync_ctrl = sync_ctrl
        self.registrar = wm.registrar("config")
        self.worker = _Worker("config", self.registrar, self.reconcile)
        self.traces: list[dict] = []

    def start(self) -> None:
        self.registrar.add_watch(CONFIG_GVK)
        self.worker.start()

    def reconcile(self, event: WatchEvent) -> None:
        obj = event.object
        meta = obj.get("metadata") or {}
        # only the singleton is honored (config_controller.go:176-179)
        if (meta.get("name"), meta.get("namespace")) != (
                self.CONFIG_NAME, self.CONFIG_NAMESPACE):
            log.warning("ignoring config: only %s/%s is honored" % (
                self.CONFIG_NAMESPACE, self.CONFIG_NAME))
            return
        spec = obj.get("spec") or {}
        if event.type == "DELETED":
            spec = {}
        sync = (spec.get("sync") or {}).get("syncOnly") or []
        gvks = []
        for entry in sync:
            gvks.append((entry.get("group") or "", entry.get("version") or "",
                         entry.get("kind") or ""))
        self.traces = (spec.get("validation") or {}).get("traces") or []
        # wipe inventory, swap watches, replay cached data
        # (config_controller.go:228-287)
        try:
            self.opa.remove_data(WipeData())
        except ClientError:
            pass
        self.sync_ctrl.registrar.replace_watches(gvks)
        metrics.report_watch_manager(len(self.wm.watched_gvks()), len(gvks))
        log.info("config synced", details={"syncOnly": [list(g) for g in gvks]})


# ---------------------------------------------------------------------- sync


class SyncController:
    def __init__(self, kube, opa: Client, wm: WatchManager):
        self.kube = kube
        self.opa = opa
        self.registrar = wm.registrar("sync")
        self.worker = _Worker("sync", self.registrar, self.reconcile)
        self._synced: dict[str, set] = {}

    def start(self) -> None:
        self.worker.start()

    def reconcile(self, event: WatchEvent) -> None:
        obj = event.object
        kind = obj.get("kind") or ""
        meta = obj.get("metadata") or {}
        uid = f"{kind}/{meta.get('namespace') or ''}/{meta.get('name')}"
        t0 = time.monotonic()
        if event.type == "DELETED":
            try:
                self.opa.remove_data(obj)
            except ClientError:
                pass
            self._synced.setdefault(kind, set()).discard(uid)
        else:
            try:
                self.opa.add_data(obj)
                self._synced.setdefault(kind, set()).add(uid)
            except ClientError as e:
                log.error("sync failed", resource_kind=kind, details=str(e))
                return
        metrics.report_sync_duration(time.monotonic() - t0)
        metrics.report_last_sync()
        for k, bucket in self._synced.items():
            metrics.report_sync("active", k, len(bucket))


# ------------------------------------------------------------------- mutator


class MutatorController:
    """Reconciles Assign / AssignMetadata / ModifySet CRs into the
    MutationSystem (reference pkg/controller/mutators/*): level-triggered
    upsert with semantic-equal dedupe inside the system, ingestion
    metrics, per-kind mutator gauges, and the schema-conflict quarantine
    surfaced as a byPod status condition — on EVERY mutator whose
    conflict state flips, not just the event's subject (a new mutator
    can quarantine an old one, and a deletion can clear it)."""

    def __init__(self, kube, system, wm: WatchManager):
        self.kube = kube
        self.system = system
        self.wm = wm
        self.registrar = wm.registrar("mutator")
        self.worker = _Worker("mutator", self.registrar, self.reconcile)

    def start(self) -> None:
        for gvk in MUTATOR_GVKS:
            self.registrar.add_watch(gvk)
        self.worker.start()

    def reconcile(self, event: WatchEvent) -> None:
        from ..mutation import MutationError

        obj = event.object
        kind = obj.get("kind") or ""
        name = (obj.get("metadata") or {}).get("name") or ""
        if event.type != "DELETED":
            # level-triggered: act on the watch cache, never a possibly
            # stale event payload (same rationale as ConstraintController)
            cur = self.wm.cached_get(gvk_of(obj), name, "")
            if cur is None:
                event = WatchEvent("DELETED", obj)
            else:
                obj = cur
        if event.type == "DELETED":
            changed = self.system.remove((kind, name))
            metrics.report_mutators(self.system.counts())
            self._refresh_statuses(changed - {(kind, name)})
            log.info("mutator deleted", mutator_kind=kind,
                     mutator_name=name)
            return
        t0 = time.monotonic()
        try:
            mutator, changed = self.system.upsert(obj)
        except MutationError as e:
            metrics.report_mutator_ingestion("error", time.monotonic() - t0)
            log.error("mutator ingestion failed", mutator_kind=kind,
                      mutator_name=name, details=str(e))
            self._status(obj, enforced=False, errors=[str(e)])
            return
        metrics.report_mutator_ingestion("ok", time.monotonic() - t0)
        metrics.report_mutators(self.system.counts())
        reason = self.system.conflicts().get(mutator.id)
        self._status(obj, enforced=reason is None,
                     errors=[reason] if reason else None)
        self._refresh_statuses(changed - {mutator.id})
        log.info("mutator ingested", mutator_kind=kind, mutator_name=name,
                 quarantined=bool(reason))

    def _refresh_statuses(self, ids: set) -> None:
        conflicts = self.system.conflicts()
        for kind, name in sorted(ids):
            # the registrar already watches every mutator GVK: serve the
            # object from the informer cache, no API round-trip
            obj = self.wm.cached_get((MUTATOR_GROUP, "v1alpha1", kind),
                                     name, "")
            if obj is None:
                continue
            reason = conflicts.get((kind, name))
            self._status(obj, enforced=reason is None,
                         errors=[reason] if reason else None)

    def _status(self, obj: dict, enforced: bool,
                errors: Optional[list] = None) -> None:
        entry: dict[str, Any] = {"enforced": enforced,
                                 "observedGeneration":
                                 (obj.get("metadata") or {}).get("generation",
                                                                 0)}
        if errors:
            entry["errors"] = [{"message": e} for e in errors]
        if by_pod_status_unchanged(obj, entry):
            return
        set_by_pod_status(obj, entry)
        _retry_status_update(self.kube, obj)


# ------------------------------------------------------------------- manager


class ControllerManager:
    """Wires the four controllers over one watch manager (reference
    pkg/controller/controller.go:41-60 AddToManager)."""

    def __init__(self, kube, opa: Client, wm: Optional[WatchManager] = None,
                 validate_actions: bool = True, mutation_system=None):
        self.kube = kube
        self.opa = opa
        self.wm = wm or WatchManager(kube)
        # client state is rebuilt from the API on start (controller.go:43)
        self.opa.reset()
        self.constraint_ctrl = ConstraintController(
            kube, opa, self.wm, validate_actions)
        self.template_ctrl = TemplateController(
            kube, opa, self.wm, self.constraint_ctrl)
        self.sync_ctrl = SyncController(kube, opa, self.wm)
        self.config_ctrl = ConfigController(kube, opa, self.wm,
                                            self.sync_ctrl)
        self.mutator_ctrl = None
        if mutation_system is not None:
            self.mutator_ctrl = MutatorController(kube, mutation_system,
                                                  self.wm)

    def start(self) -> None:
        self.constraint_ctrl.start()
        self.template_ctrl.start()
        self.sync_ctrl.start()
        self.config_ctrl.start()
        if self.mutator_ctrl is not None:
            self.mutator_ctrl.start()

    def drain(self, timeout: float = 10.0) -> None:
        """Wait until every reconcile queue has no queued OR in-flight
        event (tests; unfinished_tasks covers the popped-but-unhandled
        gap that a queue-empty check plus settle-sleep raced). The
        all-idle predicate reads each queue at a different instant, so
        a later-checked worker can emit into an already-checked queue
        mid-pass — require two consecutive idle passes: a cascade in
        that window leaves its source task unfinished into the second
        pass, or its target queued."""
        deadline = time.monotonic() + timeout
        workers = [self.template_ctrl.worker, self.constraint_ctrl.worker,
                   self.sync_ctrl.worker, self.config_ctrl.worker]
        if self.mutator_ctrl is not None:
            workers.append(self.mutator_ctrl.worker)
        stable = 0
        while time.monotonic() < deadline:
            if all(w.idle() for w in workers):
                stable += 1
                if stable >= 2:
                    return
            else:
                stable = 0
            time.sleep(0.002)

    def stop(self) -> None:
        workers = [self.template_ctrl.worker, self.constraint_ctrl.worker,
                   self.sync_ctrl.worker, self.config_ctrl.worker]
        if self.mutator_ctrl is not None:
            workers.append(self.mutator_ctrl.worker)
        for w in workers:
            w.stop()
        # JOIN before teardown: a worker mid-get() still delivers one
        # last event, and a template reconcile racing the finalizer
        # scrub would re-add what teardown just removed. Generous
        # timeout — a reconcile stuck in status-update retries must get
        # a chance to finish; if it still hasn't, proceed loudly (best-
        # effort teardown beats hanging the shutdown forever)
        for w in workers:
            w.join(timeout=15.0)
            if w._thread.is_alive():
                log.error(f"worker {w.name} still running at shutdown; "
                          "finalizer teardown may race it")
        self.template_ctrl.teardown()
        self.wm.stop()
