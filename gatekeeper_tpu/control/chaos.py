"""Seeded chaos orchestration + crash-consistency verification.

PRs 3/4/7/16/18 each shipped a one-off chaos leg (engine kill, kill -9
mid-sweep, shard kill, controller-under-kill) scattered across five
test files — all clean deaths, none replayable, no single place that
asserts the plane's global invariants after an arbitrary fault
sequence. This module is that place:

  * `ChaosSchedule.generate(seed, ...)` — a fault schedule derived
    DETERMINISTICALLY from one integer seed: same seed, same kinds,
    same targets, same offsets, same parameters. Every run prints the
    seed so any failure replays exactly (`tools/chaos_verify.py
    --seed N`).
  * `ChaosOrchestrator` — executes a schedule against live plane
    handles (the three supervisors, the FakeKube stub, the fault
    injector, /dev/shm) and keeps a ledger of what actually fired,
    exposed on `/debug/chaos` together with the injector's
    armed/fired snapshots.
  * `Verifier` — the crash-consistency checks run after every
    schedule: zero unanswered admissions with every verdict matching
    the stance contract, post-convergence audit round bit-equal to a
    clean oracle, at most one lease holder ever writing status
    (fencing), no leaked processes/fds//dev/shm segments, and no
    stale lifecycle gauges (the gklint gauge-teardown family list,
    checked at RUNTIME after teardown).

The schedule is deterministic; the plane's *response* (which child was
alive to kill, how long recovery took) is not — that asymmetry is the
point: one fixed sequence of inputs, invariants over any interleaving
of outcomes.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from ..utils.faults import FAULTS
from . import shm
from .logging import logger

log = logger("chaos")


# ------------------------------------------------------------- schedule


@dataclass(frozen=True)
class FaultAction:
    """One scheduled fault: fire `kind` against child slot `target`
    (resolved modulo the live children at fire time) at `t` seconds
    after the schedule starts. `param`/`count` carry kind-specific
    shape (an errno flavor, an armed-fault fire budget)."""

    t: float
    kind: str
    target: int = 0
    param: str = ""
    count: int = 1

    def to_dict(self) -> dict:
        return {"t": round(self.t, 3), "kind": self.kind,
                "target": self.target, "param": self.param,
                "count": self.count}


# the full fault surface; schedules draw from a subset of these kinds.
# process-level kinds act on supervisor children (SIGKILL / SIGSTOP);
# the rest arm utils/faults points or poke the FakeKube / /dev/shm.
SURFACE = (
    "engine.kill", "engine.pause",
    "frontend.kill", "frontend.pause",
    "shard.kill", "shard.pause",
    "wire.reset", "wire.truncate", "wire.slow",
    "backplane.error",
    "kube.flap", "kube.stall",
    "lease.steal", "lease.expire",
    "state.disk", "state.corrupt",
    "shm.corrupt", "shm.unlink",
)

_PARAMS = {
    "wire.slow": ("0.02", "0.05"),
    "state.disk": ("enospc", "eio"),
    "kube.flap": ("429", "410", "503"),
    "state.corrupt": ("corrupt", "truncate"),
}


class ChaosSchedule:
    """A deterministic fault schedule: (seed, surface, n, horizon) in,
    the same ordered FaultAction list out, every time."""

    def __init__(self, seed: int, actions: list):
        self.seed = int(seed)
        self.actions = list(actions)

    @classmethod
    def generate(cls, seed: int, surface=SURFACE, n_actions: int = 8,
                 horizon_s: float = 10.0,
                 max_target: int = 4) -> "ChaosSchedule":
        """Derive a schedule from one integer seed. All randomness
        comes from a private Random(seed) — nothing reads the global
        RNG or the clock, so replay is exact by construction."""
        rng = random.Random(int(seed))
        surface = tuple(surface)
        actions = []
        for _ in range(n_actions):
            kind = surface[rng.randrange(len(surface))]
            params = _PARAMS.get(kind)
            actions.append(FaultAction(
                t=round(rng.uniform(0.0, horizon_s), 3),
                kind=kind,
                target=rng.randrange(max_target),
                param=params[rng.randrange(len(params))] if params
                else "",
                count=1 + rng.randrange(3),
            ))
        actions.sort(key=lambda a: (a.t, a.kind, a.target))
        return cls(seed, actions)

    def to_dict(self) -> dict:
        return {"seed": self.seed,
                "actions": [a.to_dict() for a in self.actions]}


# ---------------------------------------------------------- plane handles


@dataclass
class PlaneHandles:
    """Duck-typed handles the orchestrator acts through. Any of them
    may be None — a schedule against a partial plane simply records
    the skipped actions in the ledger (the verifier does NOT treat a
    skip as a violation; an all-skip schedule exercises nothing)."""

    frontends: Any = None     # FrontendSupervisor
    engines: Any = None       # EngineSupervisor
    audit_shards: Any = None  # AuditShardSupervisor
    kube: Any = None          # FakeKube
    shm_prefix: str = "gk-bp-"


# --------------------------------------------------------- orchestrator


class ChaosOrchestrator:
    """Executes one schedule against live plane handles, recording a
    ledger of what fired. `run()` is synchronous (the verify harness
    owns the load threads); `start()` wraps it in a thread."""

    def __init__(self, plane: PlaneHandles, schedule: ChaosSchedule,
                 time_scale: float = 1.0):
        self.plane = plane
        self.schedule = schedule
        # compresses/stretches the schedule's t offsets (CI runs the
        # same schedule faster than a soak would)
        self.time_scale = time_scale
        self.ledger: list[dict] = []
        self._ledger_lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._t0: Optional[float] = None

    # ------------------------------------------------------------- run

    def run(self) -> list[dict]:
        global _ACTIVE
        _ACTIVE = self
        log.info("chaos schedule starting",
                 details={"seed": self.schedule.seed,
                          "actions": len(self.schedule.actions)})
        self._t0 = time.monotonic()
        for action in self.schedule.actions:
            due = self._t0 + action.t * self.time_scale
            delay = due - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            try:
                detail = self._fire(action)
            except Exception as e:  # a fault action must never kill
                detail = {"error": repr(e)}  # the orchestrator itself
            self._log(action, detail)
        return list(self.ledger)

    def start(self) -> threading.Thread:
        self._thread = threading.Thread(target=self.run,
                                        name="chaos-orchestrator",
                                        daemon=True)
        self._thread.start()
        return self._thread

    def join(self, timeout: Optional[float] = None) -> None:
        if self._thread is not None:
            self._thread.join(timeout)

    def _log(self, action: FaultAction, detail: dict) -> None:
        ent = dict(action.to_dict())
        ent["at_s"] = round(time.monotonic() - self._t0, 3)
        ent["detail"] = detail
        with self._ledger_lock:
            self.ledger.append(ent)

    # ----------------------------------------------------------- actions

    @staticmethod
    def _slot(sup, target: int):
        """Resolve a schedule target index onto the supervisor's live
        children (modulo), or None when none are up."""
        pids = sup.child_pids() if sup is not None else {}
        if not pids:
            return None
        keys = sorted(pids)
        return keys[target % len(keys)]

    def _fire(self, a: FaultAction) -> dict:
        p = self.plane
        domain, _, verb = a.kind.partition(".")
        if domain == "engine":
            k = self._slot(p.engines, a.target)
            if k is None:
                return {"skipped": "no live engine child"}
            (p.engines.kill_engine if verb == "kill"
             else p.engines.pause_engine)(k)
            return {"engine": k, "signal":
                    "SIGKILL" if verb == "kill" else "SIGSTOP"}
        if domain == "frontend":
            k = self._slot(p.frontends, a.target)
            if k is None:
                return {"skipped": "no live frontend"}
            (p.frontends.kill_child if verb == "kill"
             else p.frontends.pause_child)(k)
            return {"worker": k, "signal":
                    "SIGKILL" if verb == "kill" else "SIGSTOP"}
        if domain == "shard":
            k = self._slot(p.audit_shards, a.target)
            if k is None:
                return {"skipped": "no live audit shard"}
            (p.audit_shards.kill_engine if verb == "kill"
             else p.audit_shards.pause_engine)(k)
            return {"shard": k, "signal":
                    "SIGKILL" if verb == "kill" else "SIGSTOP"}
        if domain == "wire":
            FAULTS.inject("backplane.wire", mode=verb, param=a.param,
                          count=a.count)
            return {"armed": f"backplane.wire:{verb}",
                    "count": a.count}
        if a.kind == "backplane.error":
            FAULTS.inject("backplane.engine", mode="error",
                          count=a.count)
            return {"armed": "backplane.engine:error", "count": a.count}
        if a.kind == "kube.flap":
            # an apiserver flap is not one error, it is WEATHER: rate-
            # limited writes, 410s on lists racing compaction, both at
            # a probability for a bounded budget, plus a real etcd-
            # style compaction so resumed watches see the 410 path
            code = a.param or "429"
            FAULTS.inject("kube.write", mode="error", param=code,
                          rate=0.5, count=a.count * 4)
            FAULTS.inject("kube.list", mode="error", param="410",
                          rate=0.5, count=a.count * 2)
            if p.kube is not None and hasattr(p.kube, "compact"):
                p.kube.compact()
            return {"armed": f"kube.write:{code} + kube.list:410",
                    "compacted": p.kube is not None}
        if a.kind == "kube.stall":
            FAULTS.inject("kube.list", mode="sleep", param="0.5",
                          sleep_s=0.5, count=a.count)
            return {"armed": "kube.list:sleep:0.5", "count": a.count}
        if domain == "lease":
            FAULTS.inject("kube.lease", mode=verb, count=1)
            return {"armed": f"kube.lease:{verb}"}
        if a.kind == "state.disk":
            FAULTS.inject("state.disk", mode="error",
                          param=a.param or "enospc", count=a.count)
            return {"armed": f"state.disk:{a.param}", "count": a.count}
        if a.kind == "state.corrupt":
            FAULTS.inject("state.snapshot",
                          mode=a.param or "corrupt", count=1)
            return {"armed": f"state.snapshot:{a.param}"}
        if domain == "shm":
            segs = shm.list_segments(p.shm_prefix)
            if not segs:
                return {"skipped": "no live shm segments"}
            name = segs[a.target % len(segs)]
            if verb == "unlink":
                shm.unlink(name)
                return {"unlinked": name}
            # stamp past the ring header region so the damage lands in
            # record space, not the allocator bookkeeping
            ok = shm.corrupt_segment(name, offset=64)
            return {"corrupted": name, "ok": ok}
        return {"skipped": f"unknown kind {a.kind}"}

    # ------------------------------------------------------------ debug

    def snapshot(self) -> dict:
        with self._ledger_lock:
            ledger = list(self.ledger)
        return {
            "seed": self.schedule.seed,
            "schedule": self.schedule.to_dict()["actions"],
            "ledger": ledger,
            "faults": {
                "armed": FAULTS.armed_snapshot(),
                "fired": FAULTS.fired_snapshot(),
            },
        }


# the most recent orchestrator, for /debug/chaos. With no schedule ever
# run the endpoint still answers with the injector's armed/fired state
# (an operator game-daying with GATEKEEPER_TPU_FAULTS sees what fired).
_ACTIVE: Optional[ChaosOrchestrator] = None


def debug_snapshot(query: str = "") -> dict:
    if _ACTIVE is not None:
        return _ACTIVE.snapshot()
    return {
        "seed": None,
        "schedule": [],
        "ledger": [],
        "faults": {
            "armed": FAULTS.armed_snapshot(),
            "fired": FAULTS.fired_snapshot(),
        },
    }


# --------------------------------------------------------- leak baseline


class LeakBaseline:
    """Before/after resource snapshot for the leak invariant: child
    pids (every tracked child must be DEAD after teardown), /dev/shm
    segments under the plane's prefix (must all be unlinked), and this
    process's fd count (bounded growth — reconnect churn may hold a
    few, a leak per request would not stay under the slack)."""

    def __init__(self, plane: PlaneHandles, fd_slack: int = 16):
        self.plane = plane
        self.fd_slack = fd_slack
        self.pids: set = set()
        self.fds = 0
        self.shm_before: set = set()

    @staticmethod
    def _fd_count() -> int:
        try:
            return len(os.listdir("/proc/self/fd"))
        except OSError:
            return 0

    def capture(self) -> "LeakBaseline":
        self.fds = self._fd_count()
        self.shm_before = set(shm.list_segments(self.plane.shm_prefix))
        return self

    def track_children(self) -> None:
        """Record every live child pid (call after boot AND after the
        schedule — respawned children get new pids)."""
        for sup in (self.plane.frontends, self.plane.engines,
                    self.plane.audit_shards):
            if sup is not None:
                self.pids.update(sup.child_pids().values())

    def violations(self) -> list[str]:
        out = []
        for pid in sorted(self.pids):
            try:
                os.kill(pid, 0)
            except OSError:
                continue  # dead (or not ours): not leaked
            out.append(f"leaked process: child pid {pid} still alive "
                       "after teardown")
        # only segments BORN during this run count: a stale segment
        # from an earlier crashed process is real debt, but not this
        # schedule's leak (sweep_stale owns that cleanup)
        after = set(shm.list_segments(self.plane.shm_prefix))
        for name in sorted(after - self.shm_before):
            out.append(f"leaked /dev/shm segment after teardown: "
                       f"{name}")
        fds = self._fd_count()
        if fds > self.fds + self.fd_slack:
            out.append(f"fd growth {self.fds} -> {fds} exceeds slack "
                       f"{self.fd_slack} (leaked sockets/pipes)")
        return out


# ------------------------------------------------------- fencing records


class RecordingKube:
    """Kube wrapper for the fencing invariant: forwards every call to
    the inner client, and records each SUCCESSFUL status write as
    (t_monotonic, identity, lease holder at write time) into a shared
    log. The verifier then asserts every status write was made by the
    then-current lease holder — the at-most-one-writer fence."""

    def __init__(self, inner, identity: str, writes: list,
                 lease_name: str = "gatekeeper-tpu-leader",
                 lease_namespace: str = "gatekeeper-system"):
        self._inner = inner
        self._identity = identity
        self._writes = writes  # shared, append-only
        self._lease_name = lease_name
        self._lease_ns = lease_namespace

    def _holder(self) -> str:
        try:
            lease = self._inner.get(
                ("coordination.k8s.io", "v1", "Lease"),
                self._lease_name, self._lease_ns)
            return (lease.get("spec") or {}).get("holderIdentity") or ""
        except Exception:
            return ""

    def update(self, obj, subresource: str = ""):
        out = self._inner.update(obj, subresource=subresource)
        if subresource == "status":
            self._writes.append((time.monotonic(), self._identity,
                                 self._holder()))
        return out

    def __getattr__(self, name):
        return getattr(self._inner, name)


# ------------------------------------------------------------- verifier

# fallback copy of the gklint lifecycle gauge families, used only when
# tools.gklint is not importable at runtime (installed package without
# the repo checkout); the import path is authoritative
_LIFECYCLE_GAUGES_FALLBACK = (
    "gatekeeper_tpu_queue_depth",
    "gatekeeper_tpu_device_duty_cycle",
    "gatekeeper_tpu_backplane_inflight",
    "gatekeeper_tpu_backplane_ring_fill_ratio",
    "gatekeeper_tpu_audit_stream_pending_events",
    "gatekeeper_tpu_slo_burn_rate",
    "gatekeeper_tpu_respawn_backoff_seconds",
    "gatekeeper_tpu_crashloop_breaker",
)


def lifecycle_gauge_names() -> tuple:
    """The gklint gauge-teardown family list, imported at runtime so
    the dynamic stale-gauge check and the static lint can never drift
    apart."""
    try:
        from tools.gklint.gauge_teardown import LIFECYCLE_GAUGE_NAMES
        return tuple(sorted(LIFECYCLE_GAUGE_NAMES))
    except ImportError:
        return _LIFECYCLE_GAUGES_FALLBACK


@dataclass
class CheckResult:
    name: str
    violations: list = field(default_factory=list)
    detail: dict = field(default_factory=dict)
    skipped: str = ""

    @property
    def ok(self) -> bool:
        return not self.violations


class Verifier:
    """Crash-consistency checks, one method per global invariant. Each
    returns (and records) a CheckResult; `report()` renders the whole
    run. A check against an absent subsystem records itself skipped
    with the reason — never silently."""

    def __init__(self):
        self.results: list[CheckResult] = []

    def _add(self, r: CheckResult) -> CheckResult:
        self.results.append(r)
        return r

    # 1 -------------------------------------------------------- answers

    def check_admissions(self, submitted: int, answered: dict,
                         errors: list,
                         fail_closed: bool = False) -> CheckResult:
        """Every submitted admission got exactly one AdmissionReview
        envelope, and every verdict matches the stance contract: a
        stance answer (status.code 503, issued when the engine was
        unreachable) must carry allowed == (not fail_closed); a real
        verdict carries a boolean `allowed` and never the NOT_READY
        internal status."""
        r = CheckResult("admissions",
                        detail={"submitted": submitted,
                                "answered": len(answered),
                                "transport_errors": len(errors)})
        for i, err in list(errors)[:5]:
            r.violations.append(
                f"admission {i} unanswered (transport error: {err})")
        if len(errors) > 5:
            r.violations.append(
                f"... and {len(errors) - 5} more transport errors")
        if len(answered) + len(errors) < submitted:
            r.violations.append(
                f"{submitted - len(answered) - len(errors)} admissions "
                "vanished without an answer OR an error")
        stance = 0
        for uid, (status, body) in answered.items():
            resp = (body or {}).get("response") or {}
            if resp.get("uid") != uid:
                r.violations.append(
                    f"admission {uid}: envelope uid mismatch "
                    f"({resp.get('uid')!r})")
                continue
            allowed = resp.get("allowed")
            if not isinstance(allowed, bool):
                r.violations.append(
                    f"admission {uid}: non-boolean allowed "
                    f"({allowed!r})")
                continue
            code = ((resp.get("status") or {}).get("code")
                    if isinstance(resp.get("status"), dict) else None)
            if code == 599:
                r.violations.append(
                    f"admission {uid}: internal NOT_READY status "
                    "leaked to an HTTP caller")
            elif code in (503, 504):
                stance += 1
                if allowed is not (not fail_closed):
                    r.violations.append(
                        f"admission {uid}: stance answer allowed="
                        f"{allowed} contradicts fail_closed="
                        f"{fail_closed}")
        r.detail["stance_answers"] = stance
        return self._add(r)

    # 2 ----------------------------------------------------- audit oracle

    def check_audit_bitequal(self, chaotic: Any,
                             oracle: Any) -> CheckResult:
        """The post-convergence audit round (sharded plane, after the
        schedule and every respawn/resync settled) must be BIT-EQUAL
        to a clean single-process oracle over the same cluster state:
        canonical-JSON equality, not set-similarity — a re-swept
        orphaned partition that double-counts or drops one violation
        fails here."""
        r = CheckResult("audit_bitequal")
        a = json.dumps(chaotic, sort_keys=True, default=str)
        b = json.dumps(oracle, sort_keys=True, default=str)
        r.detail["bytes"] = len(a)
        if a != b:
            r.violations.append(
                "post-convergence audit round differs from the clean "
                f"oracle ({len(a)} vs {len(b)} canonical bytes)")
        return self._add(r)

    # 3 --------------------------------------------------------- fencing

    def check_fencing(self, writes: list,
                      writers: Optional[set] = None) -> CheckResult:
        """At most one lease holder ever writes status. RecordingKube
        entries are (t, identity, holder-at-write-time); the violation
        is a write by one CANDIDATE while a DIFFERENT candidate held
        the lease — two fenced writers live at once. A holder outside
        `writers` (a fault-injected thief, or the brief stale window
        before the deposed candidate's next renew tick notices) never
        has a second writer behind it, so it is recorded in the detail
        but is not a violation; with writers=None every mismatch is."""
        r = CheckResult("lease_fencing",
                        detail={"status_writes": len(writes)})
        mismatches = 0
        for t, identity, holder in writes:
            if identity == holder:
                continue
            mismatches += 1
            if writers is None or holder in writers:
                r.violations.append(
                    f"status write by {identity!r} at t={t:.3f} while "
                    f"lease holder was {holder!r}")
        r.detail["holder_mismatches"] = mismatches
        return self._add(r)

    # 4 ----------------------------------------------------------- leaks

    def check_leaks(self, baseline: LeakBaseline) -> CheckResult:
        r = CheckResult("resource_leaks",
                        detail={"tracked_pids": len(baseline.pids)})
        r.violations.extend(baseline.violations())
        return self._add(r)

    # 5 ---------------------------------------------------- stale gauges

    def check_stale_gauges(self) -> CheckResult:
        """After full plane teardown every lifecycle-bound gauge series
        (the gklint gauge-teardown families, read at runtime) must be
        zero: a non-zero series is a dead component still exporting."""
        from . import metrics

        r = CheckResult("stale_gauges")
        families = lifecycle_gauge_names()
        r.detail["families"] = len(families)
        for name in families:
            for labels, value in sorted(metrics.gauge_series(name)
                                        .items()):
                if value:
                    r.violations.append(
                        f"stale gauge after teardown: {name}"
                        f"{dict(zip(('labels',), (labels,)))} = "
                        f"{value}")
        return self._add(r)

    # ------------------------------------------------------------ report

    def violation_count(self) -> int:
        return sum(len(r.violations) for r in self.results)

    def report(self) -> dict:
        return {
            "checks": [
                {"name": r.name, "ok": r.ok, "skipped": r.skipped,
                 "violations": r.violations, "detail": r.detail}
                for r in self.results
            ],
            "invariant_violations": self.violation_count(),
        }
