"""Shared-memory admission backplane rings.

PR 13's saturation scrape proved the serving plane edge-bound: device
duty cycle 0.07 with micro-batches sealing on max_wait at fill 0.013
while the engine sustains ~6k batched reviews/s per chip. Part of the
remaining edge cost is pure byte motion — every review was framed and
copied twice across the Unix-socket backplane (frontend sendall ->
kernel -> engine recv -> payload slice). This module removes the
payload from the socket entirely:

    frontend process                       engine process
    ┌───────────────────┐   descriptor    ┌──────────────────┐
    │ HTTP accept/parse │ ──(rid,off,len)─►│ memoryview slice │
    │ body -> REQ ring ─┼───── UDS ───────┼─► jsonio.loads   │
    │ REPLY ring -> HTTP│◄──(rid,off,len)──┼── envelope bytes │
    └───────────────────┘                 └──────────────────┘
            └────────── mmap'd shared memory ──────────┘

Each frontend OWNS one request ring and one reply ring
(`multiprocessing.shared_memory`, i.e. /dev/shm): review bytes are
written into the request ring at accept time, the Q frame shrinks to a
(rid, offset, length) descriptor, and the engine parses the review
straight out of the mapped ring — zero payload copies across the
backplane. Responses ride the reply ring the same way (the engine is
that ring's writer). The SOCKET stays the ordering / wakeup / failure
channel; the rings carry only payload bytes.

Concurrency model (deliberately asymmetric — it is what makes the ring
safe without cross-process locks):

  * the WRITER owns all allocation state (head/tail are plain Python
    ints in the writing process, guarded by a process-local lock);
  * the READER communicates exactly one thing back: a one-byte DONE
    flag per record (single-byte stores are atomic; a stale read just
    delays slot reuse by one reclaim pass);
  * records are reclaimed in FIFO allocation order by scanning DONE
    flags from the tail, so out-of-order release (engine pool threads,
    HTTP response threads) is absorbed with bounded head-of-line
    blocking rather than corruption;
  * when a burst outruns the reader (no contiguous space under the
    watermark), `alloc` returns None and the caller falls back to the
    inline-payload frame — the accept loop NEVER blocks on ring space.

Lifecycle rides the frontend supervisor contract: deterministic names
(`gk-bp-<supervisor pid>-w<slot>-{q,r}`) are created at frontend spawn,
unlinked on clean exit, and swept by the supervisor before a respawn
(a SIGKILLed frontend cannot unlink its own segments). The engine
attaches on the H-frame handshake, answers an A-frame ack (descriptors
are only sent after the ack), and detaches when the connection dies —
failing that frontend's in-flight requests exactly as before.
"""

from __future__ import annotations

import os
import struct
import threading
from collections import deque
from typing import Optional

try:  # the container may lack /dev/shm or the module (exotic platforms)
    from multiprocessing import shared_memory as _shm
except ImportError:  # pragma: no cover - stdlib module, but stay honest
    _shm = None

# record header: u32 payload length | u8 state | 3 pad. Only the state
# byte is cross-process (reader -> writer); the length is a debugging
# aid. Payload follows the header, 8-byte aligned.
REC_HDR = 8
_LEN = struct.Struct("!I")
ST_BUSY = 1
ST_DONE = 2

# one record may claim at most this fraction of the ring: a single
# monster review must not evict the whole burst into the inline path
MAX_ITEM_FRACTION = 0.25
# allocation watermark: keep this much headroom so release lag under a
# burst degrades into occasional inline fallbacks, not boundary thrash
WATERMARK = 0.9375


def supported() -> bool:
    return _shm is not None


def _align(n: int) -> int:
    return (n + 7) & ~7


def create(name: str, size: int):
    """Create (replacing any stale same-named segment) a ring segment."""
    if _shm is None:
        raise OSError("multiprocessing.shared_memory unavailable")
    unlink(name)
    return _shm.SharedMemory(name=name, create=True, size=size)


def attach(name: str):
    if _shm is None:
        raise OSError("multiprocessing.shared_memory unavailable")
    seg = _shm.SharedMemory(name=name)
    # CPython registers segments with the resource tracker on ATTACH
    # too (bpo-39959): without this unregister, an attaching process's
    # exit would WARN about — and worse, unlink — rings its peer still
    # owns. The creator's registration is the one that should stand.
    try:
        from multiprocessing import resource_tracker

        resource_tracker.unregister(seg._name, "shared_memory")
    except Exception:
        pass
    return seg


def unlink(name: str) -> None:
    """Remove a segment by name, from any process; missing is fine
    (the supervisor sweeps a SIGKILLed frontend's rings this way)."""
    if _shm is None:
        return
    try:
        seg = _shm.SharedMemory(name=name)
    except (OSError, ValueError):
        return
    try:
        seg.unlink()
    except OSError:
        pass
    finally:
        _close_quiet(seg)


# segments that could not unmap because a slice was still exported
# (an in-flight response mid-send at teardown): parked here so their
# finalizer never re-raises from GC; retried on the next park
_GRAVEYARD: list = []


def _close_quiet(seg) -> None:
    """Close a segment tolerating exported memoryviews: a slice still
    held by an in-flight response keeps the mapping alive until GC —
    parking a page beats raising into a teardown path."""
    for parked in _GRAVEYARD[:]:
        try:
            parked.close()
            _GRAVEYARD.remove(parked)
        except (BufferError, OSError):
            pass
    try:
        seg.close()
    except BufferError:
        _GRAVEYARD.append(seg)
    except OSError:
        pass


class RingWriter:
    """The allocating side of one ring (frontend for the request ring,
    engine for the reply ring). All state process-local except payload
    bytes and the per-record DONE flags."""

    def __init__(self, seg):
        self.seg = seg
        self.buf = seg.buf
        self.size = len(seg.buf)
        self.max_item = int(self.size * MAX_ITEM_FRACTION) - REC_HDR
        self._limit = int(self.size * WATERMARK)
        self._lock = threading.Lock()
        self._head = 0  # virtual (monotonic) offsets; phys = v % size
        self._tail = 0
        # FIFO of (virt_off, padded_len, hdr_phys_or_None): None marks a
        # wrap gap (the unusable remainder before a wrapped record)
        self._recs: deque = deque()
        self.allocs = 0
        self.fallbacks = 0

    # -- allocation ---------------------------------------------------

    def _reclaim_locked(self) -> None:
        while self._recs:
            _virt, plen, hdr = self._recs[0]
            if hdr is not None and self.buf[hdr + 4] != ST_DONE:
                break
            self._recs.popleft()
            self._tail += plen

    def append(self, data) -> Optional[int]:
        """Write one payload; returns its physical offset for the
        descriptor, or None when the ring is out of space / the item
        exceeds the per-item cap (caller sends the inline frame)."""
        n = len(data)
        need = _align(REC_HDR + n)
        if n > self.max_item:
            with self._lock:
                self.fallbacks += 1
            return None
        with self._lock:
            self._reclaim_locked()
            used = self._head - self._tail
            phys = self._head % self.size
            gap = 0
            if phys + need > self.size:
                gap = self.size - phys  # record never straddles the end
                phys = 0
            if used + gap + need > self._limit:
                self.fallbacks += 1
                return None
            if gap:
                self._recs.append((self._head, gap, None))
                self._head += gap
            hdr = phys
            _LEN.pack_into(self.buf, hdr, n)
            self.buf[hdr + 4] = ST_BUSY
            self._recs.append((self._head, need, hdr))
            self._head += need
            self.allocs += 1
        off = hdr + REC_HDR
        self.buf[off:off + n] = data
        return off

    def cancel(self, off: int) -> None:
        """Release a slot the reader will never consume (send failed,
        waiter abandoned, connection died): marks it DONE so reclaim
        can pass. The reader may still be parsing a cancelled slot on a
        wedged-peer race; a garbled parse answers 400 to a request id
        nobody waits on — verdicts are unaffected."""
        self.buf[off - REC_HDR + 4] = ST_DONE

    def fail_all(self) -> None:
        """Mark every outstanding record DONE (the attached reader is
        gone — connection loss already failed its in-flight waiters)."""
        with self._lock:
            for _virt, _plen, hdr in self._recs:
                if hdr is not None:
                    self.buf[hdr + 4] = ST_DONE
            self._reclaim_locked()

    # -- introspection ------------------------------------------------

    def used_fraction(self) -> float:
        with self._lock:
            self._reclaim_locked()
            return (self._head - self._tail) / self.size

    def close(self) -> None:
        self.buf = None
        _close_quiet(self.seg)


class RingReader:
    """The consuming side: descriptor -> zero-copy memoryview, then one
    state-byte release. No allocation state lives here."""

    def __init__(self, seg):
        self.seg = seg
        self._mv = memoryview(seg.buf)

    def view(self, off: int, n: int) -> memoryview:
        return self._mv[off:off + n]

    def release(self, off: int) -> None:
        self.seg.buf[off - REC_HDR + 4] = ST_DONE

    def close(self) -> None:
        try:
            self._mv.release()
        except BufferError:
            pass
        _close_quiet(self.seg)


class RingSlice:
    """A response payload living in a reply ring: bytes-like enough for
    the HTTP send path (len / buffer / bytes()), released back to the
    ring exactly once, after the final send (or on error)."""

    __slots__ = ("mv", "_reader", "_off", "_released")

    def __init__(self, reader: RingReader, off: int, n: int):
        self.mv = reader.view(off, n)
        self._reader = reader
        self._off = off
        self._released = False

    def __len__(self) -> int:
        return len(self.mv)

    def __bytes__(self) -> bytes:
        return bytes(self.mv)

    def release(self) -> None:
        if self._released:
            return
        self._released = True
        mv, self.mv = self.mv, None
        try:
            mv.release()
        except BufferError:  # pragma: no cover - defensive
            pass
        try:
            self._reader.release(self._off)
        except (TypeError, ValueError):  # ring torn down first
            pass


class ClientRings:
    """The frontend-owned ring pair for one engine connection: this
    process WRITES the request ring and READS the reply ring."""

    def __init__(self, prefix: str, size_bytes: int):
        self.prefix = prefix
        self.qname = f"{prefix}-q"
        self.rname = f"{prefix}-r"
        qseg = create(self.qname, size_bytes)
        try:
            rseg = create(self.rname, size_bytes)
        except OSError:
            _close_quiet(qseg)
            unlink(self.qname)
            raise
        self.req = RingWriter(qseg)
        self.reply = RingReader(rseg)

    def hello(self) -> dict:
        return {"q": self.qname, "r": self.rname}

    def reply_slice(self, off: int, n: int) -> RingSlice:
        return RingSlice(self.reply, off, n)

    def on_disconnect(self) -> None:
        """Engine gone: every in-flight request slot is dead (the
        waiters were failed); free them so the ring cannot silt up."""
        self.req.fail_all()

    def close(self, unlink_segments: bool = True) -> None:
        if unlink_segments:
            unlink(self.qname)
            unlink(self.rname)
        self.req.close()
        self.reply.close()


class EngineRings:
    """The engine-attached view of one frontend's ring pair: READS the
    request ring, WRITES the reply ring."""

    def __init__(self, names: dict):
        qseg = attach(str(names["q"]))
        try:
            rseg = attach(str(names["r"]))
        except OSError:
            _close_quiet(qseg)
            raise
        self.req = RingReader(qseg)
        self.reply = RingWriter(rseg)

    def close(self) -> None:
        self.req.close()
        self.reply.close()


def sweep_stale(prefix: str) -> None:
    """Unlink any ring segments under `prefix` (supervisor respawn /
    shutdown path: a SIGKILLed frontend leaves its segments behind)."""
    for suffix in ("-q", "-r"):
        unlink(prefix + suffix)


# ------------------------------------------------------------ chaos hooks


_SHM_DIR = "/dev/shm"


def list_segments(prefix: str = "") -> list[str]:
    """Names of live /dev/shm segments starting with `prefix` (the
    chaos verifier's leak check: after a schedule + teardown, no
    gk-bp-* segment may remain). Empty where /dev/shm is absent."""
    try:
        names = os.listdir(_SHM_DIR)
    except OSError:
        return []
    return sorted(n for n in names if n.startswith(prefix))


def corrupt_segment(name: str, offset: int = 0,
                    pattern: bytes = b"\xde\xad\xbe\xef") -> bool:
    """Chaos action: stamp `pattern` into a live segment at `offset`
    without the owner's locks — a torn/corrupted record the reader
    must survive (parse failure -> 400 / inline retry, never a smeared
    verdict). Returns False when the segment does not exist."""
    if _shm is None:
        return False
    try:
        seg = _shm.SharedMemory(name=name)
    except (OSError, ValueError):
        return False
    try:
        from multiprocessing import resource_tracker

        resource_tracker.unregister(seg._name, "shared_memory")
    except Exception:
        pass
    try:
        end = min(len(seg.buf), offset + len(pattern))
        if end > offset:
            seg.buf[offset:end] = pattern[: end - offset]
        return True
    finally:
        _close_quiet(seg)
