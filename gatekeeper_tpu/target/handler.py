"""K8s validation target handler.

Counterpart of the reference's K8sValidationTarget (pkg/target/target.go):
maps synced objects onto inventory paths, wraps inputs into gkReview dicts,
re-extracts violating resources, and publishes the `spec.match` schema.
Objects are unstructured dicts throughout; inventory paths are tuples of
segments, so group/version strings like "apps/v1" need no URL escaping
(the reference's url.PathEscape at target.go:73-76 — which its audit-cache
Rego then mis-splits — is unnecessary here).
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Any, Optional, Tuple

from ..client.types import Result
from .matcher import matches_label_selector

TARGET_NAME = "admission.k8s.gatekeeper.sh"

_VALID_OPERATORS = ("In", "NotIn", "Exists", "DoesNotExist")


class TargetError(Exception):
    pass


class WipeData:
    """Sentinel: RemoveData(WipeData()) clears the target's inventory
    (reference pkg/target/target.go:36-40; config controller uses it)."""


@dataclass
class AugmentedReview:
    admission_request: dict
    namespace: Optional[dict] = None


@dataclass
class AugmentedUnstructured:
    object: dict
    namespace: Optional[dict] = None


def _gvk_of(obj: dict) -> tuple[str, str, str]:
    api_version = obj.get("apiVersion") or ""
    group, _, version = api_version.rpartition("/")
    return group, version, obj.get("kind") or ""


def _meta(obj: dict) -> dict:
    m = obj.get("metadata")
    return m if isinstance(m, dict) else {}


class K8sValidationTarget:
    def __init__(self):
        # cross-audit Result.resource render memo (see handle_violation)
        self._resource_memo: dict = {}

    def get_name(self) -> str:
        return TARGET_NAME

    # ------------------------------------------------------------ data path

    def process_data(self, obj: Any) -> Tuple[bool, tuple, Any]:
        """Map an object to its inventory path (reference target.go:62-89).

        Returns (handled, path, data); WipeData maps to the empty path.
        """
        if isinstance(obj, WipeData) or obj is WipeData:
            return True, (), None
        if isinstance(obj, dict):
            group, version, kind = _gvk_of(obj)
            if not version:
                raise TargetError(f"resource {_meta(obj).get('name')} has no version")
            if not kind:
                raise TargetError(f"resource {_meta(obj).get('name')} has no kind")
            gv = f"{group}/{version}" if group else version
            name = _meta(obj).get("name") or ""
            ns = _meta(obj).get("namespace") or ""
            if not ns:
                return True, ("cluster", gv, kind, name), obj
            return True, ("namespace", ns, gv, kind, name), obj
        return False, (), None

    # ------------------------------------------------------------- reviews

    def handle_review(self, obj: Any) -> Tuple[bool, Optional[dict]]:
        """Wrap supported inputs into a gkReview dict
        (reference target.go:91-127)."""
        if isinstance(obj, AugmentedReview):
            review = dict(obj.admission_request)
            if obj.namespace is not None:
                review["_unstable"] = {"namespace": obj.namespace}
            return True, review
        if isinstance(obj, AugmentedUnstructured):
            review = self._object_to_review(obj.object)
            if obj.namespace is not None:
                review["_unstable"] = {"namespace": obj.namespace}
                ns_name = _meta(obj.namespace).get("name")
                if ns_name:
                    review["namespace"] = ns_name
            return True, review
        if isinstance(obj, dict):
            if "kind" in obj and isinstance(obj.get("kind"), dict):
                # already AdmissionRequest-shaped
                return True, dict(obj)
            if "apiVersion" in obj and isinstance(obj.get("kind"), str):
                return True, self._object_to_review(obj)
        return False, None

    def _object_to_review(self, obj: dict) -> dict:
        group, version, kind = _gvk_of(obj)
        review: dict = {
            "kind": {"group": group, "version": version, "kind": kind},
            "object": obj,
        }
        name = _meta(obj).get("name")
        if name:
            review["name"] = name
        ns = _meta(obj).get("namespace")
        if ns:
            review["namespace"] = ns
        return review

    # ----------------------------------------------------------- violations

    def handle_violation(self, result: Result,
                         memo: Optional[dict] = None) -> None:
        """Re-extract the violating resource from the review
        (reference target.go:193-244).

        memo (scoped to one response batch by the caller) dedupes the
        deep copy across the many results one object produces in a large
        audit; like Result.constraint, the resource dict is then shared
        between those results."""
        review = result.review
        if not isinstance(review, dict):
            raise TargetError(f"could not cast review as object: {review!r}")
        kind = review.get("kind")
        if not isinstance(kind, dict):
            raise TargetError("review has no kind")
        group = kind.get("group")
        version = kind.get("version")
        kname = kind.get("kind")
        for f, v in (("group", group), ("version", version), ("kind", kname)):
            if not isinstance(v, str):
                raise TargetError(f"review[kind][{f}] is not a string: {v!r}")
        api_version = version if not group else f"{group}/{version}"
        obj = review.get("object")
        if not isinstance(obj, dict):
            obj = review.get("oldObject")
        if not isinstance(obj, dict):
            raise TargetError("no object or oldObject returned in review")
        key = (id(obj), api_version, kname)
        resource = memo.get(key) if memo is not None else None
        if resource is None:
            # cross-audit memo: steady-state sweeps re-render the same
            # store objects every interval; identity-checked so a
            # replaced object re-copies
            ent = self._resource_memo.get(key)
            if ent is not None and ent[0] is obj:
                resource = ent[1]
            else:
                resource = json.loads(json.dumps(obj))
                resource["apiVersion"] = api_version
                resource["kind"] = kname
                if len(self._resource_memo) > 131072:
                    self._resource_memo.clear()
                self._resource_memo[key] = (obj, resource)
            if memo is not None:
                memo[key] = resource
        result.resource = resource

    # -------------------------------------------------------------- schema

    def match_schema(self) -> dict:
        """JSONSchema of spec.match (reference target.go:246-310)."""
        string_list = {"type": "array", "items": {"type": "string"}}
        label_selector = {
            "properties": {
                "matchExpressions": {
                    "type": "array",
                    "items": {
                        "properties": {
                            "key": {"type": "string"},
                            "operator": {
                                "type": "string",
                                "enum": list(_VALID_OPERATORS),
                            },
                            "values": {
                                "type": "array",
                                "items": {"type": "string"},
                            },
                        }
                    },
                }
            }
        }
        return {
            "properties": {
                "kinds": {
                    "type": "array",
                    "items": {
                        "properties": {
                            "apiGroups": {"items": {"type": "string"}},
                            "kinds": {"items": {"type": "string"}},
                        }
                    },
                },
                "namespaces": string_list,
                "excludedNamespaces": string_list,
                "labelSelector": label_selector,
                "namespaceSelector": label_selector,
            }
        }

    # ----------------------------------------------------------- validation

    def validate_constraint(self, constraint: dict) -> None:
        """Label-selector validation (reference target.go:312-346)."""
        spec = constraint.get("spec") or {}
        match = spec.get("match") or {}
        for sel_field in ("labelSelector", "namespaceSelector"):
            sel = match.get(sel_field)
            if sel is None:
                continue
            if not isinstance(sel, dict):
                raise TargetError(f"spec.match.{sel_field} must be an object")
            self._validate_label_selector(sel, f"spec.match.{sel_field}")

    def _validate_label_selector(self, sel: dict, path: str) -> None:
        ml = sel.get("matchLabels")
        if ml is not None:
            if not isinstance(ml, dict):
                raise TargetError(f"{path}.matchLabels must be an object")
            for k, v in ml.items():
                if not isinstance(v, str):
                    raise TargetError(f"{path}.matchLabels[{k!r}] must be a string")
        exprs = sel.get("matchExpressions")
        if exprs is None:
            return
        if not isinstance(exprs, list):
            raise TargetError(f"{path}.matchExpressions must be an array")
        for i, e in enumerate(exprs):
            if not isinstance(e, dict):
                raise TargetError(f"{path}.matchExpressions[{i}] must be an object")
            op = e.get("operator")
            if op not in _VALID_OPERATORS:
                raise TargetError(
                    f"{path}.matchExpressions[{i}]: invalid operator {op!r}"
                )
            values = e.get("values") or []
            if op in ("In", "NotIn") and not values:
                raise TargetError(
                    f"{path}.matchExpressions[{i}]: operator {op} requires values"
                )
            if op in ("Exists", "DoesNotExist") and values:
                raise TargetError(
                    f"{path}.matchExpressions[{i}]: operator {op} forbids values"
                )

    # sanity: matcher import is part of the public target surface
    matches_label_selector = staticmethod(matches_label_selector)
