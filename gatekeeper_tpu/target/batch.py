"""Batched constraint matching: match masks for the audit cross-product.

Computes mask[R, C] (review × constraint) without R×C Python calls: match
depends only on (group, kind, namespace[, Namespace-object identity]) for
constraints without label selectors, so reviews are grouped by that
signature and each (group-signature, constraint) decided once. Only
label-selector constraints (and Namespace-kind reviews, whose own labels
feed namespaceSelector) fall back to per-review checks.

Semantics delegate to the differentially-tested predicate in matcher.py.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from .matcher import NamespaceLookup, constraint_matches


def _has_label_selector(constraint: dict) -> bool:
    spec = constraint.get("spec")
    spec = spec if isinstance(spec, dict) else {}
    match = spec.get("match")
    match = match if isinstance(match, dict) else {}
    return "labelSelector" in match


def _signature(review: dict) -> Optional[tuple]:
    """Grouping key, or None if the review needs per-object matching."""
    kind = review.get("kind")
    kind = kind if isinstance(kind, dict) else {}
    if kind.get("group", "") in ("", None) and kind.get("kind") == "Namespace":
        return None  # object labels/name feed the match; keep per-object
    if "_unstable" in review:
        return None  # sideloaded namespace object varies per review
    ns = review.get("namespace") if "namespace" in review else "\x00absent"
    return (kind.get("group"), kind.get("kind"), ns)


def match_masks(constraints: list[dict], reviews: list[dict],
                lookup_ns: NamespaceLookup) -> np.ndarray:
    R, C = len(reviews), len(constraints)
    mask = np.zeros((R, C), dtype=bool)
    label_dep = [_has_label_selector(c) for c in constraints]

    group_cache: dict[tuple, dict[int, bool]] = {}
    for r, review in enumerate(reviews):
        sig = _signature(review)
        if sig is None:
            for c, constraint in enumerate(constraints):
                mask[r, c] = constraint_matches(constraint, review, lookup_ns)
            continue
        cached = group_cache.get(sig)
        if cached is None:
            cached = {}
            group_cache[sig] = cached
        for c, constraint in enumerate(constraints):
            if label_dep[c]:
                mask[r, c] = constraint_matches(constraint, review, lookup_ns)
                continue
            hit = cached.get(c)
            if hit is None:
                hit = constraint_matches(constraint, review, lookup_ns)
                cached[c] = hit
            mask[r, c] = hit
    return mask
