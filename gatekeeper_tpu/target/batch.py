"""Batched constraint matching: match masks for the audit cross-product.

Computes mask[R, C] (review × constraint) without R×C Python calls. The
match predicate (matcher.py, mirroring pkg/target/regolib/src.rego) reads
only a small projection of each review:

  * kinds clause            → (kind.group, kind.kind)
  * namespaces / excluded   → the effective namespace name (get_ns_name)
  * namespaceSelector       → raw review.namespace (cache lookup key) plus
                              the object/oldObject label state for
                              Namespace-kind reviews
  * labelSelector           → object/oldObject label state

Reviews are therefore grouped ONCE by the full signature of all those
components; each constraint declares which components it depends on, and
`constraint_matches` runs once per (constraint, projected signature) —
for selector-free constraints that is once per (group, kind) in the whole
cluster. Semantics still delegate to the differentially-tested predicate
in matcher.py; this module only memoizes it (correctness asserted by the
brute-force differential in tests/test_target_matcher.py).

Reviews carrying `_unstable` (namespace sideload — the webhook per
request, discovery-mode audit for every namespaced object) contribute
the sideloaded namespace's LABELS to the signature: that is all
_matches_nsselector can observe of it, so objects sharing a namespace
still collapse into one group instead of falling back to per-review
evaluation (the discovery audit sideloads on every namespaced review —
a fallback there would reintroduce the R×C matcher loop).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..utils.values import freeze
from .matcher import NamespaceLookup, _get_ns_name, _has_field, _MISSING, \
    constraint_matches


def _dependence(constraint: dict) -> tuple:
    """(name_dep, nssel_dep, lblsel_dep) — which signature components the
    constraint's match clauses read beyond (group, kind)."""
    spec = constraint.get("spec")
    spec = spec if isinstance(spec, dict) else {}
    match = spec.get("match")
    match = match if isinstance(match, dict) else {}
    name_dep = _has_field(match, "namespaces") or \
        _has_field(match, "excludedNamespaces")
    return (name_dep, "namespaceSelector" in match, "labelSelector" in match)


def _labels_key(labels: dict):
    """Hashable signature key for a labels dict. Labels are dict[str, str]
    in practice: a sorted-items tuple is a ~4x cheaper key than a
    recursive freeze (hash() probes for unhashable values so malformed
    labels fall back cleanly)."""
    try:
        t = tuple(sorted(labels.items()))
        hash(t)
        return t
    except TypeError:
        return freeze(labels)


def _label_state(review: dict, field: str):
    """(is-empty, hashable labels key) of review.object/.oldObject —
    everything _any_labelselector_match can observe."""
    v = review.get(field)
    v = v if isinstance(v, dict) else {}
    if not v:
        return (True, None)
    meta = v.get("metadata")
    labels = meta.get("labels") if isinstance(meta, dict) else None
    if not isinstance(labels, dict):
        return (False, None)
    return (False, _labels_key(labels))


def _unstable_state(review: dict):
    """Hashable key of the sideloaded namespace as _get_ns observes it:
    (present-and-resolving, labels key), or _MISSING for a malformed
    sideload (→ per-review fallback)."""
    if "_unstable" not in review:
        return None
    unstable = review.get("_unstable")
    if not isinstance(unstable, dict):
        return _MISSING
    ns = unstable.get("namespace")
    if ns is None:
        return (False, None)
    if not isinstance(ns, dict):
        return _MISSING
    meta = ns.get("metadata")
    labels = meta.get("labels") if isinstance(meta, dict) else None
    if not isinstance(labels, dict):
        return (True, None)
    return (True, _labels_key(labels))


def _signature(review: dict) -> Optional[tuple]:
    """Full match-relevant signature, or None for per-review fallback."""
    ust = _unstable_state(review)
    if ust is _MISSING:
        return None
    kind = review.get("kind")
    kind = kind if isinstance(kind, dict) else {}
    eff_ns = _get_ns_name(review)
    if eff_ns is _MISSING:
        eff_ns = "\x00missing"
    return (
        kind.get("group"), kind.get("kind"),
        ("namespace" in review, review.get("namespace")),
        eff_ns,
        _label_state(review, "object"),
        _label_state(review, "oldObject"),
        ust,
    )


def _project(sig: tuple, dep: tuple) -> tuple:
    name_dep, nssel_dep, lblsel_dep = dep
    key = (sig[0], sig[1])
    if name_dep:
        key += (sig[3],)
    if nssel_dep:
        key += (sig[2], sig[4], sig[5], sig[6])
    if lblsel_dep:
        key += (sig[4], sig[5])
    return key


def match_masks(constraints: list[dict], reviews: list[dict],
                lookup_ns: NamespaceLookup,
                sig_cache: Optional[dict] = None) -> np.ndarray:
    """mask[R, C]. sig_cache (id(review) -> signature) lets one audit
    reuse signatures across per-kind calls over the same review list."""
    R, C = len(reviews), len(constraints)
    mask = np.zeros((R, C), dtype=bool)

    groups: dict[tuple, list[int]] = {}
    fallback: list[int] = []
    for r, review in enumerate(reviews):
        if sig_cache is not None:
            sig = sig_cache.get(id(review), _MISSING)
            if sig is _MISSING:
                sig = _signature(review)
                sig_cache[id(review)] = sig
        else:
            sig = _signature(review)
        if sig is None:
            fallback.append(r)
        else:
            groups.setdefault(sig, []).append(r)

    # constraints bucketed by dependence class (usually 1-2 classes per
    # audit); the expensive group->projection collapse runs once per class,
    # NOT once per constraint — selector-free constraints then cost one
    # matcher call per (group, kind) in the whole cluster
    classes: dict[tuple, list[int]] = {}
    for c, constraint in enumerate(constraints):
        classes.setdefault(_dependence(constraint), []).append(c)

    for dep, cidxs in classes.items():
        proj: dict[tuple, list] = {}
        rep: dict[tuple, int] = {}
        for sig, rows in groups.items():
            key = _project(sig, dep)
            bucket = proj.get(key)
            if bucket is None:
                proj[key] = list(rows)
                rep[key] = rows[0]
            else:
                bucket.extend(rows)
        proj_rows = [(np.asarray(rows), reviews[rep[key]])
                     for key, rows in proj.items()]
        cidx_arr = np.asarray(cidxs)
        # assign per (projection group, matched-constraint set) BLOCK:
        # one np.ix_ write instead of |groups|×|constraints| fancy-index
        # writes (the all-match case — selector-free constraints — is a
        # single [R, C] block memset)
        for rows, review in proj_rows:
            matched = [c for c in cidxs
                       if constraint_matches(constraints[c], review,
                                             lookup_ns)]
            if not matched:
                continue
            cols = cidx_arr if len(matched) == len(cidxs) \
                else np.asarray(matched)
            mask[np.ix_(rows, cols)] = True
        for r in fallback:
            for c in cidxs:
                mask[r, c] = constraint_matches(constraints[c], reviews[r],
                                                lookup_ns)
    return mask
