from .handler import AugmentedReview, AugmentedUnstructured, K8sValidationTarget, WipeData
from .matcher import constraint_matches, needs_autoreject

__all__ = [
    "AugmentedReview",
    "AugmentedUnstructured",
    "K8sValidationTarget",
    "WipeData",
    "constraint_matches",
    "needs_autoreject",
]
