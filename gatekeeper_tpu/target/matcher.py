"""Native constraint-match predicate.

The reference evaluates `spec.match` (kinds / namespaces /
excludedNamespaces / labelSelector / namespaceSelector) with a generated
Rego library (pkg/target/regolib/src.rego, embedded at
pkg/target/target_template_source.go:6-336). Here the same semantics are
implemented natively — this predicate is the batch-selection mask of the
vectorized audit sweep, so it must be cheap and host-side.

Semantics are mirrored clause-by-clause from the Rego source, including its
edge cases (differentially tested against that Rego running in our
interpreter — tests/test_target_matcher.py):

  * `get_default` treats JSON null as missing; `has_field` treats null as
    PRESENT (src.rego:84-118) — so `namespaceSelector: null` still triggers
    autoreject but selects like `{}`.
  * a `kinds` entry missing `apiGroups` or `kinds` never matches
    (src.rego:135-149: enumeration over a missing field is undefined).
  * `namespaces`/`excludedNamespaces` require a resolvable namespace name —
    cluster-scoped non-Namespace objects never match a constraint that sets
    either field (src.rego:286-302, get_ns_name undefined).
  * label-selector matching considers object/oldObject per
    src.rego:203-252 (either may satisfy the selector when both exist; an
    empty object counts as absent).
  * matchExpressions: unknown operators are ignored; `In` with empty
    values is violated only by a missing key (src.rego:156-181).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

_MISSING = object()


def _get_default(obj: Any, field: str, default: Any) -> Any:
    """Field lookup treating null as missing (src.rego:100-118)."""
    if not isinstance(obj, dict):
        return default
    v = obj.get(field, _MISSING)
    if v is _MISSING or v is None:
        return default
    return v


def _has_field(obj: Any, field: str) -> bool:
    """Presence check; null counts as present (src.rego:84-98)."""
    return isinstance(obj, dict) and field in obj


NamespaceLookup = Callable[[str], Optional[dict]]


def _review_kind(review: dict) -> dict:
    k = review.get("kind")
    return k if isinstance(k, dict) else {}


def _is_ns(kind: dict) -> bool:
    # reference is_ns (src.rego:258-261) requires kind.group == "" exactly;
    # a missing or null group leaves it undefined, so it must NOT match
    return kind.get("group") == "" and kind.get("kind") == "Namespace"


def _get_ns_name(review: dict):
    """src.rego:272-280; returns None when undefined."""
    if _is_ns(_review_kind(review)):
        obj = review.get("object")
        if isinstance(obj, dict):
            meta = obj.get("metadata")
            if isinstance(meta, dict) and "name" in meta:
                return meta["name"]
        return None
    return review.get("namespace", _MISSING) if "namespace" in review else None


def _get_ns(review: dict, lookup_namespace: NamespaceLookup):
    """Resolve the review's namespace object (src.rego:263-270)."""
    unstable = review.get("_unstable")
    if isinstance(unstable, dict):
        ns = unstable.get("namespace")
        if ns is not None:
            return ns
    name = review.get("namespace")
    if isinstance(name, str) and name:
        return lookup_namespace(name)
    return None


def needs_autoreject(
    match: Any, review: dict, lookup_namespace: NamespaceLookup
) -> bool:
    """autoreject_review preconditions per constraint (src.rego:7-20):
    namespaceSelector present, namespace not resolvable from the cache or
    the sideloaded `_unstable.namespace`, and review.namespace not
    explicitly empty."""
    if not _has_field(match if isinstance(match, dict) else {}, "namespaceSelector"):
        return False
    ns_name = review.get("namespace")
    if "namespace" in review and ns_name == "":
        return False
    unstable = review.get("_unstable")
    if isinstance(unstable, dict) and unstable.get("namespace"):
        return False
    if isinstance(ns_name, str) and ns_name and lookup_namespace(ns_name):
        return False
    return True


def constraint_matches(
    constraint: dict, review: dict, lookup_namespace: NamespaceLookup
) -> bool:
    """matching_constraints body (src.rego:22-37)."""
    spec = _get_default(constraint, "spec", {})
    match = _get_default(spec, "match", {})
    if not isinstance(match, dict):
        match = {}
    return (
        _any_kind_selector_matches(match, review)
        and _matches_namespaces(match, review)
        and _does_not_match_excluded(match, review)
        and _matches_nsselector(match, review, lookup_namespace)
        and _any_labelselector_match(_get_default(match, "labelSelector", {}), review)
    )


# ------------------------------------------------------------------- kinds


def _any_kind_selector_matches(match: dict, review: dict) -> bool:
    selectors = _get_default(match, "kinds", [{"apiGroups": ["*"], "kinds": ["*"]}])
    if not isinstance(selectors, (list, tuple)):
        return False
    kind = _review_kind(review)
    group = kind.get("group")
    kname = kind.get("kind")
    for ks in selectors:
        if not isinstance(ks, dict):
            continue
        groups = ks.get("apiGroups")
        kinds = ks.get("kinds")
        if not isinstance(groups, (list, tuple)) or not isinstance(kinds, (list, tuple)):
            continue  # missing/null field → selector can never match
        if ("*" in groups or (group is not None and group in groups)) and (
            "*" in kinds or (kname is not None and kname in kinds)
        ):
            return True
    return False


# -------------------------------------------------------------- namespaces


def _matches_namespaces(match: dict, review: dict) -> bool:
    if not _has_field(match, "namespaces"):
        return True
    ns = _get_ns_name(review)
    if ns is None or ns is _MISSING:
        return False
    nss = match.get("namespaces")
    listed = set(x for x in nss if isinstance(x, str)) if isinstance(nss, (list, tuple)) else set()
    return ns in listed


def _does_not_match_excluded(match: dict, review: dict) -> bool:
    if not _has_field(match, "excludedNamespaces"):
        return True
    ns = _get_ns_name(review)
    if ns is None or ns is _MISSING:
        return False
    nss = match.get("excludedNamespaces")
    listed = set(x for x in nss if isinstance(x, str)) if isinstance(nss, (list, tuple)) else set()
    return ns not in listed


# ---------------------------------------------------------- label selectors


def _labels_of(obj: Any) -> dict:
    meta = _get_default(obj if isinstance(obj, dict) else {}, "metadata", {})
    labels = _get_default(meta if isinstance(meta, dict) else {}, "labels", {})
    return labels if isinstance(labels, dict) else {}


def _match_expression_violated(op: str, labels: dict, key: str, values: list) -> bool:
    """src.rego:156-181; unknown operators are never violated."""
    if op == "In":
        if key not in labels:
            return True
        return len(values) > 0 and not any(labels[key] == v for v in values)
    if op == "NotIn":
        return len(values) > 0 and key in labels and any(labels[key] == v for v in values)
    if op == "Exists":
        return key not in labels
    if op == "DoesNotExist":
        return key in labels
    return False


def matches_label_selector(selector: Any, labels: dict) -> bool:
    if not isinstance(selector, dict):
        selector = {}
    match_labels = _get_default(selector, "matchLabels", {})
    if isinstance(match_labels, dict):
        for k, v in match_labels.items():
            if k not in labels or labels[k] != v:
                return False
    exprs = _get_default(selector, "matchExpressions", [])
    if isinstance(exprs, (list, tuple)):
        for e in exprs:
            if not isinstance(e, dict):
                continue
            op = e.get("operator")
            key = e.get("key")
            values = _get_default(e, "values", [])
            if not isinstance(values, (list, tuple)):
                values = []
            if isinstance(op, str) and isinstance(key, str):
                if _match_expression_violated(op, labels, key, values):
                    return False
    return True


def _obj_or_empty(review: dict, field: str) -> Any:
    v = _get_default(review, field, {})
    return v if isinstance(v, dict) else {}


def _any_labelselector_match(selector: Any, review: dict) -> bool:
    """src.rego:203-252: which of object/oldObject carries the labels."""
    obj = _obj_or_empty(review, "object")
    old = _obj_or_empty(review, "oldObject")
    if old == {} and obj != {}:
        return matches_label_selector(selector, _labels_of(obj))
    if old != {} and obj == {}:
        return matches_label_selector(selector, _labels_of(old))
    if old != {} and obj != {}:
        return matches_label_selector(selector, _labels_of(obj)) or \
            matches_label_selector(selector, _labels_of(old))
    return matches_label_selector(selector, {})


# ------------------------------------------------------- namespace selector


def _matches_nsselector(
    match: dict, review: dict, lookup_namespace: NamespaceLookup
) -> bool:
    if not _has_field(match, "namespaceSelector"):
        return True
    selector = _get_default(match, "namespaceSelector", {})
    if _is_ns(_review_kind(review)):
        return _any_labelselector_match(selector, review)
    ns = _get_ns(review, lookup_namespace)
    if ns is None:
        return False
    return matches_label_selector(selector, _labels_of(ns))
