"""Shipped policy library.

The framework's counterpart of the reference's `library/` content
(library/general + library/pod-security-policy): 23 ConstraintTemplates
as ready-to-apply YAML, authored for this engine (each template's rego is
an independent implementation; behavior parity with the reference
library is asserted differentially over the reference's own test corpus
in tests/test_policies.py).

Use:
    from gatekeeper_tpu import policies
    client.add_template(policies.load("general/requiredlabels"))
    for name in policies.names(): ...

`python -m gatekeeper_tpu.policies.demo` runs a self-contained demo.
"""

from __future__ import annotations

import functools
import pathlib

import yaml

_ROOT = pathlib.Path(__file__).parent
GROUPS = ("general", "pod-security-policy")


def names() -> list[str]:
    """All shipped template names, e.g. "general/requiredlabels"."""
    out = []
    for group in GROUPS:
        for p in sorted((_ROOT / group).glob("*.yaml")):
            out.append(f"{group}/{p.stem}")
    return out


@functools.lru_cache(maxsize=64)
def _load_cached(name: str) -> dict:
    path = _ROOT / f"{name}.yaml"
    if not path.is_file():
        raise KeyError(f"no shipped policy named {name!r}; "
                       f"see gatekeeper_tpu.policies.names()")
    with open(path) as f:
        return yaml.safe_load(f)


def load(name: str) -> dict:
    """The ConstraintTemplate dict for a shipped policy (fresh copy)."""
    import copy

    return copy.deepcopy(_load_cached(name))


def load_all() -> dict[str, dict]:
    return {n: load(n) for n in names()}


def kind_of(name: str) -> str:
    """The constraint Kind a shipped template defines."""
    return _load_cached(name)["spec"]["crd"]["spec"]["names"]["kind"]
