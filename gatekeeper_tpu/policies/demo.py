"""Self-contained policy demo: `python -m gatekeeper_tpu.policies.demo`.

Loads the shipped library, applies a few constraints, then shows the two
evaluation paths a cluster would exercise:
  * admission review of a compliant and a violating Pod;
  * an audit sweep over synced inventory.
The framework analog of the reference's demo/basic walkthrough.
"""

from __future__ import annotations

from gatekeeper_tpu import policies
from gatekeeper_tpu.client import Backend
from gatekeeper_tpu.ir import TpuDriver
from gatekeeper_tpu.target import AugmentedUnstructured, K8sValidationTarget


def pod(name: str, image: str, privileged: bool = False) -> dict:
    ctx = {"privileged": True} if privileged else {}
    return {
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": name, "namespace": "default",
                     "labels": {"app": name}},
        "spec": {"containers": [{
            "name": "main", "image": image,
            "securityContext": ctx,
        }]},
    }


def main() -> None:
    client = Backend(TpuDriver()).new_client([K8sValidationTarget()])
    for name in policies.names():
        client.add_template(policies.load(name))
    print(f"installed {len(policies.names())} templates:",
          ", ".join(policies.names()[:4]), "...")

    client.add_constraint({
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": "K8sAllowedRepos", "metadata": {"name": "corp-repos-only"},
        "spec": {"parameters": {"repos": ["registry.corp.example/"]}},
    })
    client.add_constraint({
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": "K8sPSPPrivilegedContainer",
        "metadata": {"name": "no-privileged"},
        "spec": {},
    })

    print("\n--- admission ---")
    for p in (pod("good", "registry.corp.example/api:v1"),
              pod("rogue", "docker.io/evil:latest", privileged=True)):
        results = client.review(AugmentedUnstructured(p)).results()
        verdict = "ALLOWED" if not results else "DENIED"
        print(f"{p['metadata']['name']:>6}: {verdict}")
        for r in results:
            print(f"        [{r.constraint['metadata']['name']}] {r.msg}")

    print("\n--- audit ---")
    for p in (pod("legacy-a", "docker.io/old:1"),
              pod("legacy-b", "registry.corp.example/ok:2", privileged=True)):
        client.add_data(p)
    for r in client.audit().results():
        print(f"{r.resource['metadata']['name']:>8}: "
              f"[{r.constraint['metadata']['name']}] {r.msg}")


if __name__ == "__main__":
    main()
