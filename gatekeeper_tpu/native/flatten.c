/* Native extraction flattener: review dicts -> fixed-shape cell arrays.
 *
 * C implementation of the ingest hot path in gatekeeper_tpu/ir/features.py
 * (the numpy/Python Extractor is the reference and fallback; differential
 * tests in tests/test_native_flatten.py pin exact equivalence, including
 * intern-id assignment order). Interning writes straight into the Python
 * StringTable's _ids dict / _strs list via the CPython API, so ids stay
 * shared with the param encoder and match tables.
 *
 * Counterpart of the JSON->tensor ingestion the reference framework gets
 * from Go's typed unstructured handling (client-go) ahead of OPA
 * evaluation; here it feeds the device program's feature tensors.
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <math.h>
#include <stdint.h>
#include <string.h>

/* kind codes mirrored from ir/prog.py */
enum {
    K_ABSENT = 0,
    K_NULL = 1,
    K_FALSE = 2,
    K_TRUE = 3,
    K_NUM = 4,
    K_STR = 5,
    K_ARR = 6,
    K_OBJ = 7,
};

typedef struct {
    PyObject *ids;   /* StringTable._ids dict */
    PyObject *strs;  /* StringTable._strs list */
    long added;
} Interner;

static long intern_obj(Interner *it, PyObject *s)
{
    PyObject *v = PyDict_GetItemWithError(it->ids, s); /* borrowed */
    if (v != NULL)
        return PyLong_AsLong(v);
    if (PyErr_Occurred())
        return -1;
    Py_ssize_t i = PyList_GET_SIZE(it->strs);
    PyObject *iv = PyLong_FromSsize_t(i);
    if (iv == NULL)
        return -1;
    if (PyDict_SetItem(it->ids, s, iv) < 0) {
        Py_DECREF(iv);
        return -1;
    }
    Py_DECREF(iv);
    if (PyList_Append(it->strs, s) < 0)
        return -1;
    it->added++;
    return (long)i;
}

/* canonical number string: "\x01n" + (str(int(f)) if integral else repr) —
 * byte-identical to ops/strtab.py canon_num */
static long intern_canon(Interner *it, double f)
{
    char buf[64];
    PyObject *s;
    if (floor(f) == f && fabs(f) < 9007199254740992.0) { /* 2**53 */
        snprintf(buf, sizeof buf, "\x01n%lld", (long long)f);
        s = PyUnicode_FromString(buf);
    } else {
        char *ds = PyOS_double_to_string(f, 'r', 0, 0, NULL);
        if (ds == NULL)
            return -1;
        s = PyUnicode_FromFormat("\x01n%s", ds);
        PyMem_Free(ds);
    }
    if (s == NULL)
        return -1;
    long id = intern_obj(it, s);
    Py_DECREF(s);
    return id;
}

static int kind_of(PyObject *v)
{
    if (v == NULL)
        return K_ABSENT;
    if (v == Py_None)
        return K_NULL;
    if (PyBool_Check(v))
        return (v == Py_True) ? K_TRUE : K_FALSE;
    if (PyLong_Check(v) || PyFloat_Check(v))
        return K_NUM;
    if (PyUnicode_Check(v))
        return K_STR;
    if (PyList_Check(v) || PyTuple_Check(v))
        return K_ARR;
    if (PyDict_Check(v))
        return K_OBJ;
    return K_ABSENT;
}

typedef struct {
    int nsegs;
    PyObject **names; /* per seg; NULL for iter segs */
    int *is_iter;
    int ndims;
    long dims[8];
    int32_t *ids;
    float *nums;
    int32_t *nids;
    int8_t *kinds;
    int32_t *keys;      /* may be NULL */
    float *key_nums;    /* may be NULL */
    int32_t *key_nids;  /* may be NULL */
    Interner it;
} Fill;

/* follow consecutive field segs; returns borrowed ref or NULL (absent) */
static PyObject *descend_fields(PyObject *node, Fill *f, int *i)
{
    while (*i < f->nsegs && !f->is_iter[*i]) {
        if (node == NULL || !PyDict_Check(node))
            return NULL;
        node = PyDict_GetItemWithError(node, f->names[*i]);
        if (node == NULL)
            return NULL; /* absent (or error: caller checks PyErr) */
        (*i)++;
    }
    return node;
}

static int put_cell(Fill *f, long off, PyObject *v)
{
    int k = kind_of(v);
    f->kinds[off] = (int8_t)k;
    if (k == K_STR) {
        long id = intern_obj(&f->it, v);
        if (id < 0)
            return -1;
        f->ids[off] = (int32_t)id;
    } else if (k == K_NUM) {
        double d = PyFloat_Check(v) ? PyFloat_AS_DOUBLE(v)
                                    : PyLong_AsDouble(v);
        if (d == -1.0 && PyErr_Occurred())
            return -1;
        f->nums[off] = (float)d;
        long id = intern_canon(&f->it, d);
        if (id < 0)
            return -1;
        f->nids[off] = (int32_t)id;
    } else if (k == K_TRUE || k == K_FALSE) {
        f->nums[off] = (k == K_TRUE) ? 1.0f : 0.0f;
    }
    return 0;
}

static int put_key_num(Fill *f, long off, double kd)
{
    f->key_nums[off] = (float)kd;
    long id = intern_canon(&f->it, kd);
    if (id < 0)
        return -1;
    f->key_nids[off] = (int32_t)id;
    return 0;
}

static int fill_rec(Fill *f, long off, PyObject *node, int i, int depth);

static int put_key(Fill *f, long sub, PyObject *key_or_null, double key_num,
                   int is_str_key, int depth)
{
    if (f->keys == NULL || depth != f->ndims - 1)
        return 0;
    if (is_str_key) {
        long id = intern_obj(&f->it, key_or_null);
        if (id < 0)
            return -1;
        f->keys[sub] = (int32_t)id;
        return 0;
    }
    return put_key_num(f, sub, key_num);
}

static int fill_child(Fill *f, long off, long j, PyObject *key_or_null,
                      double key_num, int is_str_key, PyObject *v, int i,
                      int depth, int last)
{
    long sub = off * f->dims[depth] + j;
    /* intern order mirrors the Python reference exactly: value before
     * key on the innermost axis, key before descent otherwise (ids must
     * be assigned identically for differential bit-equality) */
    if (last) {
        if (put_cell(f, sub, v) < 0)
            return -1;
        return put_key(f, sub, key_or_null, key_num, is_str_key, depth);
    }
    if (put_key(f, sub, key_or_null, key_num, is_str_key, depth) < 0)
        return -1;
    return fill_rec(f, sub, v, i + 1, depth + 1);
}

static int fill_rec(Fill *f, long off, PyObject *node, int i, int depth)
{
    node = descend_fields(node, f, &i);
    if (node == NULL)
        return PyErr_Occurred() ? -1 : 0;
    if (i == f->nsegs) {
        /* trailing-cell offset: remaining dims (none: i consumed all
         * iter segs) — off is the full linear index */
        return put_cell(f, off, node);
    }
    /* segs[i] is an iter seg */
    int last = (i == f->nsegs - 1);
    long cap = f->dims[depth];
    if (PyDict_Check(node)) {
        PyObject *k, *v;
        Py_ssize_t pos = 0;
        long j = 0;
        while (PyDict_Next(node, &pos, &k, &v)) {
            if (j >= cap)
                break;
            int is_str = PyUnicode_Check(k);
            double kd = 0.0;
            if (!is_str) {
                kd = PyFloat_Check(k) ? PyFloat_AS_DOUBLE(k)
                                      : PyLong_AsDouble(k);
                if (kd == -1.0 && PyErr_Occurred())
                    return -1;
            }
            if (fill_child(f, off, j, k, kd, is_str, v, i, depth, last) < 0)
                return -1;
            j++;
        }
        return 0;
    }
    if (PyList_Check(node) || PyTuple_Check(node)) {
        Py_ssize_t n = PySequence_Fast_GET_SIZE(node);
        PyObject **items = PySequence_Fast_ITEMS(node);
        for (Py_ssize_t j = 0; j < n && j < cap; j++) {
            if (fill_child(f, off, (long)j, NULL, (double)j, 0, items[j],
                           i, depth, last) < 0)
                return -1;
        }
        return 0;
    }
    return 0; /* scalar where a collection was expected: absent */
}

/* ---------------------------------------------------------- entry points */

static int parse_segs(PyObject *segs, Fill *f, PyObject ***names_out,
                      int **iter_out)
{
    Py_ssize_t n = PyTuple_GET_SIZE(segs);
    PyObject **names = PyMem_Calloc(n ? n : 1, sizeof(PyObject *));
    int *is_iter = PyMem_Calloc(n ? n : 1, sizeof(int));
    if (names == NULL || is_iter == NULL) {
        PyMem_Free(names);
        PyMem_Free(is_iter);
        PyErr_NoMemory();
        return -1;
    }
    for (Py_ssize_t k = 0; k < n; k++) {
        PyObject *seg = PyTuple_GET_ITEM(segs, k);
        is_iter[k] = PyObject_IsTrue(PyTuple_GET_ITEM(seg, 0));
        names[k] = PyTuple_GET_ITEM(seg, 1); /* borrowed */
    }
    f->nsegs = (int)n;
    *names_out = names;
    *iter_out = is_iter;
    return 0;
}

static void *buf_ptr(PyObject *obj, Py_buffer *view, int *ok)
{
    if (obj == Py_None)
        return NULL;
    if (PyObject_GetBuffer(obj, view, PyBUF_CONTIG) < 0) {
        *ok = 0;
        return NULL;
    }
    return view->buf;
}

static PyObject *root_of(PyObject *review, PyObject *root_name)
{
    /* "review" -> the review dict itself; else review[root] if dict */
    const char *r = PyUnicode_AsUTF8(root_name);
    if (r != NULL && strcmp(r, "review") == 0)
        return review;
    PyObject *v = PyDict_Check(review)
        ? PyDict_GetItemWithError(review, root_name) : NULL;
    if (v != NULL && !PyDict_Check(v))
        return NULL;
    return v;
}

static PyObject *py_fill_slot(PyObject *self, PyObject *args)
{
    PyObject *reviews, *root_name, *segs, *dims_t;
    PyObject *o_ids, *o_nums, *o_nids, *o_kinds, *o_keys, *o_knums,
        *o_knids, *ids_dict, *strs_list;
    if (!PyArg_ParseTuple(args, "O!OO!O!OOOOOOOO!O!",
                          &PyList_Type, &reviews, &root_name,
                          &PyTuple_Type, &segs, &PyTuple_Type, &dims_t,
                          &o_ids, &o_nums, &o_nids, &o_kinds, &o_keys,
                          &o_knums, &o_knids,
                          &PyDict_Type, &ids_dict,
                          &PyList_Type, &strs_list))
        return NULL;

    Fill f;
    memset(&f, 0, sizeof f);
    f.it.ids = ids_dict;
    f.it.strs = strs_list;
    f.ndims = (int)PyTuple_GET_SIZE(dims_t);
    if (f.ndims > 8) {
        PyErr_SetString(PyExc_ValueError, ">8 iteration axes");
        return NULL;
    }
    for (int d = 0; d < f.ndims; d++)
        f.dims[d] = PyLong_AsLong(PyTuple_GET_ITEM(dims_t, d));

    Py_buffer b_ids, b_nums, b_nids, b_kinds, b_keys, b_knums, b_knids;
    int ok = 1;
    int held_keys = 0;
    f.ids = buf_ptr(o_ids, &b_ids, &ok);
    f.nums = buf_ptr(o_nums, &b_nums, &ok);
    f.nids = buf_ptr(o_nids, &b_nids, &ok);
    f.kinds = buf_ptr(o_kinds, &b_kinds, &ok);
    if (ok && o_keys != Py_None) {
        f.keys = buf_ptr(o_keys, &b_keys, &ok);
        f.key_nums = buf_ptr(o_knums, &b_knums, &ok);
        f.key_nids = buf_ptr(o_knids, &b_knids, &ok);
        held_keys = ok;
    }
    PyObject **names = NULL;
    int *is_iter = NULL;
    PyObject *result = NULL;
    if (!ok || parse_segs(segs, &f, &names, &is_iter) < 0)
        goto done;
    f.names = names;
    f.is_iter = is_iter;

    Py_ssize_t n_reviews = PyList_GET_SIZE(reviews);
    for (Py_ssize_t n = 0; n < n_reviews; n++) {
        PyObject *review = PyList_GET_ITEM(reviews, n);
        PyObject *node = root_of(review, root_name);
        if (node == NULL) {
            if (PyErr_Occurred())
                goto done;
            continue;
        }
        if (fill_rec(&f, (long)n, node, 0, 0) < 0)
            goto done;
    }
    result = PyLong_FromLong(f.it.added);

done:
    PyMem_Free(names);
    PyMem_Free(is_iter);
    if (f.ids) PyBuffer_Release(&b_ids);
    if (f.nums) PyBuffer_Release(&b_nums);
    if (f.nids) PyBuffer_Release(&b_nids);
    if (f.kinds) PyBuffer_Release(&b_kinds);
    if (held_keys) {
        PyBuffer_Release(&b_keys);
        PyBuffer_Release(&b_knums);
        PyBuffer_Release(&b_knids);
    }
    return result;
}

static PyObject *py_fill_count(PyObject *self, PyObject *args)
{
    PyObject *reviews, *root_name, *segs, *o_counts, *o_kinds;
    if (!PyArg_ParseTuple(args, "O!OO!OO", &PyList_Type, &reviews,
                          &root_name, &PyTuple_Type, &segs, &o_counts,
                          &o_kinds))
        return NULL;
    Fill f;
    memset(&f, 0, sizeof f);
    PyObject **names = NULL;
    int *is_iter = NULL;
    if (parse_segs(segs, &f, &names, &is_iter) < 0)
        return NULL;
    f.names = names;
    f.is_iter = is_iter;
    Py_buffer b_counts, b_kinds;
    int ok = 1;
    float *counts = buf_ptr(o_counts, &b_counts, &ok);
    int8_t *kinds = buf_ptr(o_kinds, &b_kinds, &ok);
    PyObject *result = NULL;
    if (!ok)
        goto done;
    Py_ssize_t n_reviews = PyList_GET_SIZE(reviews);
    for (Py_ssize_t n = 0; n < n_reviews; n++) {
        PyObject *review = PyList_GET_ITEM(reviews, n);
        PyObject *node = root_of(review, root_name);
        int i = 0;
        node = descend_fields(node, &f, &i);
        if (PyErr_Occurred())
            goto done;
        if (node == NULL || i < f.nsegs)
            continue;
        int k = kind_of(node);
        kinds[n] = (int8_t)k;
        if (k == K_ARR || k == K_OBJ || k == K_STR) {
            Py_ssize_t len = PyObject_Length(node);
            if (len < 0)
                goto done;
            counts[n] = (float)len;
        }
    }
    result = Py_NewRef(Py_None);
done:
    PyMem_Free(names);
    PyMem_Free(is_iter);
    if (counts) PyBuffer_Release(&b_counts);
    if (kinds) PyBuffer_Release(&b_kinds);
    return result;
}

static PyObject *py_slot_sizes(PyObject *self, PyObject *args);

/* sizes prepass: max collection length per iter-seg position */
typedef struct {
    Fill *f;
    long maxes[8];
} Sizes;

static void sizes_rec(Sizes *sz, PyObject *node, int i, int depth)
{
    node = descend_fields(node, sz->f, &i);
    if (node == NULL || i >= sz->f->nsegs)
        return;
    Py_ssize_t n;
    if (PyDict_Check(node)) {
        n = PyDict_GET_SIZE(node);
        if ((long)n > sz->maxes[depth])
            sz->maxes[depth] = (long)n;
        PyObject *k, *v;
        Py_ssize_t pos = 0;
        while (PyDict_Next(node, &pos, &k, &v))
            sizes_rec(sz, v, i + 1, depth + 1);
    } else if (PyList_Check(node) || PyTuple_Check(node)) {
        n = PySequence_Fast_GET_SIZE(node);
        if ((long)n > sz->maxes[depth])
            sz->maxes[depth] = (long)n;
        PyObject **items = PySequence_Fast_ITEMS(node);
        for (Py_ssize_t j = 0; j < n; j++)
            sizes_rec(sz, items[j], i + 1, depth + 1);
    }
}

static PyObject *py_slot_sizes(PyObject *self, PyObject *args)
{
    PyObject *reviews, *root_name, *segs;
    if (!PyArg_ParseTuple(args, "O!OO!", &PyList_Type, &reviews,
                          &root_name, &PyTuple_Type, &segs))
        return NULL;
    Fill f;
    memset(&f, 0, sizeof f);
    PyObject **names = NULL;
    int *is_iter = NULL;
    if (parse_segs(segs, &f, &names, &is_iter) < 0)
        return NULL;
    f.names = names;
    f.is_iter = is_iter;
    int ndims = 0;
    for (int k = 0; k < f.nsegs; k++)
        if (is_iter[k])
            ndims++;
    if (ndims > 8) {
        PyMem_Free(names);
        PyMem_Free(is_iter);
        PyErr_SetString(PyExc_ValueError, ">8 iteration axes");
        return NULL;
    }
    Sizes sz;
    memset(&sz, 0, sizeof sz);
    sz.f = &f;
    Py_ssize_t n_reviews = PyList_GET_SIZE(reviews);
    for (Py_ssize_t n = 0; n < n_reviews; n++) {
        PyObject *review = PyList_GET_ITEM(reviews, n);
        PyObject *node = root_of(review, root_name);
        if (node != NULL)
            sizes_rec(&sz, node, 0, 0);
        if (PyErr_Occurred()) {
            PyMem_Free(names);
            PyMem_Free(is_iter);
            return NULL;
        }
    }
    PyObject *out = PyList_New(ndims);
    for (int d = 0; d < ndims; d++)
        PyList_SET_ITEM(out, d, PyLong_FromLong(sz.maxes[d]));
    PyMem_Free(names);
    PyMem_Free(is_iter);
    return out;
}

static PyMethodDef methods[] = {
    {"fill_slot", py_fill_slot, METH_VARARGS,
     "Fill one slot's cell arrays from a review batch."},
    {"fill_count", py_fill_count, METH_VARARGS,
     "Fill a count-mode slot."},
    {"slot_sizes", py_slot_sizes, METH_VARARGS,
     "Max collection length per iteration axis."},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef module = {
    PyModuleDef_HEAD_INIT, "_flatten",
    "Native extraction flattener (see gatekeeper_tpu/ir/features.py).",
    -1, methods,
};

PyMODINIT_FUNC PyInit__flatten(void)
{
    return PyModule_Create(&module);
}
