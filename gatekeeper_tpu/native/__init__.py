"""Native runtime components (C extensions).

flatten: the extraction flattener (ir/features.py's ingest hot path).
Built on demand from flatten.c with the system compiler into this
package directory; every consumer falls back to the pure-Python path
when no compiler or prebuilt artifact is available, so the framework
stays importable anywhere. Disable with GATEKEEPER_TPU_NATIVE=0."""

from __future__ import annotations

import logging
import os
import subprocess
import sysconfig
from typing import Optional

log = logging.getLogger("gatekeeper_tpu.native")

_DIR = os.path.dirname(__file__)
_flatten = None
_tried = False


def _build() -> Optional[str]:
    src = os.path.join(_DIR, "flatten.c")
    suffix = sysconfig.get_config_var("EXT_SUFFIX") or ".so"
    out = os.path.join(_DIR, "_flatten" + suffix)
    if os.path.exists(out) and \
            os.path.getmtime(out) >= os.path.getmtime(src):
        return out
    cc = os.environ.get("CC", "cc")
    include = sysconfig.get_path("include")
    # build to a temp path + atomic rename: two processes racing the
    # first build must never import a half-written artifact
    tmp = out + f".build-{os.getpid()}"
    cmd = [cc, "-O2", "-shared", "-fPIC", f"-I{include}", src, "-o", tmp]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=120)
    except (OSError, subprocess.TimeoutExpired) as e:
        log.info("native flatten build unavailable (%s); using the "
                 "Python extractor", e)
        return None
    if proc.returncode != 0:
        log.warning("native flatten build failed; using the Python "
                    "extractor:\n%s", proc.stderr[-2000:])
        return None
    os.replace(tmp, out)
    return out


def flatten_ext():
    """The _flatten extension module, or None (Python fallback)."""
    global _flatten, _tried
    if _tried:
        return _flatten
    _tried = True
    if os.environ.get("GATEKEEPER_TPU_NATIVE", "1") == "0":
        return None
    path = _build()
    if path is None:
        return None
    # package-qualified spec load: no sys.path mutation, and no collision
    # with any other module that happens to be named "_flatten"
    import importlib.util

    try:
        spec = importlib.util.spec_from_file_location(
            __name__ + "._flatten", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        _flatten = mod
    except ImportError as e:
        log.warning("native flatten import failed (%s); using the Python "
                    "extractor", e)
        _flatten = None
    return _flatten
