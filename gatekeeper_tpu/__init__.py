"""gatekeeper_tpu: TPU-native Kubernetes admission/audit policy engine."""

__version__ = "0.1.0"

# Lockset tracing (GATEKEEPER_TPU_LOCKTRACE=1) arms HERE, before any
# submodule import constructs a lock — so spawned engine children and
# frontend workers (`python -m gatekeeper_tpu.control.engine` / `.
# control.backplane`), which inherit the env var, trace their locks
# exactly like the pytest process does. A no-op when unarmed.
import os as _os

if _os.environ.get("GATEKEEPER_TPU_LOCKTRACE", "") not in ("", "0",
                                                           "false"):
    from .utils import locktrace as _locktrace

    _locktrace.install()
