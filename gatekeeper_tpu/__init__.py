"""gatekeeper_tpu: TPU-native Kubernetes admission/audit policy engine."""

__version__ = "0.1.0"
