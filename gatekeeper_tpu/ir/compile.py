"""Template compiler: Rego AST → vectorized Program.

Compiles the guard structure of each violation clause into the tensor IR
(ir/prog.py). Bindings that only feed the violation head (msg/details
construction — sprintf, get_message-style helpers) are NOT compiled: the
device program decides which (object, constraint) pairs fire, and the host
interpreter materializes exact messages for those pairs. The compiled
filter may over-fire (host re-check is authoritative) but must never
under-fire; anything outside the subset raises Uncompilable and the
template runs on the interpreter driver instead.

Supported subset (grown corpus-first, SURVEY.md §7 P0):
  * scalar guards over input.review.* / input.parameters.* paths
  * iteration over object lists/maps and parameter lists (up to 2 axes
    per slot), including `v := obj.labels[k]` map-entry iteration
  * set comprehensions over object keys/values and parameter values;
    set difference + count(s) {>,!=,==,<=} 0 patterns
  * string predicates startswith/endswith/contains/re_match with the
    pattern from parameters or constants (match-table rows)
  * array comprehensions of booleans + any() (allowedrepos pattern)
  * boolean helper functions (single package), inlined; `not` with
    locally-bound axes reduced inside the negation
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Any, Optional, Union

from ..rego import ast as A
from .prog import (
    And,
    Axis,
    Clause,
    Cmp,
    Const,
    Exists,
    Expr,
    Guard,
    MatchLookup,
    Not,
    Or,
    OrReduce,
    OVal,
    ObjSlotSpec,
    ParamSlotSpec,
    Program,
    PVal,
    Seg,
    SumReduce,
    Truthy,
)

_MATCH_OPS = {"startswith": "startswith", "endswith": "endswith",
              "contains": "contains", "re_match": "re_match"}
_MAX_INLINE_DEPTH = 8
_MAX_SLOT_AXES = 2


class Uncompilable(Exception):
    pass


# ---------------------------------------------------------------- symbolics


@dataclass(frozen=True)
class SPath:
    """A path into the review ("object"/"oldObject"/"review" roots) or the
    parameters document ("params" root). segs is a tuple of Seg."""

    root: str
    segs: tuple


@dataclass(frozen=True)
class SKey:
    """The key bound by a map-iteration bracket."""

    axis: str
    kind: str  # "obj" | "param"


@dataclass(frozen=True)
class SSet:
    """A set of scalars: object map keys, object list/map values, or
    parameter list values."""

    source: str  # "objkeys" | "objvals" | "paramvals"
    path: SPath  # path whose final seg is the iteration


@dataclass(frozen=True)
class SSetDiff:
    left: Union[SSet, "SSetDiff"]
    right: SSet


@dataclass(frozen=True)
class SBoolList:
    """[b | <param iteration>; b = pred] — axes local to the comprehension."""

    axes: tuple
    expr: Expr


@dataclass(frozen=True)
class SConst:
    value: Any


@dataclass(frozen=True)
class SExpr:
    expr: Expr
    # set-derived counts may double-count duplicates; they are only valid in
    # comparisons that reduce to emptiness tests (see _check_zero_only)
    zero_only: bool = False


Symbolic = Union[SPath, SKey, SSet, SSetDiff, SBoolList, SConst, SExpr]


class _Ctx:
    """Mutable compile state shared across a template's clauses."""

    def __init__(self, module: A.Module):
        self.module = module
        self.rules: dict[str, list[A.Rule]] = {}
        for r in module.rules:
            self.rules.setdefault(r.name, []).append(r)
        self.obj_slots: dict[tuple, ObjSlotRec] = {}
        self.param_slots: dict[tuple, ParamSlotRec] = {}
        self.axis_n = 0
        self.axes: dict[str, Axis] = {}

    def new_axis(self, kind: str) -> str:
        name = f"a{self.axis_n}"
        self.axis_n += 1
        return name


@dataclass
class ObjSlotRec:
    slot: int
    root: str
    segs: tuple
    mode: str


@dataclass
class ParamSlotRec:
    slot: int
    segs: tuple
    mode: str
    pattern_ops: set = field(default_factory=set)


def compile_template(module: A.Module, kind: str) -> Program:
    """Compile the (already rewritten) entry module of a template."""
    ctx = _Ctx(module)
    vio = ctx.rules.get("violation")
    if not vio:
        raise Uncompilable("no violation rule")
    clauses = []
    for rule in vio:
        clauses.append(_compile_clause(ctx, rule))
    obj_slots = tuple(
        ObjSlotSpec(slot=r.slot, root=r.root, segs=r.segs, mode=r.mode)
        for r in sorted(ctx.obj_slots.values(), key=lambda r: r.slot)
    )
    param_slots = tuple(
        ParamSlotSpec(slot=r.slot, segs=r.segs, mode=r.mode,
                      pattern_ops=tuple(sorted(r.pattern_ops)))
        for r in sorted(ctx.param_slots.values(), key=lambda r: r.slot)
    )
    return Program(kind=kind, obj_slots=obj_slots, param_slots=param_slots,
                   clauses=tuple(clauses),
                   axes=tuple(ctx.axes.values()))


# ------------------------------------------------------------------ clauses


def _head_vars(rule: A.Rule) -> set:
    out: set = set()
    if rule.key is not None:
        _collect_vars(rule.key, out)
    if rule.value is not None:
        _collect_vars(rule.value, out)
    return out


def _collect_vars(t, out: set) -> None:
    if isinstance(t, A.Var):
        out.add(t.name)
    elif isinstance(t, A.Ref):
        _collect_vars(t.base, out)
        for a in t.args:
            _collect_vars(a, out)
    elif isinstance(t, A.Call):
        for a in t.args:
            _collect_vars(a, out)
    elif isinstance(t, A.BinOp):
        _collect_vars(t.lhs, out)
        _collect_vars(t.rhs, out)
    elif isinstance(t, A.UnaryMinus):
        _collect_vars(t.term, out)
    elif isinstance(t, A.ArrayLit) or isinstance(t, A.SetLit):
        for x in t.items:
            _collect_vars(x, out)
    elif isinstance(t, A.ObjectLit):
        for k, v in t.items:
            _collect_vars(k, out)
            _collect_vars(v, out)
    elif isinstance(t, (A.ArrayCompr, A.SetCompr)):
        _collect_vars(t.head, out)
        for l in t.body:
            _collect_vars(l.expr, out)
    elif isinstance(t, A.ObjectCompr):
        _collect_vars(t.key, out)
        _collect_vars(t.value, out)
        for l in t.body:
            _collect_vars(l.expr, out)
    elif isinstance(t, (A.Assign, A.Unify)):
        _collect_vars(t.lhs, out)
        _collect_vars(t.rhs, out)


def _needed_vars(rule: A.Rule) -> set:
    """Vars needed by guard literals (directly or through needed bindings).
    Head-only bindings are skipped — the host re-derives them."""
    binds: list[tuple[str, set]] = []  # (bound var, refs)
    guard_refs: set = set()
    for lit in rule.body:
        e = lit.expr
        if isinstance(e, (A.Assign, A.Unify)) and isinstance(e.lhs, A.Var):
            refs: set = set()
            _collect_vars(e.rhs, refs)
            binds.append((e.lhs.name, refs))
        elif isinstance(e, A.SomeDecl):
            continue
        else:
            _collect_vars(e, guard_refs)
    needed = set(guard_refs)
    changed = True
    while changed:
        changed = False
        for var, refs in binds:
            if var in needed and not refs <= needed:
                needed |= refs
                changed = True
    return needed


def _compile_clause(ctx: _Ctx, rule: A.Rule) -> Clause:
    if rule.kind != "partial_set":
        raise Uncompilable("violation must be a partial-set rule")
    comp = _ClauseCompiler(ctx, _needed_vars(rule))
    for lit in rule.body:
        comp.literal(lit)
    return Clause(axes=tuple(comp.clause_axes), guards=tuple(comp.guards))


class _ClauseCompiler:
    def __init__(self, ctx: _Ctx, needed: set, env: Optional[dict] = None,
                 depth: int = 0):
        self.ctx = ctx
        self.needed = needed
        self.env: dict[str, Symbolic] = env if env is not None else {}
        self.clause_axes: list[Axis] = []
        self.guards: list[Guard] = []
        self.depth = depth

    # -------------------------------------------------------------- literals

    def literal(self, lit: A.Literal) -> None:
        if lit.withs:
            raise Uncompilable("with modifiers are not vectorizable")
        e = lit.expr
        if isinstance(e, A.SomeDecl):
            return
        if not lit.negated and isinstance(e, (A.Assign, A.Unify)) and \
                isinstance(e.lhs, A.Var):
            name = e.lhs.name
            if name not in self.needed and not name.startswith("$wc"):
                return  # head-only binding: host materializes
            self.env[name] = self.bind_rhs(e.rhs)
            return
        if not lit.negated and isinstance(e, (A.Assign, A.Unify)):
            raise Uncompilable(f"unsupported binding pattern {e!r}")
        # guard literal
        new_axes_start = len(self.clause_axes)
        expr = self.bool_expr(e)
        if lit.negated:
            local = tuple(a.name for a in self.clause_axes[new_axes_start:])
            del self.clause_axes[new_axes_start:]
            self.guards.append(Guard(expr=Not(expr, local_axes=local)))
        else:
            self.guards.append(Guard(expr=expr))

    # -------------------------------------------------------------- bindings

    def bind_rhs(self, t) -> Symbolic:
        if isinstance(t, A.Scalar):
            return SConst(t.value)
        if isinstance(t, A.Ref) or isinstance(t, A.Var):
            return self.resolve_ref(t)
        if isinstance(t, A.SetCompr):
            return self.set_compr(t)
        if isinstance(t, A.ArrayCompr):
            return self.bool_list_compr(t)
        if isinstance(t, A.BinOp) and t.op == "-":
            l = self.bind_rhs(t.lhs)
            r = self.bind_rhs(t.rhs)
            if isinstance(l, (SSet, SSetDiff)) and isinstance(r, SSet):
                return SSetDiff(l, r)
            raise Uncompilable("only set difference is supported for '-' bindings")
        if isinstance(t, A.Call):
            if tuple(t.fn) == ("count",):
                return self.count_symbolic(t.args[0])
            return SExpr(self.call_expr(t))
        raise Uncompilable(f"unsupported binding rhs {type(t).__name__}")

    # ------------------------------------------------------------------ refs

    def resolve_ref(self, t) -> Symbolic:
        """Resolve a Var/Ref term to a symbolic path/element."""
        if isinstance(t, A.Var):
            if t.name == "input":
                raise Uncompilable("bare input reference")
            if t.name in self.env:
                return self.env[t.name]
            raise Uncompilable(f"unbound var {t.name}")
        if not isinstance(t, A.Ref):
            raise Uncompilable(f"not a ref: {type(t).__name__}")
        if isinstance(t.base, A.Var) and t.base.name == "input":
            sym = None
            args = t.args
            if not args or not isinstance(args[0], A.Scalar):
                raise Uncompilable("dynamic input root")
            root0 = args[0].value
            if root0 == "review":
                if len(args) > 1 and isinstance(args[1], A.Scalar) and \
                        args[1].value in ("object", "oldObject"):
                    sym = SPath(root=args[1].value, segs=())
                    rest = args[2:]
                else:
                    sym = SPath(root="review", segs=())
                    rest = args[1:]
            elif root0 == "parameters":
                sym = SPath(root="params", segs=())
                rest = args[1:]
            else:
                raise Uncompilable(f"unsupported input root {root0!r}")
        else:
            sym = self.resolve_ref(t.base) if isinstance(t.base, A.Ref) else \
                self.resolve_var_base(t.base)
            rest = t.args
        return self.walk_segments(sym, rest)

    def resolve_var_base(self, base) -> Symbolic:
        if isinstance(base, A.Var):
            if base.name in self.env:
                return self.env[base.name]
            raise Uncompilable(f"unbound base var {base.name}")
        raise Uncompilable(f"unsupported ref base {type(base).__name__}")

    def walk_segments(self, sym: Symbolic, args: tuple) -> Symbolic:
        for arg in args:
            if not isinstance(sym, SPath):
                raise Uncompilable("cannot descend into non-path symbolic")
            if isinstance(arg, A.Scalar):
                if not isinstance(arg.value, str):
                    raise Uncompilable("non-string static bracket")
                sym = replace(sym, segs=sym.segs + (Seg("field", name=arg.value),))
            elif isinstance(arg, A.Var):
                name = arg.name
                if name in self.env:
                    bound = self.env[name]
                    if isinstance(bound, SKey):
                        raise Uncompilable(
                            "indexing by a previously-bound key is not supported"
                        )
                    raise Uncompilable("indexing by bound var")
                # fresh var or wildcard -> iteration axis
                axis = self.ctx.new_axis("obj")
                is_param = sym.root == "params"
                kind = "param" if is_param else "obj"
                prior_iters = any(s.kind == "iter" for s in sym.segs)
                sym = replace(sym, segs=sym.segs + (Seg("iter", axis=axis),))
                self._register_axis(axis, kind, sym)
                if not name.startswith("$wc"):
                    if prior_iters:
                        # extraction records keys for the innermost axis only
                        raise Uncompilable(
                            "key binding on an outer axis of a nested iteration"
                        )
                    self.env[name] = SKey(axis=axis, kind=kind)
            else:
                raise Uncompilable("composite bracket pattern")
        return sym

    def _register_axis(self, axis: str, kind: str, sym: SPath) -> None:
        """Axis presence is owned by the slot of the iterated collection."""
        if kind == "obj":
            rec = self._obj_slot(sym, mode="entries")
        else:
            rec = self._param_slot(sym, mode="list")
        ax = Axis(name=axis, kind=kind, slot=rec.slot)
        self.ctx.axes[axis] = ax
        self.clause_axes.append(ax)

    # ----------------------------------------------------------------- slots

    def _obj_slot(self, sym: SPath, mode: str) -> ObjSlotRec:
        n_axes = sum(1 for s in sym.segs if s.kind == "iter")
        if n_axes > _MAX_SLOT_AXES:
            raise Uncompilable("too many iteration axes in one path")
        key = (sym.root, sym.segs, mode)
        rec = self.ctx.obj_slots.get(key)
        if rec is None:
            rec = ObjSlotRec(slot=len(self.ctx.obj_slots) +
                             len(self.ctx.param_slots),
                             root=sym.root, segs=sym.segs, mode=mode)
            self.ctx.obj_slots[key] = rec
        return rec

    def _param_slot(self, sym: SPath, mode: str) -> ParamSlotRec:
        key = (sym.segs, mode)
        rec = self.ctx.param_slots.get(key)
        if rec is None:
            rec = ParamSlotRec(slot=len(self.ctx.obj_slots) +
                               len(self.ctx.param_slots),
                               segs=sym.segs, mode=mode)
            self.ctx.param_slots[key] = rec
        return rec

    # -------------------------------------------------------- comprehensions

    def set_compr(self, t: A.SetCompr) -> SSet:
        if not isinstance(t.head, A.Var):
            raise Uncompilable("set comprehension head must be a var")
        head = t.head.name
        if len(t.body) != 1:
            raise Uncompilable("multi-literal set comprehension")
        e = t.body[0].expr
        if t.body[0].negated:
            raise Uncompilable("negated comprehension body")
        sub = _ClauseCompiler(self.ctx, self.needed | {head},
                              env=dict(self.env), depth=self.depth)
        if isinstance(e, (A.Assign, A.Unify)) and isinstance(e.lhs, A.Var) \
                and e.lhs.name == head:
            sym = sub.resolve_ref(e.rhs)
            if not isinstance(sym, SPath):
                raise Uncompilable("comprehension rhs must be a path")
            if not sym.segs or not any(s.kind == "iter" for s in sym.segs):
                raise Uncompilable("comprehension must iterate")
            source = "paramvals" if sym.root == "params" else "objvals"
            return SSet(source=source, path=sym)
        if isinstance(e, A.Ref):
            # {k | obj.labels[k]} — key-set form
            sym = sub.resolve_ref(e)
            bound = sub.env.get(head)
            if isinstance(bound, SKey) and isinstance(sym, SPath):
                source = "paramvals" if sym.root == "params" else "objkeys"
                if source == "objkeys":
                    # path up to (and including) the iteration seg
                    return SSet(source="objkeys", path=sym)
                raise Uncompilable("param key-set comprehension")
            raise Uncompilable("unrecognized set comprehension form")
        raise Uncompilable("unsupported set comprehension body")

    def bool_list_compr(self, t: A.ArrayCompr) -> SBoolList:
        """[b | x = params.list[_]; ...guards...; b = pred(x)]"""
        if not isinstance(t.head, A.Var):
            raise Uncompilable("array comprehension head must be a var")
        head = t.head.name
        sub = _ClauseCompiler(self.ctx, self.needed | {head} | _body_vars(t.body),
                              env=dict(self.env), depth=self.depth)
        start_axes = len(sub.clause_axes)
        pred: Optional[Expr] = None
        for lit in t.body:
            e = lit.expr
            if not lit.negated and isinstance(e, (A.Assign, A.Unify)) and \
                    isinstance(e.lhs, A.Var) and e.lhs.name == head:
                pred = sub.bool_expr(e.rhs)
            else:
                sub.literal(lit)
        if pred is None:
            raise Uncompilable("array comprehension without boolean head binding")
        axes = tuple(a.name for a in sub.clause_axes[start_axes:])
        guards = [g.expr if not g.negated else Not(g.expr)
                  for g in sub.guards]
        expr = And(tuple(guards + [pred])) if guards else pred
        # comprehension axes do not escape into the clause
        for a in sub.clause_axes[start_axes:]:
            pass
        return SBoolList(axes=axes, expr=expr)

    # ----------------------------------------------------------- guard exprs

    def bool_expr(self, e) -> Expr:
        if isinstance(e, A.BinOp):
            return self.cmp_expr(e)
        if isinstance(e, A.Call):
            return self.call_expr(e)
        if isinstance(e, (A.Ref, A.Var)):
            return Truthy(self.value_expr(self.to_symbolic(e)))
        if isinstance(e, A.Scalar):
            # any scalar except `false` succeeds as a body literal (null too)
            return Const("bool", e.value is not False)
        if isinstance(e, (A.Assign, A.Unify)):
            # expression-position unification under `not`; only equality of
            # two compilable values is supported
            lhs = self.to_symbolic(e.lhs)
            rhs = self.to_symbolic(e.rhs)
            _check_zero_only(lhs, rhs, "eq")
            return self.eq_expr(lhs, rhs)
        raise Uncompilable(f"unsupported guard {type(e).__name__}")

    def to_symbolic(self, t) -> Symbolic:
        if isinstance(t, A.Var) and t.name in self.env:
            return self.env[t.name]
        if isinstance(t, A.Call):
            if tuple(t.fn) == ("count",):
                return self.count_symbolic(t.args[0])
            return SExpr(self.call_expr(t))
        return self.bind_rhs(t)

    def cmp_expr(self, e: A.BinOp) -> Expr:
        op_map = {"==": "eq", "!=": "ne", "<": "lt", "<=": "le",
                  ">": "gt", ">=": "ge"}
        if e.op not in op_map:
            raise Uncompilable(f"unsupported operator {e.op}")
        op = op_map[e.op]
        lhs = self.term_for_cmp(e.lhs)
        rhs = self.term_for_cmp(e.rhs)
        _check_zero_only(lhs, rhs, op)
        if op in ("eq", "ne"):
            # `a != b` is undefined (not true) when a side is undefined, so
            # it is its own comparison op rather than Not(eq)
            return self.eq_expr(lhs, rhs, op)
        lexpr = self.num_expr(lhs)
        rexpr = self.num_expr(rhs)
        return Cmp(op, lexpr, rexpr, dtype="num")

    def term_for_cmp(self, t) -> Symbolic:
        if isinstance(t, A.Call) and tuple(t.fn) == ("count",):
            return self.count_symbolic(t.args[0])
        return self.to_symbolic(t)

    def count_symbolic(self, arg) -> SExpr:
        sym = self.to_symbolic(arg)
        zero_only = isinstance(sym, (SSet, SSetDiff))
        return SExpr(self.count_of(sym), zero_only=zero_only)

    def eq_expr(self, lhs: Symbolic, rhs: Symbolic, op: str = "eq") -> Expr:
        if isinstance(lhs, SExpr) or isinstance(rhs, SExpr):
            l = self.num_expr(lhs)
            r = self.num_expr(rhs)
            return Cmp(op, l, r, dtype="num")
        return Cmp(op, self.value_expr(lhs), self.value_expr(rhs),
                   dtype="auto")

    def num_expr(self, sym: Symbolic) -> Expr:
        if isinstance(sym, SExpr):
            return sym.expr
        if isinstance(sym, SConst):
            if isinstance(sym.value, bool) or not isinstance(sym.value, (int, float)):
                raise Uncompilable("numeric comparison with non-number")
            return Const("num", float(sym.value))
        return self.value_expr(sym)

    def value_expr(self, sym: Symbolic) -> Expr:
        """Leaf device expr for a scalar symbolic value."""
        if isinstance(sym, SConst):
            v = sym.value
            if isinstance(v, bool):
                return Const("bool", v)
            if isinstance(v, (int, float)):
                return Const("num", float(v))
            if isinstance(v, str):
                return Const("str", v)
            raise Uncompilable(f"unsupported constant {v!r}")
        if isinstance(sym, SKey):
            if sym.kind == "param":
                ax = self.ctx.axes[sym.axis]
                return PVal(ax.slot, f="key", axis=sym.axis)
            ax = self.ctx.axes[sym.axis]
            return OVal(ax.slot, f="key", axis=sym.axis)
        if isinstance(sym, SExpr):
            return sym.expr
        if isinstance(sym, SPath):
            axes = [s.axis for s in sym.segs if s.kind == "iter"]
            axis = axes[-1] if axes else None
            if sym.root == "params":
                mode = "list" if axes else "scalar"
                rec = self._param_slot(sym, mode=mode)
                return PVal(rec.slot, f="val", axis=axis)
            mode = "entries" if axes else "scalar"
            rec = self._obj_slot(sym, mode=mode)
            return OVal(rec.slot, f="val", axis=axis)
        raise Uncompilable(f"cannot make a scalar of {type(sym).__name__}")

    # ----------------------------------------------------------------- calls

    def call_expr(self, e: A.Call) -> Expr:
        fn = tuple(e.fn)
        if fn == ("any",):
            sym = self.to_symbolic(e.args[0])
            if isinstance(sym, SBoolList):
                out = sym.expr
                for ax in reversed(sym.axes):
                    out = OrReduce(ax, out)
                return out
            raise Uncompilable("any() over non-comprehension")
        if fn == ("count",):
            raise Uncompilable("bare count() guard")
        if len(fn) == 1 and fn[0] in _MATCH_OPS:
            return self.match_call(_MATCH_OPS[fn[0]], e.args)
        if fn == ("glob", "match"):
            # glob.match(pattern, delimiters, value)
            if len(e.args) != 3:
                raise Uncompilable("glob.match arity")
            return self.match_call("glob", (e.args[0], e.args[2]))
        if len(fn) == 1 and fn[0] in self.ctx.rules:
            return self.inline_helper(fn[0], e.args)
        raise Uncompilable(f"unsupported call {'.'.join(fn)}")

    def match_call(self, op: str, args: tuple) -> Expr:
        """startswith(value, pattern) / re_match(pattern, value) etc."""
        if op in ("re_match", "glob"):
            pattern_t, value_t = args[0], args[1]
        else:
            value_t, pattern_t = args[0], args[1]
        value = self.to_symbolic(value_t)
        vexpr = self.value_expr(value)
        pattern = self.to_symbolic(pattern_t)
        if isinstance(pattern, SConst):
            if not isinstance(pattern.value, str):
                raise Uncompilable("pattern must be a string")
            row = Const("row", (op, pattern.value))
        elif isinstance(pattern, SPath) and pattern.root == "params":
            axes = [s.axis for s in pattern.segs if s.kind == "iter"]
            mode = "list" if axes else "scalar"
            rec = self._param_slot(pattern, mode=mode)
            rec.pattern_ops.add(op)
            row = PVal(rec.slot, f=f"row:{op}", axis=axes[-1] if axes else None)
        elif isinstance(pattern, SKey) and pattern.kind == "param":
            raise Uncompilable("param key as pattern")
        else:
            raise Uncompilable("pattern must come from parameters or constants")
        return MatchLookup(row=row, sid=vexpr)

    def count_of(self, sym: Symbolic) -> Expr:
        if isinstance(sym, SSetDiff):
            return self.setdiff_count(sym)
        if isinstance(sym, SSet):
            # |set comprehension| as an existence sum — dedup makes this
            # valid only for emptiness comparisons (zero_only enforced by
            # the caller via count_symbolic)
            if sym.source == "paramvals":
                return PVal(self._set_slot(sym), f="count")
            axis = self.ctx.new_axis("iter")
            elem = self._set_elem(sym, axis)
            return SumReduce(axis, Exists(elem))
        if isinstance(sym, SPath):
            # count(path): defined only when the collection exists
            if sym.root == "params":
                rec = self._param_slot(sym, mode="count")
                return PVal(rec.slot, f="count")
            rec = self._obj_slot(sym, mode="count")
            return OVal(rec.slot, f="count")
        raise Uncompilable("unsupported count() argument")

    def count_expr(self, arg) -> Expr:
        return self.count_symbolic(arg).expr

    def _set_slot(self, s: SSet) -> int:
        if s.source == "paramvals":
            return self._param_slot(s.path, mode="list").slot
        return self._obj_slot(s.path, mode="entries").slot

    def setdiff_count(self, sd: SSetDiff) -> Expr:
        """|A - B| as a device expr, valid for comparisons against 0 (set
        dedup does not change emptiness)."""
        if not isinstance(sd.left, SSet):
            raise Uncompilable("nested set difference")
        left, right = sd.left, sd.right
        l_axis = self.ctx.new_axis("iter")
        r_axis = self.ctx.new_axis("iter")
        lv = self._set_elem(left, l_axis)
        rv = self._set_elem(right, r_axis)
        member = OrReduce(r_axis, Cmp("eq", lv, rv, dtype="auto"))
        return SumReduce(l_axis, Not(member))

    def _set_elem(self, s: SSet, axis: str) -> Expr:
        slot = self._set_slot(s)
        rec_kind = "param" if s.source == "paramvals" else "obj"
        self.ctx.axes[axis] = Axis(name=axis, kind=rec_kind, slot=slot)
        if s.source == "paramvals":
            return PVal(slot, f="val", axis=axis)
        if s.source == "objkeys":
            return OVal(slot, f="key", axis=axis)
        return OVal(slot, f="val", axis=axis)

    # --------------------------------------------------------------- helpers

    def inline_helper(self, name: str, args: tuple) -> Expr:
        if self.depth >= _MAX_INLINE_DEPTH:
            raise Uncompilable(f"helper inline depth exceeded at {name}")
        rules = self.ctx.rules[name]
        actuals = [self.to_symbolic(a) for a in args]
        alts: list[Expr] = []
        for r in rules:
            if r.kind != "function":
                raise Uncompilable(f"{name} is not a function")
            if r.value is not None and not (
                isinstance(r.value, A.Scalar) and r.value.value is True
            ):
                raise Uncompilable(f"{name} is not a boolean helper")
            if len(r.args) != len(actuals):
                continue
            env = {}
            ok = True
            for formal, actual in zip(r.args, actuals):
                if not isinstance(formal, A.Var):
                    ok = False
                    break
                env[formal.name] = actual
            if not ok:
                raise Uncompilable(f"{name}: non-var formal args")
            sub = _ClauseCompiler(self.ctx, _body_vars(r.body) | self.needed,
                                  env=env, depth=self.depth + 1)
            for lit in r.body:
                sub.literal(lit)
            exprs = [g.expr if not g.negated else Not(g.expr)
                     for g in sub.guards]
            body = And(tuple(exprs)) if len(exprs) != 1 else exprs[0]
            # axes bound inside the helper are existential at its boundary
            for ax in sub.clause_axes:
                body = OrReduce(ax.name, body)
            alts.append(body)
        if not alts:
            raise Uncompilable(f"{name}: no applicable clauses")
        return Or(tuple(alts)) if len(alts) > 1 else alts[0]


# comparisons whose truth is unchanged by duplicate counting (emptiness
# tests); (op, const) with the count on the LEFT side
_ZERO_SAFE = {("gt", 0), ("ne", 0), ("eq", 0), ("le", 0), ("ge", 1), ("lt", 1)}
_FLIP = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le", "eq": "eq", "ne": "ne"}


def _check_zero_only(lhs: "Symbolic", rhs: "Symbolic", op: str) -> None:
    """Reject comparisons where a dedup-sensitive count could change the
    outcome (the never-under-fire invariant)."""
    for count_side, other, eff_op in ((lhs, rhs, op), (rhs, lhs, _FLIP[op])):
        if isinstance(count_side, SExpr) and count_side.zero_only:
            if not (isinstance(other, SConst) and
                    isinstance(other.value, (int, float)) and
                    not isinstance(other.value, bool) and
                    (eff_op, other.value) in _ZERO_SAFE):
                raise Uncompilable(
                    "set-derived counts may only be compared for emptiness "
                    "(e.g. count(x) > 0)"
                )


def _body_vars(body: tuple) -> set:
    out: set = set()
    for lit in body:
        _collect_vars(lit.expr, out)
    return out
