"""Template compiler: Rego AST → vectorized Program.

Compiles the guard structure of each violation clause into the tensor IR
(ir/prog.py). Bindings that only feed the violation head (msg/details
construction — sprintf, get_message-style helpers) are NOT compiled: the
device program decides which (object, constraint) pairs fire, and the host
interpreter materializes exact messages for those pairs. The compiled
filter may over-fire (host re-check is authoritative) but must never
under-fire; anything outside the subset raises Uncompilable and the
template runs on the interpreter driver instead.

Supported subset (grown corpus-first, SURVEY.md §7 P0):
  * scalar guards over input.review.* / input.parameters.* paths
  * iteration over object lists/maps and parameter lists (up to 2 axes
    per slot), including `v := obj.labels[k]` map-entry iteration and
    path segments indexed by const-bound vars (`spec[field][_]`)
  * local partial-set rules (`input_containers`) and path-valued helper
    functions, flattened by ir/specialize.py before compilation
  * set comprehensions (multi-literal filter bodies over the generator
    element, non-var heads via binding introduction, object AND
    parameter key-sets, const-head existence sets), set
    difference/intersection, membership against constants or computed
    values, and count comparisons that reduce to emptiness tests
  * string predicates startswith/endswith/contains/re_match/glob with
    patterns from parameters or constants (match-table rows), including
    pattern transforms (trim) applied at encode time
  * pure unary helper functions (canonify_cpu/mem) and unary builtins
    (to_number/lower/upper/trim_space) as vocab-indexed derived
    columns, and binary string helpers (path_matches) as
    interpreter-backed match-table rows (ops/derived.py)
  * pure builtins over all-constant arguments folded at compile time
    (concat/sprintf/... — computed bracket keys reduce to static paths)
  * boolean/value helper functions inlined with constant-formal
    unification; `not` with locally-bound axes reduced inside the negation

Anything outside raises Uncompilable(code, detail) with a code from the
stable bounded REASON_CODES taxonomy; the driver records it, /debug/
templates and gatekeeper_tpu_compile_fallback_total{reason} surface it.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Any, Optional, Union

from ..rego import ast as A
from .prog import (
    And,
    Arith,
    Axis,
    Clause,
    Cmp,
    Const,
    DerivedSpec,
    DerivedVal,
    Exists,
    Expr,
    Guard,
    K_ABSENT,
    K_ARR,
    K_STR,
    KindIs,
    MatchLookup,
    Not,
    Or,
    OrReduce,
    OVal,
    ObjSlotSpec,
    ParamSlotSpec,
    Program,
    PVal,
    Seg,
    SumReduce,
    Truthy,
)
from .specialize import specialize_module

_MATCH_OPS = {"startswith": "startswith", "endswith": "endswith",
              "contains": "contains", "re_match": "re_match"}
# pattern-side transforms applied at encode time (rego fn name -> tag)
_PATTERN_TRANSFORMS = {"trim": "trim", "lower": "lower", "upper": "upper",
                       "trim_prefix": "trim_prefix",
                       "trim_suffix": "trim_suffix"}
_CMP_OPS = {"==": "eq", "!=": "ne", "<": "lt", "<=": "le", ">": "gt",
            ">=": "ge"}
_ARITH_OPS = {"+": "add", "-": "sub", "*": "mul"}
# unary builtins lowered to vocab-indexed derived columns (ops/derived.py
# builtin_unary): evaluated once per interned vocab entry on the host,
# a single gather inside the [N, C] sweep
_BUILTIN_DERIVED = {"to_number", "lower", "upper", "trim_space"}
# pure builtins folded at compile time when every argument is constant
# (computed bracket keys like concat("/", ["apps", "v1"]) reduce to the
# static-field path the walker already handles)
_CONST_FOLDABLE = {"concat", "sprintf", "lower", "upper", "trim",
                   "trim_space", "trim_prefix", "trim_suffix", "replace",
                   "to_number", "format_int"}
_NOFOLD = object()
_MAX_INLINE_DEPTH = 8
_MAX_SLOT_AXES = 2


# Stable fallback-reason taxonomy. The metric
# `gatekeeper_tpu_compile_fallback_total{reason}` labels on these codes
# (bounded label set) and tests assert on codes, not prose — the detail
# string is free to change, the codes are an interface.
REASON_CODES = frozenset({
    # dense (elementwise) compiler
    "rule-shape",     # violation rule missing / not a partial set
    "axes",           # axis scoping: nesting depth, reduce-in-scope, keys
    "with-modifier",  # `with` is not vectorizable
    "binding",        # unsupported binding / destructure pattern
    "call",           # builtin or helper call outside the subset
    "unbound-var",    # reference to a var the compiler never bound
    "input-root",     # input.* path outside review/parameters
    "path",           # ref/bracket shape the path walker can't follow
    "set-op",         # set bracket/difference/intersection misuse
    "const",          # non-scalar constant
    "comprehension",  # comprehension form outside the subset
    "guard",          # guard/comparison expression outside the subset
    "count",          # count() misuse (incl. non-emptiness set counts)
    "pattern",        # match pattern not from parameters/constants
    "helper",         # helper function inlining failed
    "module-shape",   # template lib/entry module merge failed (driver)
    # inventory-join compiler
    "join-input",     # input reference outside input.review
    "join-generator", # inventory generator missing or malformed
    "join-with",      # `with` inside a join clause
    "join-identity",  # identity (not identical(...)) fn outside the shape
    "join-data",      # data read outside the inventory generator
    "join-mixed",     # mixed inv/rev literal that is not a join equality
    "join-shape",     # violation clause not recognizable as a join
    "internal",       # taxonomy drift guard — never raised deliberately
})


class Uncompilable(Exception):
    """A template (or clause) outside the device-compilable subset.

    `code` is one of REASON_CODES; `detail` carries the site-specific
    prose. str() renders "code: detail" — operators see both, metrics
    and tests key on the code alone."""

    def __init__(self, code: str, detail: str = ""):
        if code not in REASON_CODES:
            # taxonomy drift must not crash the compile path (the caller
            # treats Uncompilable as a routine fallback signal) — fold
            # the stray code into the detail under a stable label
            code, detail = "internal", f"{code}: {detail}" if detail else code
        self.code = code
        self.detail = detail
        super().__init__(f"{code}: {detail}" if detail else code)


# ---------------------------------------------------------------- symbolics


@dataclass(frozen=True)
class SPath:
    """A path into the review ("object"/"oldObject"/"review" roots) or the
    parameters document ("params" root). segs is a tuple of Seg."""

    root: str
    segs: tuple


@dataclass(frozen=True)
class SKey:
    """The key bound by a map-iteration bracket."""

    axis: str
    kind: str  # "obj" | "param"


@dataclass(frozen=True)
class SSet:
    """A set of scalars with optional element filter. source:
    "objkeys" | "objvals" | "paramvals" | "exists" (const-head compr whose
    elements don't matter, only non-emptiness). axes are the set-local
    iteration axes created during the comprehension walk; filter (over
    those axes) gates which elements belong."""

    source: str
    path: Optional[SPath]
    axes: tuple = ()
    filter: Optional[Expr] = None


@dataclass(frozen=True)
class SSetDiff:
    left: Union[SSet, "SSetDiff"]
    right: SSet


@dataclass(frozen=True)
class SSetInter:
    left: SSet
    right: SSet


@dataclass(frozen=True)
class SBoolList:
    """[b | <param iteration>; b = pred] — axes local to the comprehension."""

    axes: tuple
    expr: Expr


@dataclass(frozen=True)
class SSprintf:
    """sprintf("prefix%v", [arg]) held symbolically: device strings are
    interned ids, so the concatenation itself is not representable, but
    equality against it IS — strip the constant prefix from the other
    side via a derived column and compare the remainder (the apparmor
    annotation-key pattern, pod-security-policy/apparmor/src.rego)."""

    prefix: str
    arg: "Symbolic"


@dataclass(frozen=True)
class SConst:
    value: Any


@dataclass(frozen=True)
class SExpr:
    expr: Expr
    # set-derived counts may double-count duplicates; they are only valid in
    # comparisons that reduce to emptiness tests (see _check_zero_only)
    zero_only: bool = False


Symbolic = Union[SPath, SKey, SSet, SSetDiff, SSetInter, SBoolList, SConst,
                 SExpr]

# cell-producing device exprs (vs boolean / numeric-computed)
_CELL_EXPRS = (OVal, PVal, Const, DerivedVal)
_BOOL_EXPRS = (Cmp, MatchLookup, Truthy, Exists, And, Or, Not, OrReduce,
               KindIs)


class _Ctx:
    """Mutable compile state shared across a template's clauses."""

    def __init__(self, module: A.Module, kind: str):
        self.module = module
        self.kind = kind
        self.rules: dict[str, list[A.Rule]] = {}
        for r in module.rules:
            self.rules.setdefault(r.name, []).append(r)
        self.obj_slots: dict[tuple, ObjSlotRec] = {}
        self.param_slots: dict[tuple, ParamSlotRec] = {}
        self.axis_n = 0
        self.axes: dict[str, Axis] = {}
        self.derived: dict[tuple, int] = {}  # spec key -> col
        self.derived_specs: list[DerivedSpec] = []
        self.pred_ops: dict[str, str] = {}  # op name -> fn name

    def new_axis(self, kind: str) -> str:
        name = f"a{self.axis_n}"
        self.axis_n += 1
        return name

    def derived_col(self, kind: str, arg: str) -> int:
        key = (kind, arg)
        col = self.derived.get(key)
        if col is None:
            col = len(self.derived_specs)
            self.derived[key] = col
            self.derived_specs.append(DerivedSpec(col=col, kind=kind,
                                                  arg=arg))
        return col

    def rec_for_slot(self, slot: int):
        for rec in self.obj_slots.values():
            if rec.slot == slot:
                return rec
        for rec in self.param_slots.values():
            if rec.slot == slot:
                return rec
        return None


@dataclass
class ObjSlotRec:
    slot: int
    root: str
    segs: tuple
    mode: str


@dataclass
class ParamSlotRec:
    slot: int
    segs: tuple
    mode: str
    pattern_ops: set = field(default_factory=set)


def compile_template(module: A.Module, kind: str) -> Program:
    """Compile the (already rewritten) entry module of a template."""
    module = specialize_module(module)
    ctx = _Ctx(module, kind)
    vio = ctx.rules.get("violation")
    if not vio:
        raise Uncompilable("rule-shape", "no violation rule")
    clauses = []
    for rule in vio:
        clause = _compile_clause(ctx, rule)
        for g in clause.guards:
            _check_no_nested_axis(g.expr, set())
        clauses.append(clause)
    obj_slots = tuple(
        ObjSlotSpec(slot=r.slot, root=r.root, segs=r.segs, mode=r.mode)
        for r in sorted(ctx.obj_slots.values(), key=lambda r: r.slot)
    )
    param_slots = tuple(
        ParamSlotSpec(slot=r.slot, segs=r.segs, mode=r.mode,
                      pattern_ops=tuple(sorted(r.pattern_ops)))
        for r in sorted(ctx.param_slots.values(), key=lambda r: r.slot)
    )
    return Program(kind=kind, obj_slots=obj_slots, param_slots=param_slots,
                   clauses=tuple(clauses),
                   axes=tuple(ctx.axes.values()),
                   derived=tuple(ctx.derived_specs),
                   pred_ops=tuple(sorted(ctx.pred_ops.items())))


def _check_no_nested_axis(e: Expr, active: set) -> None:
    """An axis reduced inside its own reduction scope would silently
    collapse to a size-1 reduce — reject (sibling reuse is fine)."""
    if isinstance(e, (OrReduce, SumReduce)):
        if e.axis in active:
            raise Uncompilable("axes", f"axis {e.axis} reduced within its own scope")
        _check_no_nested_axis(e.e, active | {e.axis})
    elif isinstance(e, (And, Or)):
        for x in e.items:
            _check_no_nested_axis(x, active)
    elif isinstance(e, Not):
        _check_no_nested_axis(e.e, active | set(e.local_axes))
    elif isinstance(e, Cmp):
        _check_no_nested_axis(e.lhs, active)
        _check_no_nested_axis(e.rhs, active)
    elif isinstance(e, MatchLookup):
        _check_no_nested_axis(e.row, active)
        _check_no_nested_axis(e.sid, active)
    elif isinstance(e, (Truthy, Exists, KindIs)):
        _check_no_nested_axis(e.e, active)
    elif isinstance(e, DerivedVal):
        _check_no_nested_axis(e.base, active)
    elif isinstance(e, Arith):
        _check_no_nested_axis(e.lhs, active)
        _check_no_nested_axis(e.rhs, active)


# ------------------------------------------------------------------ clauses


def _collect_vars(t, out: set) -> None:
    if isinstance(t, A.Var):
        out.add(t.name)
    elif isinstance(t, A.Ref):
        _collect_vars(t.base, out)
        for a in t.args:
            _collect_vars(a, out)
    elif isinstance(t, A.Call):
        for a in t.args:
            _collect_vars(a, out)
    elif isinstance(t, A.BinOp):
        _collect_vars(t.lhs, out)
        _collect_vars(t.rhs, out)
    elif isinstance(t, A.UnaryMinus):
        _collect_vars(t.term, out)
    elif isinstance(t, A.ArrayLit) or isinstance(t, A.SetLit):
        for x in t.items:
            _collect_vars(x, out)
    elif isinstance(t, A.ObjectLit):
        for k, v in t.items:
            _collect_vars(k, out)
            _collect_vars(v, out)
    elif isinstance(t, (A.ArrayCompr, A.SetCompr)):
        _collect_vars(t.head, out)
        for l in t.body:
            _collect_vars(l.expr, out)
    elif isinstance(t, A.ObjectCompr):
        _collect_vars(t.key, out)
        _collect_vars(t.value, out)
        for l in t.body:
            _collect_vars(l.expr, out)
    elif isinstance(t, (A.Assign, A.Unify)):
        _collect_vars(t.lhs, out)
        _collect_vars(t.rhs, out)


def _needed_vars(rule: A.Rule) -> set:
    """Vars needed by guard literals (directly or through needed bindings).
    Head-only bindings are skipped — the host re-derives them."""
    binds: list[tuple[str, set]] = []  # (bound var, refs)
    guard_refs: set = set()
    for lit in rule.body:
        e = lit.expr
        if isinstance(e, (A.Assign, A.Unify)) and isinstance(e.lhs, A.Var):
            refs: set = set()
            _collect_vars(e.rhs, refs)
            binds.append((e.lhs.name, refs))
        elif isinstance(e, A.SomeDecl):
            continue
        else:
            _collect_vars(e, guard_refs)
    needed = set(guard_refs)
    changed = True
    while changed:
        changed = False
        for var, refs in binds:
            if var in needed and not refs <= needed:
                needed |= refs
                changed = True
    return needed


def _compile_clause(ctx: _Ctx, rule: A.Rule) -> Clause:
    if rule.kind != "partial_set":
        raise Uncompilable("rule-shape", "violation must be a partial-set rule")
    comp = _ClauseCompiler(ctx, _needed_vars(rule))
    for lit in rule.body:
        comp.literal(lit)
    return Clause(axes=tuple(comp.clause_axes), guards=tuple(comp.guards))


class _ClauseCompiler:
    def __init__(self, ctx: _Ctx, needed: set, env: Optional[dict] = None,
                 depth: int = 0):
        self.ctx = ctx
        self.needed = needed
        self.env: dict[str, Symbolic] = env if env is not None else {}
        self.clause_axes: list[Axis] = []
        self.guards: list[Guard] = []
        self.depth = depth
        # (axes, filter) scopes opened by in-guard set iteration; consumed
        # by the enclosing literal (existential wrap)
        self.pending_scopes: list[tuple[tuple, Optional[Expr]]] = []

    # -------------------------------------------------------------- literals

    def literal(self, lit: A.Literal) -> None:
        if lit.withs:
            raise Uncompilable("with-modifier", "with modifiers are not vectorizable")
        e = lit.expr
        if isinstance(e, A.SomeDecl):
            return
        if not lit.negated and isinstance(e, (A.Assign, A.Unify)) and \
                isinstance(e.lhs, A.Var):
            name = e.lhs.name
            if name not in self.needed and not name.startswith("$wc"):
                return  # head-only binding: host materializes
            self.env[name] = self.bind_rhs(e.rhs)
            if self.pending_scopes:
                raise Uncompilable("binding", "set iteration in binding position")
            return
        if not lit.negated and isinstance(e, (A.Assign, A.Unify)) and \
                isinstance(e.lhs, A.ArrayLit) and isinstance(e.rhs, A.Call) \
                and tuple(e.rhs.fn) == ("split",):
            self.split_destructure(e.lhs, e.rhs)
            return
        if not lit.negated and isinstance(e, (A.Assign, A.Unify)):
            raise Uncompilable("binding", f"unsupported binding pattern {e!r}")
        # guard literal
        new_axes_start = len(self.clause_axes)
        expr = self.bool_expr(e)
        expr = self._wrap_pending(expr)
        if lit.negated:
            local = tuple(a.name for a in self.clause_axes[new_axes_start:])
            del self.clause_axes[new_axes_start:]
            self.guards.append(Guard(expr=Not(expr, local_axes=local)))
        else:
            self.guards.append(Guard(expr=expr))

    def _wrap_pending(self, expr: Expr) -> Expr:
        """Existentially close set-iteration scopes opened inside a guard."""
        while self.pending_scopes:
            axes, filt = self.pending_scopes.pop()
            if filt is not None:
                expr = And((filt, expr))
            for ax in reversed(axes):
                expr = OrReduce(ax, expr)
        return expr

    def split_destructure(self, lhs: A.ArrayLit, call: A.Call) -> None:
        """[a, b] := split(x, "/") — parts as derived columns; the clause
        is undefined unless the split yields exactly len(lhs) parts."""
        if len(call.args) != 2 or not isinstance(call.args[1], A.Scalar) \
                or not isinstance(call.args[1].value, str):
            raise Uncompilable("binding", "split destructure needs a constant separator")
        sep = call.args[1].value
        base = self.value_expr(self.to_symbolic(call.args[0]))
        k = len(lhs.items)
        col0 = None
        for i, v in enumerate(lhs.items):
            if not isinstance(v, A.Var):
                raise Uncompilable("binding", "split destructure into non-vars")
            col = self.ctx.derived_col("split", f"{sep}|{i}|{k}")
            if i == 0:
                col0 = col
            if v.name in self.needed and not v.name.startswith("$wc"):
                self.env[v.name] = SExpr(DerivedVal(col, base))
        # arity guard: part 0 is defined iff the split has exactly k parts
        self.guards.append(Guard(expr=Exists(DerivedVal(col0, base))))

    # -------------------------------------------------------------- bindings

    def bind_rhs(self, t) -> Symbolic:
        if isinstance(t, A.Scalar):
            return SConst(t.value)
        if isinstance(t, A.ArrayLit) and not t.items:
            return SConst(())
        if isinstance(t, A.Ref) or isinstance(t, A.Var):
            return self.resolve_ref(t)
        if isinstance(t, A.SetCompr):
            return self.set_compr(t)
        if isinstance(t, A.ArrayCompr):
            return self.bool_list_compr(t)
        if isinstance(t, A.BinOp):
            if t.op in _CMP_OPS:
                return SExpr(self.cmp_expr(t))
            l = self.to_symbolic(t.lhs)
            r = self.to_symbolic(t.rhs)
            if t.op == "-" and isinstance(l, (SSet, SSetDiff)) and \
                    isinstance(r, SSet):
                return SSetDiff(l, r)
            if t.op == "&" and isinstance(l, SSet) and isinstance(r, SSet):
                return SSetInter(l, r)
            if t.op in _ARITH_OPS:
                return SExpr(Arith(_ARITH_OPS[t.op], self.num_expr(l),
                                   self.num_expr(r)))
            raise Uncompilable("binding", f"unsupported binary op {t.op} in binding")
        if isinstance(t, A.Call):
            if tuple(t.fn) == ("count",):
                return self.count_symbolic(t.args[0])
            return self.call_value(t)
        raise Uncompilable("binding", f"unsupported binding rhs {type(t).__name__}")

    def _const_term(self, a) -> Any:
        """The constant value of a term, or _NOFOLD."""
        if isinstance(a, A.Scalar):
            return a.value
        if isinstance(a, A.ArrayLit):
            items = [self._const_term(x) for x in a.items]
            return _NOFOLD if any(x is _NOFOLD for x in items) \
                else tuple(items)
        if isinstance(a, A.Var):
            bound = self.env.get(a.name)
            if isinstance(bound, SConst) and not isinstance(
                    bound.value, tuple):
                return bound.value
        return _NOFOLD

    def _const_fold(self, t: A.Call) -> Optional[SConst]:
        """Evaluate a pure builtin over all-constant arguments at compile
        time (via the exact host builtin, so folding can never diverge
        from the interpreter)."""
        fn = tuple(t.fn)
        if len(fn) != 1 or fn[0] not in _CONST_FOLDABLE:
            return None
        vals = [self._const_term(a) for a in t.args]
        if any(v is _NOFOLD for v in vals):
            return None
        from ..rego.builtins import BUILTINS

        b = BUILTINS.get(fn)
        if b is None:
            return None
        try:
            r = b(*vals)
        except Exception:
            return None  # undefined at compile time: normal paths decide
        if isinstance(r, (str, int, float, bool)):
            return SConst(r)
        return None

    def call_value(self, t: A.Call) -> Symbolic:
        """A call in value (binding) position."""
        folded = self._const_fold(t)
        if folded is not None:
            return folded
        fn = tuple(t.fn)
        if fn == ("sprintf",) and len(t.args) == 2 and \
                isinstance(t.args[0], A.Scalar) and \
                isinstance(t.args[0].value, str) and \
                isinstance(t.args[1], A.ArrayLit) and \
                len(t.args[1].items) == 1:
            fmt = t.args[0].value
            if fmt.endswith("%v") and fmt.count("%") == 1:
                return SSprintf(fmt[:-2],
                                self.to_symbolic(t.args[1].items[0]))
        if len(fn) == 1 and fn[0] in _BUILTIN_DERIVED and len(t.args) == 1:
            base = self.value_expr(self.to_symbolic(t.args[0]))
            if isinstance(base, _CELL_EXPRS):
                col = self.ctx.derived_col("builtin", fn[0])
                return SExpr(DerivedVal(col, base))
            raise Uncompilable("call", f"{fn[0]} over non-cell value")
        if len(fn) == 1 and fn[0] in self.ctx.rules:
            sym = self._unary_derived(fn[0], t.args)
            if sym is not None:
                return sym
        return SExpr(self.call_expr(t))

    # ------------------------------------------------------------------ refs

    def resolve_ref(self, t) -> Symbolic:
        """Resolve a Var/Ref term to a symbolic path/element."""
        if isinstance(t, A.Var):
            if t.name == "input":
                raise Uncompilable("input-root", "bare input reference")
            if t.name in self.env:
                return self.env[t.name]
            raise Uncompilable("unbound-var", f"unbound var {t.name}")
        if not isinstance(t, A.Ref):
            raise Uncompilable("path", f"not a ref: {type(t).__name__}")
        if isinstance(t.base, A.Var) and t.base.name == "input":
            sym = None
            args = t.args
            if not args or not isinstance(args[0], A.Scalar):
                raise Uncompilable("input-root", "dynamic input root")
            root0 = args[0].value
            if root0 == "review":
                if len(args) > 1 and isinstance(args[1], A.Scalar) and \
                        args[1].value in ("object", "oldObject"):
                    sym = SPath(root=args[1].value, segs=())
                    rest = args[2:]
                else:
                    sym = SPath(root="review", segs=())
                    rest = args[1:]
            elif root0 == "parameters":
                sym = SPath(root="params", segs=())
                rest = args[1:]
            else:
                raise Uncompilable("input-root", f"unsupported input root {root0!r}")
        else:
            sym = self.resolve_ref(t.base) if isinstance(t.base, A.Ref) else \
                self.resolve_var_base(t.base)
            rest = t.args
        return self.walk_segments(sym, rest)

    def resolve_var_base(self, base) -> Symbolic:
        if isinstance(base, A.Var):
            if base.name in self.env:
                return self.env[base.name]
            raise Uncompilable("unbound-var", f"unbound base var {base.name}")
        raise Uncompilable("path", f"unsupported ref base {type(base).__name__}")

    def walk_segments(self, sym: Symbolic, args: tuple) -> Symbolic:
        for ai, arg in enumerate(args):
            if isinstance(sym, SSet):
                return self.set_bracket(sym, arg, args[ai + 1:])
            if not isinstance(sym, SPath):
                raise Uncompilable("path", "cannot descend into non-path symbolic")
            if isinstance(arg, A.Scalar):
                if not isinstance(arg.value, str):
                    raise Uncompilable("path", "non-string static bracket")
                sym = replace(sym, segs=sym.segs + (Seg("field", name=arg.value),))
            elif isinstance(arg, A.Var):
                name = arg.name
                if name in self.env:
                    bound = self.env[name]
                    if isinstance(bound, SConst) and \
                            isinstance(bound.value, str):
                        # const-bound var: spec[field][_] with field from
                        # an object-head expansion or helper formal
                        sym = replace(sym, segs=sym.segs +
                                      (Seg("field", name=bound.value),))
                        continue
                    if isinstance(bound, SKey):
                        # re-indexing the SAME collection by the same key
                        # var (ranges[j].min … ranges[j].max) aliases the
                        # existing axis; correlated indexing across
                        # different collections is not vectorizable
                        ax = self.ctx.axes.get(bound.axis)
                        owner = self.ctx.rec_for_slot(ax.slot) if ax else None
                        here = sym.segs + (Seg("iter", axis=bound.axis),)
                        same_root = owner is not None and (
                            getattr(owner, "root", "params") ==
                            ("params" if sym.root == "params" else sym.root))
                        if same_root and tuple(owner.segs) == here:
                            sym = replace(sym, segs=here)
                            continue
                        # different collection: desugar to a fresh axis
                        # with a key(new) == key(bound) guard — the joint
                        # ∃-reduction is exactly the correlated lookup
                        sym = self._computed_key_bracket(sym, bound)
                        continue
                    # var bound to a scalar value: coll[k] with k computed
                    # elsewhere — desugar like any computed key
                    sym = self._computed_key_bracket(sym, bound)
                    continue
                # fresh var or wildcard -> iteration axis
                axis = self.ctx.new_axis("obj")
                is_param = sym.root == "params"
                kind = "param" if is_param else "obj"
                sym = replace(sym, segs=sym.segs + (Seg("iter", axis=axis),))
                self._register_axis(axis, kind, sym)
                if not name.startswith("$wc"):
                    self.env[name] = SKey(axis=axis, kind=kind)
            elif isinstance(arg, (A.Ref, A.Call)):
                # coll[<computed key>] (labels[spec.key], ...): desugar to
                # iteration over the collection plus a key == value guard
                sym = self._computed_key_bracket(sym, self.to_symbolic(arg))
            else:
                raise Uncompilable("path", "composite bracket pattern")
        return sym

    def _computed_key_bracket(self, sym: SPath, key_sym) -> SPath:
        """m[<computed>] -> iterate m's entries on a fresh axis, guarded by
        key(axis) == <computed>. The ∃-reduction over the axis then yields
        exactly the map-lookup semantics (absent key -> no binding)."""
        if isinstance(key_sym, SSprintf):
            # m[sprintf("prefix%v", [x])]: guard on
            # strip_prefix(key(axis)) == x. Exact iff when x is a string
            # (strip_prefix is UNDEF for non-prefixed keys); a numeric x
            # would render as its decimal string, which the sid equality
            # cannot see — those rows OVER-fire instead (host re-check
            # is authoritative), never under-fire
            arg_expr = self.value_expr(key_sym.arg)
            if not isinstance(arg_expr, _CELL_EXPRS):
                raise Uncompilable("call", "unsupported sprintf key argument")
            col = self.ctx.derived_col("strip_prefix", key_sym.prefix)
            axis = self.ctx.new_axis("obj")
            kind = "param" if sym.root == "params" else "obj"
            out = replace(sym, segs=sym.segs + (Seg("iter", axis=axis),))
            self._register_axis(axis, kind, out)
            key_of_axis = self.value_expr(SKey(axis=axis, kind=kind))
            self.guards.append(Guard(expr=Or((
                Cmp("eq", DerivedVal(col, key_of_axis), arg_expr,
                    dtype="auto"),
                Not(KindIs(arg_expr, (K_ABSENT, K_STR)), ()),
            ))))
            return out
        key_expr = self.value_expr(key_sym)
        if not isinstance(key_expr, _CELL_EXPRS):
            raise Uncompilable("path", "unsupported computed bracket key")
        axis = self.ctx.new_axis("obj")
        kind = "param" if sym.root == "params" else "obj"
        out = replace(sym, segs=sym.segs + (Seg("iter", axis=axis),))
        self._register_axis(axis, kind, out)
        key_of_axis = self.value_expr(SKey(axis=axis, kind=kind))
        self.guards.append(Guard(expr=Cmp("eq", key_of_axis, key_expr,
                                          dtype="auto")))
        return out

    def set_bracket(self, s: SSet, arg, rest: tuple) -> Symbolic:
        """boundset[x]: membership test (const) or element iteration
        (fresh var / wildcard)."""
        if rest:
            raise Uncompilable("set-op", "descending into set elements")
        if s.source == "exists":
            raise Uncompilable("set-op", "bracket on existence-only set")
        if isinstance(arg, A.Scalar):
            elem = self._set_elem_expr(s)
            test = Cmp("eq", elem, self._const_expr(arg.value), dtype="auto")
            if s.filter is not None:
                test = And((s.filter, test))
            for ax in reversed(s.axes):
                test = OrReduce(ax, test)
            return SExpr(test)
        if isinstance(arg, A.Var) and (arg.name.startswith("$wc")
                                       or arg.name not in self.env):
            # iteration: open an existential scope closed by the literal
            elem = self._set_elem_expr(s)
            self.pending_scopes.append((s.axes, s.filter))
            if not arg.name.startswith("$wc"):
                self.env[arg.name] = SExpr(elem)
            return SExpr(elem)
        if isinstance(arg, (A.Ref, A.Call, A.Var)):
            # membership test against a computed value:
            # boundset[input.review.object.metadata.name]
            val = self.value_expr(self.to_symbolic(arg))
            if isinstance(val, _CELL_EXPRS):
                elem = self._set_elem_expr(s)
                test = Cmp("eq", elem, val, dtype="auto")
                if s.filter is not None:
                    test = And((s.filter, test))
                for ax in reversed(s.axes):
                    test = OrReduce(ax, test)
                return SExpr(test)
        raise Uncompilable("set-op", "unsupported set bracket")

    def _const_expr(self, v) -> Expr:
        if isinstance(v, bool):
            return Const("bool", v)
        if isinstance(v, (int, float)):
            return Const("num", float(v))
        if isinstance(v, str):
            return Const("str", v)
        raise Uncompilable("const", f"unsupported constant {v!r}")

    def _register_axis(self, axis: str, kind: str, sym: SPath) -> None:
        """Axis presence is owned by the slot of the iterated collection."""
        if kind == "obj":
            rec = self._obj_slot(sym, mode="entries")
        else:
            rec = self._param_slot(sym, mode="list")
        ax = Axis(name=axis, kind=kind, slot=rec.slot)
        self.ctx.axes[axis] = ax
        self.clause_axes.append(ax)

    # ----------------------------------------------------------------- slots

    def _obj_slot(self, sym: SPath, mode: str) -> ObjSlotRec:
        n_axes = sum(1 for s in sym.segs if s.kind == "iter")
        if n_axes > _MAX_SLOT_AXES:
            raise Uncompilable("axes", "too many iteration axes in one path")
        key = (sym.root, sym.segs, mode)
        rec = self.ctx.obj_slots.get(key)
        if rec is None:
            rec = ObjSlotRec(slot=len(self.ctx.obj_slots) +
                             len(self.ctx.param_slots),
                             root=sym.root, segs=sym.segs, mode=mode)
            self.ctx.obj_slots[key] = rec
        return rec

    def _param_slot(self, sym: SPath, mode: str) -> ParamSlotRec:
        key = (sym.segs, mode)
        rec = self.ctx.param_slots.get(key)
        if rec is None:
            rec = ParamSlotRec(slot=len(self.ctx.obj_slots) +
                               len(self.ctx.param_slots),
                               segs=sym.segs, mode=mode)
            self.ctx.param_slots[key] = rec
        return rec

    # -------------------------------------------------------- comprehensions

    def set_compr(self, t: A.SetCompr) -> SSet:
        """{head | generator; ...filters...}. Forms:
          {x | x := path[_]}        — value set
          {x.f | x := path[_]}      — value set with a non-var head (the
                                      head path extends the generator's)
          {k | path[k]}             — key set, over OBJECT or PARAMETER
                                      maps
          {x | x = path[_][k]; ...} — nested value set
          {1 | guards}              — existence set (const head)
        Body literals may bind intermediate vars (the bindings land in
        the comprehension-local env, so multi-literal filter bodies can
        reference the generator element); remaining literals become the
        element filter."""
        sub = _ClauseCompiler(self.ctx, self.needed | _body_vars(t.body),
                              env=dict(self.env), depth=self.depth)
        head = t.head
        head_name = head.name if isinstance(head, A.Var) else None
        head_vars: set = set()
        _collect_vars(head, head_vars)
        sub.needed = sub.needed | head_vars
        # a head var already bound in the enclosing scope can never be a
        # key-iteration binder here (it would unify, not generate)
        head_preknown = head_name is not None and head_name in sub.env
        start_axes = len(sub.clause_axes)
        key_gen: Optional[tuple] = None  # (SKey binder, iterated SPath)
        filters: list[Expr] = []
        for lit in t.body:
            e = lit.expr
            if isinstance(e, A.SomeDecl):
                continue
            if not lit.negated and isinstance(e, (A.Assign, A.Unify)) and \
                    isinstance(e.lhs, A.Var) and e.lhs.name not in sub.env:
                # fresh-var binding; a unify against an ALREADY-bound var
                # falls through to the filter path as an equality (a
                # rebind would widen the set — an under-fire risk)
                sub.env[e.lhs.name] = sub.bind_rhs(e.rhs)
                if sub.pending_scopes:
                    raise Uncompilable("binding",
                                       "set iteration in binding position")
                continue
            if not lit.negated and isinstance(e, A.Ref) and \
                    key_gen is None and not head_preknown:
                # possible key-iteration generator: path[k] binding the
                # head var as a fresh map key
                sym = sub.resolve_ref(e)
                bound = sub.env.get(head_name) if head_name else None
                if isinstance(bound, SKey) and isinstance(sym, SPath):
                    key_gen = (bound, sym)
                    continue
                # plain ref guard: reuse the resolved symbolic (resolving
                # again via bool_expr would mint duplicate axes)
                if isinstance(sym, SExpr) and isinstance(sym.expr,
                                                         _BOOL_EXPRS):
                    expr = sym.expr
                else:
                    expr = Truthy(sub.value_expr(sym))
                filters.append(sub._wrap_pending(expr))
                continue
            # filter literal
            ax_mark = len(sub.clause_axes)
            expr = sub.bool_expr(e)
            expr = sub._wrap_pending(expr)
            if lit.negated:
                local = tuple(a.name for a in sub.clause_axes[ax_mark:])
                del sub.clause_axes[ax_mark:]
                expr = Not(expr, local_axes=local)
            filters.append(expr)
        axes = tuple(a.name for a in sub.clause_axes[start_axes:])
        filt = And(tuple(filters)) if len(filters) > 1 else (
            filters[0] if filters else None)
        if key_gen is not None:
            binder, sym = key_gen
            source = "paramkeys" if binder.kind == "param" else "objkeys"
            return SSet(source=source, path=sym, axes=axes, filter=filt)
        # value set: the head term resolved against the comprehension env
        # (a bound var, or a non-var head like c.image extending the
        # generator element's path)
        if head_name is not None or isinstance(head, (A.Ref, A.Call)):
            sym = sub.env.get(head_name) if head_name is not None else None
            if sym is None:
                try:
                    sym = sub.to_symbolic(head)
                except Uncompilable as e:
                    raise Uncompilable(
                        "comprehension",
                        f"unsupported comprehension head ({e.detail or e.code})")
            if isinstance(sym, SPath) and any(
                    s.kind == "iter" for s in sym.segs):
                source = "paramvals" if sym.root == "params" else "objvals"
                return SSet(source=source, path=sym, axes=axes, filter=filt)
            if isinstance(sym, SKey):
                # head is a key var bound through a v := m[k] literal
                ax = self.ctx.axes[sym.axis]
                rec = self.ctx.rec_for_slot(ax.slot)
                if rec is not None:
                    path = SPath(root=getattr(rec, "root", "params"),
                                 segs=tuple(rec.segs))
                    source = ("paramkeys" if sym.kind == "param"
                              else "objkeys")
                    return SSet(source=source, path=path, axes=axes,
                                filter=filt)
            raise Uncompilable("comprehension",
                               "comprehension generator must iterate")
        if isinstance(head, A.Scalar):
            # existence set: {1 | guards}
            return SSet(source="exists", path=None, axes=axes,
                        filter=filt)
        raise Uncompilable("comprehension",
                           "unrecognized set comprehension form")

    def bool_list_compr(self, t: A.ArrayCompr) -> SBoolList:
        """[b | x = params.list[_]; ...guards...; b = pred(x)]"""
        if not isinstance(t.head, A.Var):
            raise Uncompilable("comprehension", "array comprehension head must be a var")
        head = t.head.name
        sub = _ClauseCompiler(self.ctx, self.needed | {head} | _body_vars(t.body),
                              env=dict(self.env), depth=self.depth)
        start_axes = len(sub.clause_axes)
        pred: Optional[Expr] = None
        for lit in t.body:
            e = lit.expr
            if not lit.negated and isinstance(e, (A.Assign, A.Unify)) and \
                    isinstance(e.lhs, A.Var) and e.lhs.name == head:
                pred = sub.bool_expr(e.rhs)
                pred = sub._wrap_pending(pred)
            else:
                sub.literal(lit)
        if pred is None:
            raise Uncompilable("comprehension", "array comprehension without boolean head binding")
        axes = tuple(a.name for a in sub.clause_axes[start_axes:])
        guards = [g.expr if not g.negated else Not(g.expr)
                  for g in sub.guards]
        expr = And(tuple(guards + [pred])) if guards else pred
        return SBoolList(axes=axes, expr=expr)

    # ----------------------------------------------------------- guard exprs

    def bool_expr(self, e) -> Expr:
        if isinstance(e, A.BinOp):
            return self.cmp_expr(e)
        if isinstance(e, A.Call):
            return self.call_expr(e)
        if isinstance(e, (A.Ref, A.Var)):
            sym = self.to_symbolic(e)
            if isinstance(sym, SExpr) and isinstance(sym.expr, _BOOL_EXPRS):
                return sym.expr
            return Truthy(self.value_expr(sym))
        if isinstance(e, A.Scalar):
            # any scalar except `false` succeeds as a body literal (null too)
            return Const("bool", e.value is not False)
        if isinstance(e, (A.Assign, A.Unify)):
            # expression-position unification under `not`; only equality of
            # two compilable values is supported
            lhs = self.to_symbolic(e.lhs)
            rhs = self.to_symbolic(e.rhs)
            _check_zero_only(lhs, rhs, "eq")
            return self.eq_expr(lhs, rhs)
        raise Uncompilable("guard", f"unsupported guard {type(e).__name__}")

    def to_symbolic(self, t) -> Symbolic:
        if isinstance(t, A.Var) and t.name in self.env:
            return self.env[t.name]
        if isinstance(t, A.Call):
            if tuple(t.fn) == ("count",):
                return self.count_symbolic(t.args[0])
            return self.call_value(t)
        return self.bind_rhs(t)

    def cmp_expr(self, e: A.BinOp) -> Expr:
        if e.op not in _CMP_OPS:
            raise Uncompilable("guard", f"unsupported operator {e.op}")
        op = _CMP_OPS[e.op]
        # X == sprintf("prefix%v", [t]) — equality against a prefixed
        # string (apparmor annotation keys): strip the prefix via a derived
        # column and compare the remainder
        if op in ("eq", "ne"):
            for a, b in ((e.lhs, e.rhs), (e.rhs, e.lhs)):
                desugar = self._sprintf_eq(a, b, op)
                if desugar is not None:
                    return desugar
        lhs = self.term_for_cmp(e.lhs)
        rhs = self.term_for_cmp(e.rhs)
        _check_zero_only(lhs, rhs, op)
        if op in ("eq", "ne"):
            # `a != b` is undefined (not true) when a side is undefined, so
            # it is its own comparison op rather than Not(eq)
            return self.eq_expr(lhs, rhs, op)
        lexpr = self.num_expr(lhs)
        rexpr = self.num_expr(rhs)
        return Cmp(op, lexpr, rexpr, dtype="num")

    def _sprintf_eq(self, value_t, call_t, op: str) -> Optional[Expr]:
        if not (isinstance(call_t, A.Call)
                and tuple(call_t.fn) == ("sprintf",)
                and len(call_t.args) == 2
                and isinstance(call_t.args[0], A.Scalar)
                and isinstance(call_t.args[0].value, str)
                and isinstance(call_t.args[1], A.ArrayLit)
                and len(call_t.args[1].items) == 1):
            return None
        fmt = call_t.args[0].value
        if not fmt.endswith("%v") or fmt.count("%") != 1:
            return None
        if op != "eq":
            raise Uncompilable("guard", "sprintf equality only supports ==")
        prefix = fmt[:-2]
        col = self.ctx.derived_col("strip_prefix", prefix)
        base = self.value_expr(self.to_symbolic(value_t))
        arg = self.value_expr(self.to_symbolic(call_t.args[1].items[0]))
        return Cmp("eq", DerivedVal(col, base), arg, dtype="auto")

    def term_for_cmp(self, t) -> Symbolic:
        if isinstance(t, A.Call) and tuple(t.fn) == ("count",):
            return self.count_symbolic(t.args[0])
        return self.to_symbolic(t)

    def count_symbolic(self, arg) -> SExpr:
        sym = self.to_symbolic(arg)
        zero_only = isinstance(sym, (SSet, SSetDiff, SSetInter))
        return SExpr(self.count_of(sym), zero_only=zero_only)

    def eq_expr(self, lhs: Symbolic, rhs: Symbolic, op: str = "eq") -> Expr:
        for a, b in ((lhs, rhs), (rhs, lhs)):
            if isinstance(a, SSprintf):
                if op != "eq":
                    raise Uncompilable("guard", "sprintf equality only supports ==")
                col = self.ctx.derived_col("strip_prefix", a.prefix)
                other = self.value_expr(b)
                arg = self.value_expr(a.arg)
                # non-string args render to strings the sid equality
                # cannot see: over-fire those rows (host re-check)
                return Or((
                    Cmp("eq", DerivedVal(col, other), arg, dtype="auto"),
                    Not(KindIs(arg, (K_ABSENT, K_STR)), ()),
                ))
        # equality against the empty array: kind test + zero count
        for a, b in ((lhs, rhs), (rhs, lhs)):
            if isinstance(a, SConst) and a.value == ():
                if op != "eq":
                    raise Uncompilable("guard", "!= [] is not supported")
                if not isinstance(b, SPath):
                    raise Uncompilable("guard", "[] comparison needs a path")
                return And((KindIs(self.value_expr(b), (K_ARR,)),
                            Cmp("eq", self.count_of(b), Const("num", 0.0),
                                dtype="num")))
        l_num = isinstance(lhs, SExpr) and not isinstance(lhs.expr,
                                                          _CELL_EXPRS)
        r_num = isinstance(rhs, SExpr) and not isinstance(rhs.expr,
                                                          _CELL_EXPRS)
        if l_num or r_num:
            l = self.num_expr(lhs)
            r = self.num_expr(rhs)
            return Cmp(op, l, r, dtype="num")
        return Cmp(op, self.value_expr(lhs), self.value_expr(rhs),
                   dtype="auto")

    def num_expr(self, sym: Symbolic) -> Expr:
        if isinstance(sym, SExpr):
            return sym.expr
        if isinstance(sym, SConst):
            if isinstance(sym.value, bool) or not isinstance(sym.value, (int, float)):
                raise Uncompilable("guard", "numeric comparison with non-number")
            return Const("num", float(sym.value))
        return self.value_expr(sym)

    def value_expr(self, sym: Symbolic) -> Expr:
        """Leaf device expr for a scalar symbolic value."""
        if isinstance(sym, SConst):
            return self._const_expr(sym.value)
        if isinstance(sym, SKey):
            ax = self.ctx.axes[sym.axis]
            self._check_key_innermost(sym, ax)
            if sym.kind == "param":
                return PVal(ax.slot, f="key", axis=sym.axis)
            return OVal(ax.slot, f="key", axis=sym.axis)
        if isinstance(sym, SExpr):
            return sym.expr
        if isinstance(sym, SPath):
            axes = [s.axis for s in sym.segs if s.kind == "iter"]
            axis = axes[-1] if axes else None
            if sym.root == "params":
                mode = "list" if axes else "scalar"
                rec = self._param_slot(sym, mode=mode)
                return PVal(rec.slot, f="val", axis=axis)
            mode = "entries" if axes else "scalar"
            rec = self._obj_slot(sym, mode=mode)
            return OVal(rec.slot, f="val", axis=axis)
        raise Uncompilable("guard", f"cannot make a scalar of {type(sym).__name__}")

    def _check_key_innermost(self, sym: SKey, ax: Axis) -> None:
        """Extraction records keys for a slot's innermost axis only."""
        rec = self.ctx.rec_for_slot(ax.slot)
        if rec is None:
            return
        iters = [s.axis for s in rec.segs if s.kind == "iter"]
        if iters and iters[-1] != sym.axis:
            raise Uncompilable("axes", "key binding on a non-innermost axis")

    # ----------------------------------------------------------------- calls

    def call_expr(self, e: A.Call) -> Expr:
        fn = tuple(e.fn)
        if fn == ("any",):
            sym = self.to_symbolic(e.args[0])
            if isinstance(sym, SBoolList):
                out = sym.expr
                for ax in reversed(sym.axes):
                    out = OrReduce(ax, out)
                return out
            raise Uncompilable("call", "any() over non-comprehension")
        if fn == ("count",):
            raise Uncompilable("count", "bare count() guard")
        if len(fn) == 1 and fn[0] in _MATCH_OPS:
            return self.match_call(_MATCH_OPS[fn[0]], e.args)
        if fn == ("glob", "match"):
            # glob.match(pattern, delimiters, value)
            if len(e.args) != 3:
                raise Uncompilable("call", "glob.match arity")
            return self.match_call("glob", (e.args[0], e.args[2]))
        if len(fn) == 1 and fn[0] in self.ctx.rules:
            try:
                return self.inline_helper(fn[0], e.args)
            except Uncompilable:
                alt = self._fn_fallback(fn[0], e.args)
                if alt is not None:
                    return alt
                raise
        raise Uncompilable("call", f"unsupported call {'.'.join(fn)}")

    def _fn_fallback(self, name: str, args: tuple) -> Optional[Expr]:
        """Helper calls the inliner can't vectorize: unary fns become
        vocab-indexed derived columns; binary (value, param-pattern) fns
        become interpreter-backed match-table rows."""
        if len(args) == 1:
            sym = self._unary_derived(name, args)
            if sym is not None:
                return Truthy(sym.expr)
            return None
        if len(args) == 2:
            return self._binary_predicate(name, args)
        return None

    def _unary_derived(self, name: str, args: tuple) -> Optional[SExpr]:
        if len(args) != 1:
            return None
        rules = self.ctx.rules.get(name) or []
        if not rules or any(r.kind != "function" for r in rules):
            return None
        if any(_refs_input(r) for r in rules):
            return None  # not pure in its argument
        try:
            sym = self.to_symbolic(args[0])
            base = self.value_expr(sym)
        except Uncompilable:
            return None
        if not isinstance(base, _CELL_EXPRS):
            return None
        col = self.ctx.derived_col("fn", name)
        return SExpr(DerivedVal(col, base))

    def _binary_predicate(self, name: str, args: tuple) -> Optional[Expr]:
        rules = self.ctx.rules.get(name) or []
        if not rules or any(r.kind != "function" for r in rules):
            return None
        if any(_refs_input(r) for r in rules):
            return None
        syms = []
        try:
            syms = [self.to_symbolic(a) for a in args]
        except Uncompilable:
            return None
        # find the parameter-side (pattern) argument
        pat_i = None
        for i, s in enumerate(syms):
            if isinstance(s, SPath) and s.root == "params":
                pat_i = i
        if pat_i is None:
            return None
        val_i = 1 - pat_i
        pat_sym = syms[pat_i]
        # op encodes argument order so the host closure applies the fn
        # with the pattern in the right position
        op = f"pred:{self.ctx.kind}:{name}:{pat_i}"
        self.ctx.pred_ops[op] = name
        try:
            vexpr = self.value_expr(syms[val_i])
        except Uncompilable:
            return None
        row = self._pattern_row(op, pat_sym)
        return MatchLookup(row=row, sid=vexpr)

    def match_call(self, op: str, args: tuple) -> Expr:
        """startswith(value, pattern) / re_match(pattern, value) etc."""
        if op in ("re_match", "glob"):
            pattern_t, value_t = args[0], args[1]
        else:
            value_t, pattern_t = args[0], args[1]
        value = self.to_symbolic(value_t)
        vexpr = self.value_expr(value)
        # pattern-side transform: startswith(x, trim(params.p[_], "*"))
        while isinstance(pattern_t, A.Call) and len(pattern_t.fn) == 1 and \
                pattern_t.fn[0] in _PATTERN_TRANSFORMS:
            targs = pattern_t.args
            if len(targs) == 2 and isinstance(targs[1], A.Scalar) and \
                    isinstance(targs[1].value, str):
                from ..ops.strtab import escape_transform_arg
                op = (f"{op}@{_PATTERN_TRANSFORMS[pattern_t.fn[0]]}:"
                      f"{escape_transform_arg(targs[1].value)}")
                pattern_t = targs[0]
            elif len(targs) == 1:
                op = f"{op}@{_PATTERN_TRANSFORMS[pattern_t.fn[0]]}:"
                pattern_t = targs[0]
            else:
                raise Uncompilable("pattern", "unsupported pattern transform")
        pattern = self.to_symbolic(pattern_t)
        row = self._pattern_row(op, pattern)
        return MatchLookup(row=row, sid=vexpr)

    def _pattern_row(self, op: str, pattern: Symbolic) -> Expr:
        if isinstance(pattern, SConst):
            if not isinstance(pattern.value, str):
                raise Uncompilable("pattern", "pattern must be a string")
            return Const("row", (op, pattern.value))
        if isinstance(pattern, SPath) and pattern.root == "params":
            axes = [s.axis for s in pattern.segs if s.kind == "iter"]
            mode = "list" if axes else "scalar"
            rec = self._param_slot(pattern, mode=mode)
            rec.pattern_ops.add(op)
            return PVal(rec.slot, f=f"row:{op}",
                        axis=axes[-1] if axes else None)
        if isinstance(pattern, SKey) and pattern.kind == "param":
            raise Uncompilable("pattern", "param key as pattern")
        raise Uncompilable("pattern", "pattern must come from parameters or constants")

    # ------------------------------------------------------------------ sets

    def _set_elem_expr(self, s: SSet) -> Expr:
        slot = self._set_slot(s)
        axes = [seg.axis for seg in s.path.segs if seg.kind == "iter"]
        axis = axes[-1] if axes else None
        if s.source == "paramvals":
            return PVal(slot, f="val", axis=axis)
        if s.source == "paramkeys":
            return PVal(slot, f="key", axis=axis)
        if s.source == "objkeys":
            return OVal(slot, f="key", axis=axis)
        return OVal(slot, f="val", axis=axis)

    def _set_slot(self, s: SSet) -> int:
        if s.source in ("paramvals", "paramkeys"):
            return self._param_slot(s.path, mode="list").slot
        return self._obj_slot(s.path, mode="entries").slot

    def count_of(self, sym: Symbolic) -> Expr:
        if isinstance(sym, SSetDiff):
            return self.setdiff_count(sym)
        if isinstance(sym, SSetInter):
            return self.setinter_count(sym)
        if isinstance(sym, SSet):
            # |set| as an existence sum — dedup makes this valid only for
            # emptiness comparisons (zero_only enforced by count_symbolic)
            if sym.source == "exists":
                inner = sym.filter if sym.filter is not None else \
                    Const("bool", True)
                out = inner
                for ax in reversed(sym.axes):
                    out = SumReduce(ax, out)
                if not sym.axes:
                    raise Uncompilable("set-op", "existence set without iteration")
                return out
            if sym.source in ("paramvals", "paramkeys") and \
                    sym.filter is None:
                return PVal(self._set_slot(sym), f="count")
            elem = self._set_elem_expr(sym)
            inner: Expr = Exists(elem)
            if sym.filter is not None:
                inner = And((sym.filter, inner))
            out = inner
            for ax in reversed(sym.axes):
                out = SumReduce(ax, out)
            return out
        if isinstance(sym, SPath):
            # count(path): defined only when the collection exists
            if sym.root == "params":
                rec = self._param_slot(sym, mode="count")
                return PVal(rec.slot, f="count")
            rec = self._obj_slot(sym, mode="count")
            return OVal(rec.slot, f="count")
        raise Uncompilable("count", "unsupported count() argument")

    def _member_test(self, elem: Expr, s: SSet) -> Expr:
        """∃ element of s equal to elem."""
        other = self._set_elem_expr(s)
        test: Expr = Cmp("eq", elem, other, dtype="auto")
        if s.filter is not None:
            test = And((s.filter, test))
        for ax in reversed(s.axes):
            test = OrReduce(ax, test)
        return test

    def setdiff_count(self, sd: SSetDiff) -> Expr:
        """|A - B| as a device expr, valid for comparisons against 0 (set
        dedup does not change emptiness)."""
        if not isinstance(sd.left, SSet):
            raise Uncompilable("set-op", "nested set difference")
        left, right = sd.left, sd.right
        if left.source == "exists" or right.source == "exists":
            raise Uncompilable("set-op", "set difference over existence set")
        lv = self._set_elem_expr(left)
        inner: Expr = Not(self._member_test(lv, right))
        if left.filter is not None:
            inner = And((left.filter, inner))
        out = inner
        for ax in reversed(left.axes):
            out = SumReduce(ax, out)
        if not left.axes:
            raise Uncompilable("set-op", "set difference without iteration")
        return out

    def setinter_count(self, si: SSetInter) -> Expr:
        left, right = si.left, si.right
        if left.source == "exists" or right.source == "exists":
            raise Uncompilable("set-op", "set intersection over existence set")
        lv = self._set_elem_expr(left)
        inner: Expr = self._member_test(lv, right)
        if left.filter is not None:
            inner = And((left.filter, inner))
        out = inner
        for ax in reversed(left.axes):
            out = SumReduce(ax, out)
        if not left.axes:
            raise Uncompilable("set-op", "set intersection without iteration")
        return out

    # --------------------------------------------------------------- helpers

    def inline_helper(self, name: str, args: tuple) -> Expr:
        if self.depth >= _MAX_INLINE_DEPTH:
            raise Uncompilable("helper", f"helper inline depth exceeded at {name}")
        rules = self.ctx.rules[name]
        actuals = [self.to_symbolic(a) for a in args]
        alts: list[Expr] = []
        for r in rules:
            if r.kind != "function":
                raise Uncompilable("helper", f"{name} is not a function")
            if len(r.args) != len(actuals):
                continue
            env = {}
            const_guards: list[Expr] = []
            ok = True
            for formal, actual in zip(r.args, actuals):
                if isinstance(formal, A.Var):
                    env[formal.name] = actual
                elif isinstance(formal, A.Scalar):
                    # constant formal: unify against the actual value
                    if isinstance(actual, SConst):
                        if actual.value != formal.value:
                            ok = False
                            break
                    else:
                        const_guards.append(Cmp(
                            "eq", self.value_expr(actual),
                            self._const_expr(formal.value), dtype="auto"))
                else:
                    ok = False
                    break
            if not ok:
                continue
            sub = _ClauseCompiler(self.ctx, _body_vars(r.body) | self.needed,
                                  env=env, depth=self.depth + 1)
            for lit in r.body:
                sub.literal(lit)
            exprs = const_guards + [
                g.expr if not g.negated else Not(g.expr)
                for g in sub.guards]
            # head value: None/true => boolean helper; a var bound to a
            # boolean expr (res := u != 0) contributes that expr; any other
            # value contributes its truthiness
            val_expr = self._helper_value(r, sub)
            if val_expr is not None:
                exprs.append(val_expr)
            body = And(tuple(exprs)) if len(exprs) != 1 else exprs[0]
            # axes bound inside the helper are existential at its boundary
            for ax in sub.clause_axes:
                body = OrReduce(ax.name, body)
            alts.append(body)
        if not alts:
            raise Uncompilable("helper", f"{name}: no applicable clauses")
        return Or(tuple(alts)) if len(alts) > 1 else alts[0]

    def _helper_value(self, r: A.Rule, sub: "_ClauseCompiler"
                      ) -> Optional[Expr]:
        v = r.value
        if v is None:
            return None
        if isinstance(v, A.Scalar):
            if v.value is True:
                return None
            # falsy head constant can never succeed in boolean position
            return Const("bool", v.value is not False and v.value is not None)
        if isinstance(v, A.Var) and v.name in sub.env:
            sym = sub.env[v.name]
            if isinstance(sym, SExpr) and isinstance(sym.expr, _BOOL_EXPRS):
                return sym.expr
            return Truthy(sub.value_expr(sym))
        if isinstance(v, (A.Ref, A.Var)):
            return Truthy(sub.value_expr(sub.to_symbolic(v)))
        raise Uncompilable("helper", f"{r.name}: unsupported head value")


def _refs_input(r: A.Rule) -> bool:
    """Does the rule body reference input/data (i.e. not pure in args)?"""
    found = [False]

    def walk(t):
        if isinstance(t, A.Var) and t.name in ("input", "data"):
            found[0] = True
        elif isinstance(t, A.Ref):
            walk(t.base)
            for a in t.args:
                walk(a)
        elif isinstance(t, A.Call):
            for a in t.args:
                walk(a)
        elif isinstance(t, A.BinOp):
            walk(t.lhs)
            walk(t.rhs)
        elif isinstance(t, A.UnaryMinus):
            walk(t.term)
        elif isinstance(t, (A.ArrayLit, A.SetLit)):
            for x in t.items:
                walk(x)
        elif isinstance(t, A.ObjectLit):
            for k, v in t.items:
                walk(k)
                walk(v)
        elif isinstance(t, (A.ArrayCompr, A.SetCompr)):
            walk(t.head)
            for l in t.body:
                walk(l.expr)
        elif isinstance(t, A.ObjectCompr):
            walk(t.key)
            walk(t.value)
            for l in t.body:
                walk(l.expr)
        elif isinstance(t, (A.Assign, A.Unify)):
            walk(t.lhs)
            walk(t.rhs)

    for lit in r.body:
        walk(lit.expr)
    if r.value is not None:
        walk(r.value)
    return found[0]


# comparisons whose truth is unchanged by duplicate counting (emptiness
# tests); (op, const) with the count on the LEFT side
_ZERO_SAFE = {("gt", 0), ("ne", 0), ("eq", 0), ("le", 0), ("ge", 1), ("lt", 1)}
_FLIP = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le", "eq": "eq", "ne": "ne"}


def _check_zero_only(lhs: "Symbolic", rhs: "Symbolic", op: str) -> None:
    """Reject comparisons where a dedup-sensitive count could change the
    outcome (the never-under-fire invariant)."""
    for count_side, other, eff_op in ((lhs, rhs, op), (rhs, lhs, _FLIP[op])):
        if isinstance(count_side, SExpr) and count_side.zero_only:
            if not (isinstance(other, SConst) and
                    isinstance(other.value, (int, float)) and
                    not isinstance(other.value, bool) and
                    (eff_op, other.value) in _ZERO_SAFE):
                raise Uncompilable(
                    "count",
                    "set-derived counts may only be compared for "
                    "emptiness (e.g. count(x) > 0)")


def _body_vars(body: tuple) -> set:
    out: set = set()
    for lit in body:
        _collect_vars(lit.expr, out)
    return out
