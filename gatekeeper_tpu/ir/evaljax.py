"""JAX evaluation of compiled Programs.

One traced function per template answers `fires[N, C]` — whether any
violation clause fires for each (object, constraint) pair. Everything is
static-shape, elementwise + reduce over small iteration axes, so XLA fuses
the whole clause into a handful of kernels; the N axis is the data-parallel
dimension sharded across the device mesh (parallel/), and the C axis rides
along broadcast.

Tri-state semantics (undefined vs false) are carried as (value, defined)
pairs collapsed into literal "success" exactly where Rego collapses them
(body-literal boundaries); `!=`/comparison definedness mirrors OPA topdown.
The filter may over-fire — unknown-comparable kinds (arrays/objects)
compare as "maybe" — because the host re-check of firing pairs is
authoritative; it must never under-fire.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.strtab import MatchTables, StringTable
from ..parallel.mesh import shard_map_wrap as _shard_map_wrap
from .prog import (
    And,
    Arith,
    Cmp,
    Const,
    DerivedVal,
    Expr,
    K_ABSENT,
    K_FALSE,
    K_NUM,
    K_STR,
    KindIs,
    MatchLookup,
    Not,
    Or,
    OrReduce,
    OVal,
    Program,
    PVal,
    SumReduce,
    Truthy,
    Exists,
)


class Cell(NamedTuple):
    sid: Any  # int32 string ids
    num: Any  # f32 (approximate; ordering comparisons only)
    nid: Any  # int32 interned canonical-number ids (exact equality)
    kind: Any  # int8


class BPair(NamedTuple):
    """Three-valued literal result: lo = certainly succeeds, hi = possibly
    succeeds (lo implies hi). Uncertainty enters at leaves that only
    approximate Rego semantics (f32 ordering ties, composite equality) and
    must survive arbitrary negation — Not(lo, hi) = (~hi, ~lo) — so the
    final filter verdict (hi) over-fires and never under-fires; the host
    re-check of firing pairs is authoritative. Exact subtrees keep
    lo `is` hi, so XLA sees a single computation for them."""

    lo: Any
    hi: Any

    @staticmethod
    def exact(v) -> "BPair":
        return BPair(v, v)

    @property
    def is_exact(self) -> bool:
        return self.lo is self.hi


def _band(a: BPair, b: BPair) -> BPair:
    lo = jnp.logical_and(a.lo, b.lo)
    hi = lo if (a.is_exact and b.is_exact) else jnp.logical_and(a.hi, b.hi)
    return BPair(lo, hi)


def _bor(a: BPair, b: BPair) -> BPair:
    lo = jnp.logical_or(a.lo, b.lo)
    hi = lo if (a.is_exact and b.is_exact) else jnp.logical_or(a.hi, b.hi)
    return BPair(lo, hi)


def _bnot(a: BPair) -> BPair:
    hi = jnp.logical_not(a.lo)
    lo = hi if a.is_exact else jnp.logical_not(a.hi)
    return BPair(lo, hi)


def _bany(a: BPair, mask, axis: int) -> BPair:
    lo = jnp.any(jnp.logical_and(a.lo, mask), axis=axis, keepdims=True)
    hi = lo if a.is_exact else jnp.any(jnp.logical_and(a.hi, mask),
                                       axis=axis, keepdims=True)
    return BPair(lo, hi)


class EvalError(Exception):
    pass


def resolve_consts(program: Program, table: StringTable,
                   match: MatchTables) -> Program:
    """Replace string/pattern constants by interned ids / match rows.
    Must run before the match table is materialized."""
    from dataclasses import replace as dc_replace

    from ..ops.strtab import canon_num

    def fix(e):
        if isinstance(e, Const):
            if e.kind == "str":
                return Const("id", table.intern(e.value))
            if e.kind == "row":
                op, pattern = e.value
                return Const("rowidx", match.row(op, pattern))
            if e.kind == "num":
                return Const("numc",
                             (float(e.value), table.intern(canon_num(e.value))))
            return e
        if isinstance(e, Cmp):
            return Cmp(e.op, fix(e.lhs), fix(e.rhs), e.dtype)
        if isinstance(e, Arith):
            return Arith(e.op, fix(e.lhs), fix(e.rhs))
        if isinstance(e, MatchLookup):
            return MatchLookup(fix(e.row), fix(e.sid))
        if isinstance(e, Truthy):
            return Truthy(fix(e.e))
        if isinstance(e, Exists):
            return Exists(fix(e.e))
        if isinstance(e, And):
            return And(tuple(fix(x) for x in e.items))
        if isinstance(e, Or):
            return Or(tuple(fix(x) for x in e.items))
        if isinstance(e, Not):
            return Not(fix(e.e), e.local_axes)
        if isinstance(e, OrReduce):
            return OrReduce(e.axis, fix(e.e))
        if isinstance(e, SumReduce):
            return SumReduce(e.axis, fix(e.e))
        if isinstance(e, DerivedVal):
            return DerivedVal(e.col, fix(e.base))
        if isinstance(e, KindIs):
            return KindIs(fix(e.e), e.kinds)
        return e

    clauses = tuple(
        dc_replace(c, guards=tuple(
            dc_replace(g, expr=fix(g.expr)) for g in c.guards))
        for c in program.clauses
    )
    return dc_replace(program, clauses=clauses)


def _collect_axes(e: Expr, out: set) -> None:
    if isinstance(e, (OVal, PVal)):
        if e.axis:
            out.add(e.axis)
    elif isinstance(e, (Cmp, Arith)):
        _collect_axes(e.lhs, out)
        _collect_axes(e.rhs, out)
    elif isinstance(e, MatchLookup):
        _collect_axes(e.row, out)
        _collect_axes(e.sid, out)
    elif isinstance(e, (Truthy, Exists, KindIs)):
        _collect_axes(e.e, out)
    elif isinstance(e, DerivedVal):
        _collect_axes(e.base, out)
    elif isinstance(e, (And, Or)):
        for x in e.items:
            _collect_axes(x, out)
    elif isinstance(e, Not):
        _collect_axes(e.e, out)
        out.update(e.local_axes)
    elif isinstance(e, (OrReduce, SumReduce)):
        _collect_axes(e.e, out)
        out.add(e.axis)


class _ClausePlan:
    """Static layout for one clause: [N, ax0, ax1, ..., C].

    C (constraints, typically hundreds) sits in the minor-most dim so TPU
    (8,128) tiling pads it by <3%; small iteration axes live in the middle
    where padding is cheap. Putting axes minor-most instead costs up to
    32x in both memory and VPU lanes."""

    def __init__(self, program: Program, clause):
        axes: set = set(a.name for a in clause.axes)
        for g in clause.guards:
            _collect_axes(g.expr, axes)
        self.axis_order = sorted(axes)
        self.axpos = {a: 1 + i for i, a in enumerate(self.axis_order)}
        self.rank = 2 + len(self.axis_order)
        self.cpos = self.rank - 1
        self.clause = clause
        self.program = program
        self.axis_table = program.axis_table()
        self.slot_specs = {s.slot: s for s in program.obj_slots}
        self.pslot_specs = {s.slot: s for s in program.param_slots}

    # ---------------------------------------------------------- placement

    def _slot_axes(self, slot: int, is_param: bool, leaf_axis) -> list[str]:
        spec = self.pslot_specs[slot] if is_param else self.slot_specs[slot]
        seg_axes = [s.axis for s in spec.segs if s.kind == "iter"]
        if leaf_axis and (not seg_axes or seg_axes[-1] != leaf_axis):
            if len(seg_axes) > 1:
                raise EvalError("axis remap on multi-axis slot")
            seg_axes = [leaf_axis]
        return seg_axes

    def place_obj(self, arr, slot: int, leaf_axis) -> Any:
        """arr [N, K...] -> broadcastable [N, ...dims..., 1]."""
        seg_axes = self._slot_axes(slot, False, leaf_axis)
        shape = [arr.shape[0]] + [1] * (self.rank - 1)
        src_dims = list(arr.shape[1:])
        for ax, k in zip(seg_axes, src_dims):
            pos = self.axpos.get(ax)
            if pos is None:
                raise EvalError(f"axis {ax} not in clause layout")
            shape[pos] = k
        # arr dims are already in seg order == sorted insertion order is NOT
        # guaranteed; reshape works only if target positions are ascending
        pos_list = [self.axpos[a] for a in seg_axes]
        if pos_list != sorted(pos_list):
            order = np.argsort(pos_list)
            arr = jnp.transpose(arr, axes=[0] + [1 + int(i) for i in order])
        return jnp.reshape(arr, shape)

    def place_param(self, arr, slot: int, leaf_axis) -> Any:
        """arr [C] or [C, P] -> [1, ...dims..., C]."""
        shape = [1] * (self.rank - 1) + [arr.shape[0]]
        if arr.ndim == 2:
            seg_axes = self._slot_axes(slot, True, leaf_axis)
            if not seg_axes:
                raise EvalError("param array has P dim but no axis")
            shape[self.axpos[seg_axes[-1]]] = arr.shape[1]
            arr = jnp.moveaxis(arr, 0, -1)  # [P, C]
        return jnp.reshape(arr, shape)

    def presence(self, axis: str, feats: dict, params: dict) -> Any:
        ax = self.axis_table[axis]
        if ax.kind == "param":
            kinds = params[ax.slot]["kind"]
            return self.place_param(kinds, ax.slot, axis) != K_ABSENT
        kinds = feats[ax.slot]["kind"]
        return self.place_obj(kinds, ax.slot, axis) != K_ABSENT


def _eval_cell(plan: _ClausePlan, e: Expr, feats, params, derived) -> Cell:
    if isinstance(e, OVal):
        arrs = feats[e.slot]
        if e.f == "key":
            sid = plan.place_obj(arrs["key_id"], e.slot, e.axis)
            num = plan.place_obj(arrs["key_num"], e.slot, e.axis)
            kind = jnp.where(sid > 0, K_STR,
                             jnp.where(jnp.isnan(num), K_ABSENT, K_NUM)
                             ).astype(jnp.int8)
            return Cell(sid, num, plan.place_obj(arrs["key_nid"], e.slot,
                                                 e.axis), kind)
        return Cell(
            plan.place_obj(arrs["id"], e.slot, e.axis),
            plan.place_obj(arrs["num"], e.slot, e.axis),
            plan.place_obj(arrs["nid"], e.slot, e.axis),
            plan.place_obj(arrs["kind"], e.slot, e.axis),
        )
    if isinstance(e, PVal):
        arrs = params[e.slot]
        if e.f.startswith("row:"):
            return Cell(plan.place_param(arrs[e.f], e.slot, e.axis),
                        jnp.float32(0), jnp.int32(0), jnp.int8(0))
        if e.f == "key":
            sid = plan.place_param(arrs["key_id"], e.slot, e.axis)
            num = plan.place_param(arrs["key_num"], e.slot, e.axis)
            kind = jnp.where(sid > 0, K_STR,
                             jnp.where(jnp.isnan(num), K_ABSENT, K_NUM)
                             ).astype(jnp.int8)
            return Cell(sid, num, plan.place_param(arrs["key_nid"], e.slot,
                                                   e.axis), kind)
        return Cell(
            plan.place_param(arrs["id"], e.slot, e.axis),
            plan.place_param(arrs["num"], e.slot, e.axis),
            plan.place_param(arrs["nid"], e.slot, e.axis),
            plan.place_param(arrs["kind"], e.slot, e.axis),
        )
    if isinstance(e, Const):
        if e.kind == "id":
            return Cell(jnp.int32(e.value), jnp.float32(jnp.nan),
                        jnp.int32(0), jnp.int8(K_STR))
        if e.kind == "numc":
            num, nid = e.value
            return Cell(jnp.int32(0), jnp.float32(num), jnp.int32(nid),
                        jnp.int8(K_NUM))
        if e.kind == "bool":
            from .prog import K_TRUE
            return Cell(jnp.int32(0), jnp.float32(1.0 if e.value else 0.0),
                        jnp.int32(0),
                        jnp.int8(K_TRUE if e.value else K_FALSE))
        if e.kind == "rowidx":
            return Cell(jnp.int32(e.value), jnp.float32(0), jnp.int32(0),
                        jnp.int8(0))
        raise EvalError(f"unresolved const {e.kind}")
    if isinstance(e, DerivedVal):
        # one gather per cell: the unary function's image over the vocab,
        # indexed by the base cell's intern id (sid for strings, nid for
        # numbers; other kinds have no image -> absent)
        base = _eval_cell(plan, e.base, feats, params, derived)
        col = derived[e.col]
        is_str = base.kind == K_STR
        is_num = base.kind == K_NUM
        ix = jnp.where(is_str, base.sid, jnp.where(is_num, base.nid, 0))
        V = col["kind"].shape[0]
        ix = jnp.clip(ix, 0, V - 1)
        kind = jnp.where(jnp.logical_or(is_str, is_num),
                         col["kind"][ix], K_ABSENT).astype(jnp.int8)
        return Cell(col["sid"][ix], col["num"][ix], col["nid"][ix], kind)
    raise EvalError(f"not a value expr: {type(e).__name__}")


def _eval_num(plan: _ClausePlan, e: Expr, feats, params, table, derived):
    """-> (vlo, vhi, defined, nid-or-None): an interval [vlo, vhi]
    containing the true value.

    Cell values are points (vlo is vhi) carrying nid, the interned
    canonical-number id (exact-equality witness for f32 ties). Counts over
    uncertain inner literals widen to [sum(lo), sum(hi)]; plain counts are
    exact small ints (exact in f32)."""
    if isinstance(e, SumReduce):
        inner = _eval_bool(plan, e.e, feats, params, table, derived)
        pres = plan.presence(e.axis, feats, params)
        pos = plan.axpos[e.axis]
        slo = jnp.sum(jnp.where(jnp.logical_and(inner.lo, pres), 1.0, 0.0),
                      axis=pos, keepdims=True)
        shi = slo if inner.is_exact else jnp.sum(
            jnp.where(jnp.logical_and(inner.hi, pres), 1.0, 0.0),
            axis=pos, keepdims=True)
        return slo, shi, jnp.bool_(True), None
    if isinstance(e, OVal) and e.f in ("count", "countz"):
        arrs = feats[e.slot]
        val = plan.place_obj(arrs["count"], e.slot, None)
        if e.f == "countz":
            return val, val, jnp.bool_(True), None
        kinds = plan.place_obj(arrs["kind"], e.slot, None)
        return val, val, kinds != K_ABSENT, None
    if isinstance(e, PVal) and e.f == "count":
        arrs = params[e.slot]
        val = plan.place_param(arrs["count"], e.slot, None)
        return val, val, jnp.bool_(True), None
    if isinstance(e, Arith):
        llo, lhi, ld, _ = _eval_num(plan, e.lhs, feats, params, table, derived)
        rlo, rhi, rd, _ = _eval_num(plan, e.rhs, feats, params, table, derived)
        defined = jnp.logical_and(ld, rd)
        if e.op == "add":
            lo, hi = llo + rlo, lhi + rhi
        elif e.op == "sub":
            lo, hi = llo - rhi, lhi - rlo
        elif e.op == "mul":
            # interval product: extremes are among the endpoint products
            a, b, c, d = llo * rlo, llo * rhi, lhi * rlo, lhi * rhi
            lo = jnp.minimum(jnp.minimum(a, b), jnp.minimum(c, d))
            hi = jnp.maximum(jnp.maximum(a, b), jnp.maximum(c, d))
        else:
            raise EvalError(f"arith op {e.op}")
        # widen by the f32 rounding slack (each op contributes <=2^-24
        # relative error; 1e-5 covers deep expression chains) plus a tiny
        # absolute term so exact-zero results still straddle the true
        # value — threshold comparisons then over-fire, never under-fire
        # (the Arith docstring's contract in prog.py; host re-check exact)
        eps = jnp.float32(1e-5)
        tiny = jnp.float32(1e-30)
        lo = lo - (jnp.abs(lo) * eps + tiny)
        hi = hi + (jnp.abs(hi) * eps + tiny)
        # f32 overflow in chained ops can yield nan (inf - inf, 0 * inf),
        # which compares False on BOTH bounds — an under-fire. Scrub nan to
        # the unbounded interval; bare ±inf endpoints are already
        # conservative (lo=-inf claims nothing, hi=+inf over-fires).
        lo = jnp.where(jnp.isnan(lo), -jnp.inf, lo)
        hi = jnp.where(jnp.isnan(hi), jnp.inf, hi)
        return lo, hi, defined, None
    cell = _eval_cell(plan, e, feats, params, derived)
    return cell.num, cell.num, cell.kind == K_NUM, cell.nid


def _cell_eq(l: Cell, r: Cell):
    """(eq-ish value, defined). Arrays/objects compare as 'maybe' (True) —
    over-fire bias; host re-check is authoritative."""
    from .prog import K_ARR, K_OBJ

    defined = jnp.logical_and(l.kind != K_ABSENT, r.kind != K_ABSENT)
    same_kind = l.kind == r.kind
    str_eq = jnp.logical_and(l.kind == K_STR, l.sid == r.sid)
    num_eq = jnp.logical_and(l.kind == K_NUM, l.nid == r.nid)
    lit_eq = jnp.logical_and(same_kind,
                             jnp.logical_or(
                                 jnp.logical_or(str_eq, num_eq),
                                 jnp.logical_and(l.kind != K_STR,
                                                 l.kind != K_NUM)))
    maybe = jnp.logical_and(
        same_kind, jnp.logical_or(l.kind == K_ARR, l.kind == K_OBJ))
    return jnp.logical_or(lit_eq, maybe), defined, maybe


def _eval_bool(plan: _ClausePlan, e: Expr, feats, params, table, derived) -> BPair:
    """-> literal success BPair (bool arrays broadcastable to the clause
    rank). hi is the over-approximation the filter fires on; lo feeds
    negation so Not() can't turn over-fire into under-fire."""
    if isinstance(e, Cmp):
        if e.dtype == "auto":
            l = _eval_cell(plan, e.lhs, feats, params, derived)
            r = _eval_cell(plan, e.rhs, feats, params, derived)
            eq, defined, maybe = _cell_eq(l, r)
            if e.op == "eq":
                # eq includes maybe-equal composites; certain only without
                return BPair(
                    jnp.logical_and(defined,
                                    jnp.logical_and(eq, ~maybe)),
                    jnp.logical_and(defined, eq))
            if e.op == "ne":
                return BPair(
                    jnp.logical_and(defined, ~eq),
                    jnp.logical_and(defined, jnp.logical_or(~eq, maybe)))
            raise EvalError(f"auto cmp op {e.op}")
        lvlo, lvhi, ld, lnid = _eval_num(plan, e.lhs, feats, params, table, derived)
        rvlo, rvhi, rd, rnid = _eval_num(plan, e.rhs, feats, params, table, derived)
        defined = jnp.logical_and(ld, rd)
        # f32 carries ~24 bits of mantissa: values that differ beyond that
        # (e.g. 16777217 vs 16777216) compare equal, hiding the true
        # ordering. A "tie" = f32-equal point values whose exact canonical
        # number ids differ — the comparison outcome is then unknown, so
        # it lands in hi but not lo. nid 0 / None = computed value, exact.
        if lnid is not None and rnid is not None:
            tie = jnp.logical_and(
                lvlo == rvlo, jnp.logical_and(lnid != rnid,
                                              jnp.logical_and(lnid != 0,
                                                              rnid != 0)))
            exact = False
        else:
            tie = jnp.bool_(False)
            exact = (lvlo is lvhi) and (rvlo is rvhi)
        point = (lvlo is lvhi) and (rvlo is rvhi)
        # interval comparison: lo = certain for all values in the
        # intervals, hi = possible for some values (plus tie uncertainty)
        if e.op == "lt":
            lo, hi = lvhi < rvlo, jnp.logical_or(lvlo < rvhi, tie)
        elif e.op == "gt":
            lo, hi = lvlo > rvhi, jnp.logical_or(lvhi > rvlo, tie)
        elif e.op == "le":
            lo = jnp.logical_and(lvhi <= rvlo, ~tie)
            hi = lvlo <= rvhi
        elif e.op == "ge":
            lo = jnp.logical_and(lvlo >= rvhi, ~tie)
            hi = lvhi >= rvlo
        elif e.op == "eq":
            pts = (lvlo == rvlo) if point else jnp.logical_and(
                lvlo == lvhi, jnp.logical_and(rvlo == rvhi, lvlo == rvlo))
            lo = jnp.logical_and(pts, ~tie)
            hi = jnp.logical_and(lvlo <= rvhi, rvlo <= lvhi)  # overlap
        elif e.op == "ne":
            pts = (lvlo == rvlo) if point else jnp.logical_and(
                lvlo == lvhi, jnp.logical_and(rvlo == rvhi, lvlo == rvlo))
            lo = jnp.logical_or(lvhi < rvlo, rvhi < lvlo)  # disjoint
            hi = jnp.logical_not(jnp.logical_and(pts, ~tie))
        else:
            raise EvalError(f"cmp op {e.op}")
        lo = jnp.logical_and(defined, lo)
        hi = lo if exact else jnp.logical_and(defined, hi)
        return BPair(lo, hi)
    if isinstance(e, MatchLookup):
        # table is bit-packed [V, W] uint32 (strtab.materialize_packed):
        # gather the string's row-bitmask words (1-D gather) and test the
        # pattern row's bit — a single fused int32 AND per (obj, constraint)
        # cell, no extra broadcast dim and no 2-D fancy-index tuples.
        row = _eval_cell(plan, e.row, feats, params, derived).sid
        sv = _eval_cell(plan, e.sid, feats, params, derived)
        defined = jnp.logical_and(row >= 0, sv.kind == K_STR)
        V, W = table.shape
        r = jnp.clip(row, 0, W * 32 - 1)
        s = jnp.clip(sv.sid, 0, V - 1)
        per_string = jnp.take(table, s, axis=0)  # [..., W]
        if W == 1:
            word = per_string[..., 0]
        else:
            word_idx = (r >> 5)[..., None]
            sel = word_idx == jnp.arange(W)
            word = jnp.sum(jnp.where(sel, per_string, 0), axis=-1,
                           dtype=jnp.uint32)
        rbit = (jnp.uint32(1) << (r & 31).astype(jnp.uint32))
        hit = (word & rbit) != 0
        return BPair.exact(jnp.logical_and(defined, hit))
    if isinstance(e, Truthy):
        c = _eval_cell(plan, e.e, feats, params, derived)
        return BPair.exact(jnp.logical_and(c.kind != K_ABSENT,
                                           c.kind != K_FALSE))
    if isinstance(e, Exists):
        c = _eval_cell(plan, e.e, feats, params, derived)
        return BPair.exact(c.kind != K_ABSENT)
    if isinstance(e, KindIs):
        c = _eval_cell(plan, e.e, feats, params, derived)
        hit = None
        for k in e.kinds:
            t = c.kind == k
            hit = t if hit is None else jnp.logical_or(hit, t)
        return BPair.exact(hit if hit is not None else jnp.bool_(False))
    if isinstance(e, And):
        out = None
        for x in e.items:
            v = _eval_bool(plan, x, feats, params, table, derived)
            out = v if out is None else _band(out, v)
        return out if out is not None else BPair.exact(jnp.bool_(True))
    if isinstance(e, Or):
        out = None
        for x in e.items:
            v = _eval_bool(plan, x, feats, params, table, derived)
            out = v if out is None else _bor(out, v)
        return out if out is not None else BPair.exact(jnp.bool_(False))
    if isinstance(e, Not):
        inner = _eval_bool(plan, e.e, feats, params, table, derived)
        for ax in e.local_axes:
            pres = plan.presence(ax, feats, params)
            inner = _bany(inner, pres, plan.axpos[ax])
        return _bnot(inner)
    if isinstance(e, OrReduce):
        inner = _eval_bool(plan, e.e, feats, params, table, derived)
        pres = plan.presence(e.axis, feats, params)
        return _bany(inner, pres, plan.axpos[e.axis])
    if isinstance(e, SumReduce):
        slo, shi, _, _ = _eval_num(plan, e, feats, params, table, derived)
        lo = slo != 0
        hi = lo if shi is slo else shi != 0
        return BPair(lo, hi)
    if isinstance(e, Const):
        if e.kind == "bool":
            return BPair.exact(jnp.bool_(bool(e.value)))
        # any non-false scalar literal succeeds
        return BPair.exact(jnp.bool_(True))
    raise EvalError(f"unsupported expr {type(e).__name__}")


def _eval_clause(plan: _ClausePlan, feats, params, table, derived):
    pair = None
    for g in plan.clause.guards:
        v = _eval_bool(plan, g.expr, feats, params, table, derived)
        if g.negated:  # guards are pre-wrapped in Not by the compiler
            v = _bnot(v)
        pair = v if pair is None else _band(pair, v)
    if pair is None:
        pair = BPair.exact(jnp.bool_(True))
    # the filter verdict is the over-approximation: possibly-fires
    success = pair.hi
    for a in plan.clause.axes:
        success = jnp.logical_and(success,
                                  plan.presence(a.name, feats, params))
    # broadcast to full rank before reducing (success may be size-1 dims)
    n = 1
    c = 1
    for slot_arrs in feats.values():
        for arr in slot_arrs.values():
            n = max(n, arr.shape[0])
    for slot_arrs in params.values():
        for arr in slot_arrs.values():
            c = max(c, arr.shape[0])
    # reduce FIRST, broadcast last: materializing the full-rank success
    # tensor would carry tiny minor dims that TPU layouts pad to (8,128)
    # tiles — reducing lets XLA fuse the whole clause into the reduction.
    # layout is [N, axes..., C]; reduce the middle dims.
    if success.ndim > 2:
        success = jnp.any(success, axis=tuple(range(1, success.ndim - 1)))
    if success.ndim == 1:
        success = success[None, :]
    return jnp.broadcast_to(success, (n, c))


def _param_c(params: dict) -> int:
    """Leading C dim of the first param array (1 for parameterless
    programs, whose device verdicts are constraint-independent)."""
    for arrs in params.values():
        for a in arrs.values():
            return a.shape[0]
    return 1


class _EagerPairs:
    """Dispatch handle for workloads below the slab threshold: the
    monolithic packed sweep + row gather dispatch at CONSTRUCTION (so a
    multi-template audit overlaps every kind's device work); only the
    dense-small / parameter-only paths stay lazy (they are
    latency-trivial)."""

    def __init__(self, ct, feats, params, table, derived, chunk, n_true,
                 n_cons=None):
        self._ct = ct
        self._args = (feats, params, table, derived, chunk, n_true,
                      n_cons)
        self._st = None
        if feats:
            n_feat = next(iter(next(iter(
                feats.values())).values())).shape[0]
            n = n_feat if n_true is None else min(n_feat, n_true)
            if n_feat > chunk:
                c = _param_c(params)
                if n_cons is not None:
                    c = min(c, n_cons)
                self._st = ct._pairs_dispatch_mono(
                    feats, params, table, derived, chunk, n, c)

    def pairs(self):
        if self._st is not None:
            yield self._ct._pairs_consume_mono(self._st)
            return
        feats, params, table, derived, chunk, n_true, n_cons = self._args
        yield self._ct.fires_pairs(feats, params, table, derived,
                                   chunk=chunk, n_true=n_true,
                                   n_cons=n_cons)


class _SlabPairs:
    """Pending slab kernels; .pairs() syncs in dispatch order with the
    capacity-retry loop."""

    def __init__(self, ct, pend, feats, params, table, derived, chunk,
                 slab, n, c):
        self._ct = ct
        self._pend = pend
        self._args = (feats, params, table, derived, chunk, slab, n, c)

    def pairs(self):
        ct = self._ct
        feats, params, table, derived, chunk, slab, n, c = self._args
        for k, (used_pcap, dev_arr) in enumerate(self._pend):
            arr = np.asarray(dev_arr)  # sync point + single fetch
            pcount = int(arr[0, 0])
            while pcount > used_pcap:
                used_pcap = max(used_pcap,
                                1 << (pcount - 1).bit_length())
                fn2 = ct._slab_pairs_jit(chunk, slab, used_pcap)
                arr = np.asarray(fn2(feats, params, table, derived,
                                     np.int32(k * slab), np.int32(n),
                                     np.int32(c)))
                pcount = int(arr[0, 0])
            ct._rows_cap = max(ct._rows_cap,
                               (1 << (pcount - 1).bit_length())
                               if pcount > 1 else 256)
            yield _decode_pair_blocks(arr, pcount)


def _decode_pair_blocks(arr: np.ndarray, pcount: int):
    """(rows, cols) from one device pair block: the kernels decode the
    bit-packed verdicts to dense (row, constraint) index pairs ON
    DEVICE (row-major, invalid columns already masked), so the host
    does no bitmask unpacking at all — two int64 casts and a slice."""
    if pcount == 0:
        z = np.zeros(0, dtype=np.int64)
        return z, z.copy()
    body = arr[1:1 + pcount]
    return body[:, 0].astype(np.int64), body[:, 1].astype(np.int64)


def _pair_expand(packed, valid_rows, row0, c, pcap):
    """Shared device tail for every pair kernel: masked bit-packed
    verdicts [R, W] -> one [pcap+1, 2] uint32 block — header row
    carrying the true pair count, then (global row, constraint) index
    pairs in row-major order (fixed-capacity nonzero over the unpacked
    bit matrix; jnp.nonzero's ascending flat order IS row-major).
    `valid_rows` masks extraction padding / slab overlap; `row0` is the
    block's global row offset; `c` (traced) masks the C-bucket padding
    columns so library edits inside a bucket still hit this program."""
    r, w = packed.shape
    w32 = w * 32
    packed = jnp.where(valid_rows[:, None], packed, jnp.uint32(0))
    bits = (packed[:, :, None] >> jnp.arange(32, dtype=jnp.uint32)
            ) & jnp.uint32(1)
    flat = bits.reshape(r, w32).astype(bool)
    flat = jnp.logical_and(flat, jnp.arange(w32, dtype=jnp.int32)[None, :]
                           < c)
    pcount = jnp.sum(flat, dtype=jnp.int32)
    pidx = jnp.nonzero(flat.reshape(-1), size=pcap,
                       fill_value=r * w32)[0]
    ok = pidx < r * w32
    sel = jnp.where(ok, pidx, 0)
    prow = (row0 + sel // w32).astype(jnp.uint32)
    pcol = (sel % w32).astype(jnp.uint32)
    prow = jnp.where(ok, prow, jnp.uint32(0))
    pcol = jnp.where(ok, pcol, jnp.uint32(0))
    body = jnp.stack([prow, pcol], axis=1)  # [pcap, 2]
    header = jnp.zeros((1, 2), jnp.uint32)
    header = header.at[0, 0].set(pcount.astype(jnp.uint32))
    return jnp.concatenate([header, body], axis=0)


class _MeshPairs:
    """Pending mesh sweep; .pairs() syncs the single all-shard fetch and
    decodes shard row blocks in mesh order (global row-major, since the
    data axis shards the N axis into contiguous ordered blocks)."""

    def __init__(self, ct, mesh, dev, rcap, chunk, args):
        self._ct = ct
        self._mesh = mesh
        self._dev = dev
        self._rcap = rcap
        self._chunk = chunk
        self._args = args  # (feats, params, table, derived, n_valid, c)

    def pairs(self):
        for _shard, rows, cols in self.pairs_labeled():
            yield rows, cols

    def pairs_labeled(self):
        """(shard, rows, cols) per data shard — the shard index feeds
        the per-shard audit stage histograms."""
        ct = self._ct
        feats, params, table, derived, n_valid, c = self._args
        rcap = self._rcap
        arr = np.asarray(self._dev)  # sync point + single fetch
        n_shards = arr.shape[0] // (rcap + 1)
        counts = arr[:: rcap + 1, 0].astype(np.int64)
        while counts.max(initial=0) > rcap:
            # some shard overflowed its gather capacity: re-run the whole
            # sweep at the next power of two (rare; remembered below)
            rcap = max(rcap, 1 << (int(counts.max()) - 1).bit_length())
            fn = ct._mesh_pairs_jit(self._mesh, self._chunk, rcap)
            arr = np.asarray(fn(feats, params, table, derived, n_valid,
                                np.int32(c)))
            counts = arr[:: rcap + 1, 0].astype(np.int64)
        # RATCHET, like _SlabPairs does for _rows_cap: resetting to this
        # sweep's count made alternating small/large mesh sweeps re-trip
        # the overflow re-run (and its jit recompile) on every grow
        ct._rows_cap_mesh = max(ct._rows_cap_mesh, 256,
                                (1 << (int(counts.max()) - 1).bit_length())
                                if counts.max(initial=0) > 1 else 256)
        for k in range(n_shards):
            block = arr[k * (rcap + 1): (k + 1) * (rcap + 1)]
            rows, cols = _decode_pair_blocks(block, int(block[0, 0]))
            yield k, rows, cols


class _MeshSlabPairs:
    """Pending DOUBLE-BUFFERED mesh slab sweeps.

    The monolithic mesh dispatch (_MeshPairs) syncs the whole sweep in
    one fetch, so at 1M+ objects the host sits idle through the entire
    device pass and the mesh sits idle through the entire
    materialization tail. Slabbing the LOCAL row axis fixes both: each
    slab is one SPMD dispatch covering every shard's next `lslab` local
    rows, at most WINDOW slabs are in flight, and the only
    jax.block_until_ready sits at the slab boundary — while the host
    materializes slab k's firing pairs, the mesh is already sweeping
    slab k+1. Yield order is (slab, shard): blocks are NOT globally
    row-major (shard d's slab-s rows are d*n_loc + [s*lslab, ...)), so
    order-sensitive consumers reassemble by each block's first global
    row (the driver's audit consume does)."""

    WINDOW = 2  # double-buffered: one slab syncing, one in flight

    def __init__(self, ct, mesh, chunk, lslab, n_slabs, rcap, args):
        self._ct = ct
        self._mesh = mesh
        self._chunk = chunk
        self._lslab = lslab
        self._n_slabs = n_slabs
        # (feats, params, table, derived, n_valid, c)
        self._args = args
        fn = ct._mesh_slab_pairs_jit(mesh, chunk, lslab, rcap)
        # prime the pipeline NOW (dispatch is async): the audit's
        # cross-kind window consumes handles long after construction
        self._pend = [
            (s, rcap, fn(args[0], args[1], args[2], args[3],
                         np.int32(s * lslab), args[4],
                         np.int32(args[5])))
            for s in range(min(self.WINDOW, n_slabs))]
        self._next = len(self._pend)

    def pairs(self):
        for _shard, rows, cols in self.pairs_labeled():
            yield rows, cols

    def pairs_labeled(self):
        ct = self._ct
        feats, params, table, derived, n_valid, c = self._args
        lslab = self._lslab
        while self._pend:
            s, rcap, dev = self._pend.pop(0)
            if self._next < self._n_slabs:
                # keep the window full BEFORE blocking: the refill slab
                # overlaps this slab's fetch + materialization
                fn = ct._mesh_slab_pairs_jit(self._mesh, self._chunk,
                                             lslab, ct._rows_cap_mesh)
                self._pend.append(
                    (self._next, ct._rows_cap_mesh,
                     fn(feats, params, table, derived,
                        np.int32(self._next * lslab), n_valid,
                        np.int32(c))))
                self._next += 1
            jax.block_until_ready(dev)  # the slab boundary: the ONLY
            # sync point in the loop
            arr = np.asarray(dev)
            n_shards = arr.shape[0] // (rcap + 1)
            counts = arr[:: rcap + 1, 0].astype(np.int64)
            while counts.max(initial=0) > rcap:
                # a shard overflowed its gather capacity: re-run THIS
                # slab at the next power of two (rare; ratcheted below)
                rcap = max(rcap,
                           1 << (int(counts.max()) - 1).bit_length())
                fn = ct._mesh_slab_pairs_jit(self._mesh, self._chunk,
                                             lslab, rcap)
                arr = np.asarray(fn(feats, params, table, derived,
                                    np.int32(s * lslab), n_valid,
                                    np.int32(c)))
                counts = arr[:: rcap + 1, 0].astype(np.int64)
            ct._rows_cap_mesh = max(
                ct._rows_cap_mesh, 256,
                (1 << (int(counts.max()) - 1).bit_length())
                if counts.max(initial=0) > 1 else 256)
            for k in range(n_shards):
                block = arr[k * (rcap + 1): (k + 1) * (rcap + 1)]
                rows, cols = _decode_pair_blocks(block, int(block[0, 0]))
                yield k, rows, cols


class CompiledTemplate:
    """Device-evaluable filter for one template."""

    def __init__(self, program: Program, table: StringTable,
                 match: MatchTables, aot=None, kind: str = ""):
        self.table = table
        self.match = match
        self.program = resolve_consts(program, table, match)
        self.plans = [_ClausePlan(self.program, c)
                      for c in self.program.clauses]
        # AOT program store (ir/aot.py): every jit below is wrapped so
        # compiled executables persist across processes and a warm boot
        # deserializes instead of recompiling. The fingerprint is over
        # the RESOLVED program — interned ids are embedded in the
        # constants, so vocab skew changes it and safely misses.
        from .aot import AotStore, program_fingerprint

        self.kind = kind
        self.aot = aot if aot is not None else AotStore()
        self.fingerprint = program_fingerprint(self.program, kind)
        self._fn = self._ajit("eval", (), self._eval)
        self._scan_cache: dict[int, Any] = {}
        self._pairs_cache: dict[tuple, Any] = {}
        # remembered firing-pair gather capacity (see _gather_pairs)
        self._rows_cap = 256
        # per-shard capacity for the mesh sweep (fires_pairs_mesh_dispatch)
        self._rows_cap_mesh = 256

    def _ajit(self, tag: str, static: tuple, fn):
        from .aot import AotJit

        return AotJit(fn, store=self.aot, fingerprint=self.fingerprint,
                      tag=tag, static=static, kind=self.kind)

    def preload_aot(self, mesh=None) -> dict:
        """Ingest-time background prewarm: deserialize every stored
        executable recorded for this program's fingerprint into the
        live jit wrappers, so the first sweep/batch at a remembered
        shape dispatches with ZERO lowering or compilation on-path.
        Mesh-program entries need the live mesh (skipped without one,
        or when the topology drifted). Returns programs loaded, by
        tag."""
        loaded: dict[str, int] = {}
        if not self.aot.enabled:
            return loaded
        for ent in self.aot.entries_for(self.fingerprint):
            tag, static = ent["tag"], ent["static"]
            try:
                if tag == "eval":
                    w = self._fn
                elif tag == "scan":
                    w = self._scan_jit(*static)
                elif tag == "slabp":
                    w = self._slab_pairs_jit(*static)
                elif tag == "pairsg":
                    w = self._pairs_jit(*static)
                elif tag in ("meshp", "mesh-slabp"):
                    if mesh is None or \
                            tuple(sorted(mesh.shape.items())) != static[-1]:
                        continue
                    if tag == "meshp":
                        w = self._mesh_pairs_jit(mesh, *static[:-1])
                    else:
                        w = self._mesh_slab_pairs_jit(mesh, *static[:-1])
                else:
                    # pre-pair-decode tags ("slab"/"rows"/"mesh"/
                    # "mesh-slab") produced row-bitmask blocks; their
                    # stored executables are format-incompatible — skip
                    continue
                key = self.aot.entry_key(self.fingerprint, tag, static,
                                         ent["asig"])
                if w.preload(ent["asig"], key):
                    loaded[tag] = loaded.get(tag, 0) + 1
            except Exception:  # pragma: no cover - prewarm best-effort
                continue
        return loaded

    def _eval(self, feats, params, table, derived):
        out = None
        for plan in self.plans:
            v = _eval_clause(plan, feats, params, table, derived)
            out = v if out is None else jnp.logical_or(out, v)
        return out

    def fires(self, feats: dict, params: dict, match_table: np.ndarray,
              derived: Optional[dict] = None) -> np.ndarray:
        """-> bool [N, C]."""
        return np.asarray(self._fn(feats, params, match_table,
                                   derived or {}))

    def fires_chunked(self, feats: dict, params: dict,
                      match_table: np.ndarray,
                      derived: Optional[dict] = None,
                      chunk: int = 8192,
                      n_cons: Optional[int] = None) -> np.ndarray:
        """Chunk the N axis so [N, C, K...] intermediates stay bounded.

        Single dispatch: inputs live on device whole, the chunk loop is a
        lax.map inside the jitted fn (no per-chunk host→device transfers —
        they dominate when the chip is reached over a network tunnel).

        n_cons bounds the valid constraint columns: the C axis may carry
        power-of-two bucket padding (driver._prepare_eval) so constraint
        add/remove inside a bucket re-hits the cached program; padded
        columns replicate the last real constraint and are sliced off
        here."""
        derived = derived or {}
        c = _param_c(params)
        if n_cons is not None:
            c = min(c, n_cons)
        if not feats:
            # parameter-only program: no object slots to chunk over
            return self.fires(feats, params, match_table, derived)[:, :c]
        n = next(iter(next(iter(feats.values())).values())).shape[0]
        if n <= chunk:
            return self.fires(feats, params, match_table, derived)[:, :c]
        if n % chunk:
            pad_n = ((n + chunk - 1) // chunk) * chunk
            feats = jax.tree_util.tree_map(
                lambda a: jnp.pad(a, [(0, pad_n - n)] + [(0, 0)] *
                                  (a.ndim - 1)), feats)
        out = self._fn_scan(feats, params, match_table, derived, chunk)
        # slice the bit-unpack padding back to the true C
        return np.asarray(out)[:n, :c]

    def _fn_scan(self, feats, params, match_table, derived, chunk: int):
        """Verdicts return bit-packed over C (32x smaller device→host
        transfer — decisive when the chip sits behind a network tunnel)."""
        packed = np.asarray(self._packed_device(feats, params, match_table,
                                                derived, chunk))
        # unpack on host (vectorized)
        bits = (packed[..., None] >> np.arange(32, dtype=np.uint32)) & 1
        return bits.reshape(packed.shape[0], -1).astype(bool)

    def _packed_device(self, feats, params, match_table, derived,
                       chunk: int):
        """Bit-packed verdicts [Npad, W] uint32, left on device."""
        return self._scan_jit(chunk)(feats, params, match_table, derived)

    def _scan_jit(self, chunk: int):
        fn = self._scan_cache.get(chunk)
        if fn is None:
            def run(feats, params, table, derived):
                def reshape(a):
                    return a.reshape((-1, chunk) + a.shape[1:])
                chunked = jax.tree_util.tree_map(reshape, feats)

                def body(ch):
                    fires = self._eval(ch, params, table, derived)  # [chunk, C]
                    c = fires.shape[-1]
                    w = (c + 31) // 32
                    pad = w * 32 - c
                    if pad:
                        fires = jnp.pad(fires, ((0, 0), (0, pad)))
                    bits = fires.reshape(fires.shape[0], w, 32)
                    weights = (jnp.uint32(1) << jnp.arange(32,
                                                           dtype=jnp.uint32))
                    return jnp.sum(
                        jnp.where(bits, weights, jnp.uint32(0)), axis=-1,
                        dtype=jnp.uint32)
                outs = jax.lax.map(body, chunked)
                return outs.reshape((-1,) + outs.shape[2:])
            fn = self._ajit("scan", (chunk,), run)
            self._scan_cache[chunk] = fn
        return fn

    # ------------------------------------------------------ sparse verdicts

    def fires_pairs(self, feats: dict, params: dict,
                    match_table: np.ndarray,
                    derived: Optional[dict] = None,
                    chunk: int = 8192,
                    n_true: Optional[int] = None,
                    n_cons: Optional[int] = None
                    ) -> tuple[np.ndarray, np.ndarray]:
        """-> (rows, cols): row-major-ordered firing (object, constraint)
        index pairs.

        Audits are ~99% rejects, so the dense [N, C] verdict tensor is
        nearly all False; extracting the firing pairs ON DEVICE
        (population count + fixed-capacity nonzero) and transferring only
        those indices beats shipping even the bit-packed tensor across a
        network-tunneled chip by another ~10x. The nonzero capacity is
        remembered from the previous sweep (steady-state audits transfer
        once); a capacity miss re-gathers at the exact count.

        n_true bounds the valid rows (feats may carry extraction bucket
        padding — empty padding objects can legitimately fire absence
        clauses, so they are masked out ON DEVICE before the count, or
        they would flood the gather capacity)."""
        derived = derived or {}
        c = _param_c(params)
        if n_cons is not None:
            c = min(c, n_cons)
        if not feats:
            fires = self.fires(feats, params, match_table, derived)
            rows, cols = np.nonzero(fires[:, :c])
            return rows.astype(np.int64), cols.astype(np.int64)
        n = next(iter(next(iter(feats.values())).values())).shape[0]
        if n_true is not None:
            n = min(n, n_true)
        if next(iter(next(iter(feats.values())).values())).shape[0] <= chunk:
            fires = self.fires(feats, params, match_table, derived)
            rows, cols = np.nonzero(fires[:n, :c])
            return rows.astype(np.int64), cols.astype(np.int64)
        st = self._pairs_dispatch_mono(feats, params, match_table, derived,
                                       chunk, n, c)
        return self._pairs_consume_mono(st)

    def _pairs_dispatch_mono(self, feats, params, match_table, derived,
                             chunk: int, n: int,
                             c: Optional[int] = None):
        """ASYNC dispatch of the monolithic packed sweep + device pair
        decode; _pairs_consume_mono syncs (with the capacity-retry
        loop)."""
        n_feat = next(iter(next(iter(feats.values())).values())).shape[0]
        if n_feat % chunk:
            pad_n = ((n_feat + chunk - 1) // chunk) * chunk
            feats = jax.tree_util.tree_map(
                lambda a: jnp.pad(a, [(0, pad_n - n_feat)] + [(0, 0)] *
                                  (a.ndim - 1)), feats)
        packed = self._packed_device(feats, params, match_table, derived,
                                     chunk)
        if c is None:
            c = _param_c(params)
        rcap = self._rows_cap
        dev = self._gather_pairs(packed, n, c, rcap)
        return (packed, n, rcap, dev, c)

    def _pairs_consume_mono(self, st):
        packed, n, rcap, dev, c = st
        arr = np.asarray(dev)  # sync
        pcount = int(arr[0, 0])
        while pcount > rcap:
            rcap = max(rcap, 1 << (pcount - 1).bit_length())
            arr = np.asarray(self._gather_pairs(packed, n, c, rcap))
            pcount = int(arr[0, 0])
        self._rows_cap = max(256, (1 << (pcount - 1).bit_length())
                             if pcount > 1 else 256)
        return _decode_pair_blocks(arr, pcount)

    def _slab_pairs_jit(self, chunk: int, slab: int, pcap: int):
        """One fused jit per (chunk, slab, pcap): clamped dynamic-slice
        of the FULL device-resident feature tree at a traced `start`,
        chunked sweep, bit-pack, and dense pair decode (_pair_expand),
        returning one [pcap+1, 2] pair block. One device dispatch + one
        fetch per slab — per-leaf host pad/slice op storms (and scalar
        count fetches) each cost an RTT on a network-tunneled chip —
        and the host receives (row, constraint) INDEX arrays, no
        bitmask unpacking."""
        key = ("slabp", chunk, slab, pcap)
        fn = self._pairs_cache.get(key)
        if fn is not None:
            return fn

        def run(feats, params, table, derived, start, n_valid, c):
            leaf = next(iter(next(iter(feats.values())).values()))
            n_feat = leaf.shape[0]  # static
            cs = jnp.minimum(start, n_feat - slab)
            sl = jax.tree_util.tree_map(
                lambda a: jax.lax.dynamic_slice_in_dim(a, cs, slab, axis=0),
                feats)
            chunked = jax.tree_util.tree_map(
                lambda a: a.reshape((-1, chunk) + a.shape[1:]), sl)

            def body(ch):
                fires = self._eval(ch, params, table, derived)  # [chunk, C]
                cc = fires.shape[-1]
                w = (cc + 31) // 32
                pad = w * 32 - cc
                if pad:
                    fires = jnp.pad(fires, ((0, 0), (0, pad)))
                bits = fires.reshape(fires.shape[0], w, 32)
                weights = (jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32))
                return jnp.sum(jnp.where(bits, weights, jnp.uint32(0)),
                               axis=-1, dtype=jnp.uint32)

            packed = jax.lax.map(body, chunked)
            packed = packed.reshape((slab,) + packed.shape[2:])
            rows_global = cs + jnp.arange(slab, dtype=jnp.int32)
            # mask extraction padding (>= n_valid) AND the clamp overlap
            # (< start): overlap rows were already emitted by the
            # previous slab
            valid = (rows_global < n_valid) & (rows_global >= start)
            return _pair_expand(packed, valid, cs, c, pcap)

        fn = self._ajit("slabp", (chunk, slab, pcap), run)
        self._pairs_cache[key] = fn
        return fn

    def fires_pairs_dispatch(self, feats: dict, params: dict,
                             match_table: np.ndarray,
                             derived: Optional[dict] = None,
                             chunk: int = 8192,
                             slab: int = 32768,
                             n_true: Optional[int] = None,
                             n_cons: Optional[int] = None):
        """Dispatch every slab kernel NOW (async); the returned handle's
        .pairs() iterator syncs and decodes slab-by-slab. Callers can
        dispatch MANY templates' sweeps before consuming any — the audit
        overlaps every kind's device work with every kind's host
        materialization."""
        derived = derived or {}
        n_feat = (next(iter(next(iter(feats.values())).values())).shape[0]
                  if feats else 0)
        n = n_feat
        if n_true is not None:
            n = min(n, n_true)
        if not feats or n <= slab or n_feat < slab:
            return _EagerPairs(self, feats, params, match_table, derived,
                               chunk, n_true, n_cons)
        c = _param_c(params)
        if n_cons is not None:
            c = min(c, n_cons)
        n_slabs = (n + slab - 1) // slab
        rcap = self._rows_cap
        fn = self._slab_pairs_jit(chunk, slab, rcap)
        pend = [(rcap, fn(feats, params, match_table, derived,
                          np.int32(k * slab), np.int32(n), np.int32(c)))
                for k in range(n_slabs)]
        return _SlabPairs(self, pend, feats, params, match_table, derived,
                          chunk, slab, n, c)

    def _mesh_pairs_jit(self, mesh, chunk: int, pcap: int):
        """One fused SPMD program per (mesh, chunk, per-shard pcap):
        shard_map over the mesh's "data" axis — each device sweeps its
        contiguous N/D row block (chunked lax.map, same eval body as the
        single-device sweep), bit-packs verdicts over C, masks padding
        rows by GLOBAL row index, and decodes its local firing pairs to
        dense (row, constraint) indices at capacity pcap (_pair_expand).
        Output spec P("data") concatenates the per-shard [pcap+1, 2]
        pair blocks, so the host pays ONE fetch for the whole mesh and
        does no bit unpacking. No cross-device collective during
        evaluation: the object axis is pure data parallelism;
        aggregation happens on host from per-shard blocks (counts ride
        in each block header)."""
        key = ("meshp", id(mesh), chunk, pcap)
        fn = self._pairs_cache.get(key)
        if fn is not None:
            return fn
        from jax.sharding import PartitionSpec as P

        def local(feats_l, params, table, derived, n_valid, c):
            leaf = next(iter(next(iter(feats_l.values())).values()))
            n_loc = leaf.shape[0]  # static: N // data axis size
            chunked = jax.tree_util.tree_map(
                lambda a: a.reshape((-1, chunk) + a.shape[1:]), feats_l)

            def body(ch):
                fires = self._eval(ch, params, table, derived)  # [chunk, C]
                cc = fires.shape[-1]
                w = (cc + 31) // 32
                pad = w * 32 - cc
                if pad:
                    fires = jnp.pad(fires, ((0, 0), (0, pad)))
                bits = fires.reshape(fires.shape[0], w, 32)
                weights = (jnp.uint32(1) << jnp.arange(32,
                                                       dtype=jnp.uint32))
                return jnp.sum(jnp.where(bits, weights, jnp.uint32(0)),
                               axis=-1, dtype=jnp.uint32)

            packed = jax.lax.map(body, chunked)
            packed = packed.reshape((n_loc,) + packed.shape[2:])
            idx = jax.lax.axis_index("data")
            row0 = idx * n_loc
            rows_global = row0 + jnp.arange(n_loc, dtype=jnp.int32)
            return _pair_expand(packed, rows_global < n_valid, row0, c,
                                pcap)

        def run(feats, params, table, derived, n_valid, c):
            fspec = jax.tree_util.tree_map(
                lambda a: P("data", *([None] * (a.ndim - 1))), feats)
            rep = lambda tree: jax.tree_util.tree_map(
                lambda a: P(*([None] * a.ndim)), tree)
            return _shard_map_wrap(
                local, mesh=mesh,
                in_specs=(fspec, rep(params), rep(table), rep(derived),
                          P(), P()),
                out_specs=P("data", None),
            )(feats, params, table, derived, n_valid, c)

        fn = self._ajit(
            "meshp", (chunk, pcap, tuple(sorted(mesh.shape.items()))), run)
        self._pairs_cache[key] = fn
        return fn

    def _mesh_slab_pairs_jit(self, mesh, chunk: int, lslab: int,
                             pcap: int):
        """One fused SPMD program per (mesh, chunk, lslab, pcap): the
        slab twin of _mesh_pairs_jit — each device dynamic-slices its
        next `lslab` LOCAL rows at a traced `start` (so every slab of
        a sweep reuses ONE compiled program), sweeps/bit-packs them,
        and decodes its local firing pairs to dense (row, constraint)
        indices at capacity pcap, with global row indices stamped from
        axis_index. Out spec P("data") concatenates per-shard
        [pcap+1, 2] blocks: one dispatch + one fetch per slab for the
        whole mesh, nothing to unpack on host."""
        key = ("mesh-slabp", id(mesh), chunk, lslab, pcap)
        fn = self._pairs_cache.get(key)
        if fn is not None:
            return fn
        from jax.sharding import PartitionSpec as P

        def local(feats_l, params, table, derived, start, n_valid, c):
            leaf = next(iter(next(iter(feats_l.values())).values()))
            n_loc = leaf.shape[0]  # static: N // data axis size
            cs = jnp.minimum(start, n_loc - lslab)
            sl = jax.tree_util.tree_map(
                lambda a: jax.lax.dynamic_slice_in_dim(a, cs, lslab,
                                                       axis=0),
                feats_l)
            chunked = jax.tree_util.tree_map(
                lambda a: a.reshape((-1, chunk) + a.shape[1:]), sl)

            def body(ch):
                fires = self._eval(ch, params, table, derived)
                cc = fires.shape[-1]
                w = (cc + 31) // 32
                pad = w * 32 - cc
                if pad:
                    fires = jnp.pad(fires, ((0, 0), (0, pad)))
                bits = fires.reshape(fires.shape[0], w, 32)
                weights = (jnp.uint32(1) << jnp.arange(32,
                                                       dtype=jnp.uint32))
                return jnp.sum(jnp.where(bits, weights, jnp.uint32(0)),
                               axis=-1, dtype=jnp.uint32)

            packed = jax.lax.map(body, chunked)
            packed = packed.reshape((lslab,) + packed.shape[2:])
            idx = jax.lax.axis_index("data")
            row0 = idx * n_loc
            loc_rows = cs + jnp.arange(lslab, dtype=jnp.int32)
            rows_global = row0 + loc_rows
            # mask extraction padding (>= n_valid) AND the clamp
            # overlap (< start): overlap rows were already emitted by
            # the previous slab
            valid = (rows_global < n_valid) & (loc_rows >= start)
            return _pair_expand(packed, valid, row0 + cs, c, pcap)

        def run(feats, params, table, derived, start, n_valid, c):
            fspec = jax.tree_util.tree_map(
                lambda a: P("data", *([None] * (a.ndim - 1))), feats)
            rep = lambda tree: jax.tree_util.tree_map(
                lambda a: P(*([None] * a.ndim)), tree)
            return _shard_map_wrap(
                local, mesh=mesh,
                in_specs=(fspec, rep(params), rep(table), rep(derived),
                          P(), P(), P()),
                out_specs=P("data", None),
            )(feats, params, table, derived, start, n_valid, c)

        fn = self._ajit(
            "mesh-slabp",
            (chunk, lslab, pcap, tuple(sorted(mesh.shape.items()))), run)
        self._pairs_cache[key] = fn
        return fn

    # the mesh slab loop engages once each shard holds at least this
    # many multiples of the chunk (below it, one dispatch is cheaper
    # than the per-slab fetch round-trips); slabs aim for ~MESH_SLABS
    # per sweep
    MESH_SLAB_MIN_CHUNKS = 8
    MESH_SLABS = 8

    def fires_pairs_mesh_dispatch(self, feats: dict, params: dict,
                                  match_table: np.ndarray, mesh,
                                  derived: Optional[dict] = None,
                                  chunk: int = 8192,
                                  n_true: Optional[int] = None,
                                  slab: Optional[int] = None,
                                  n_cons: Optional[int] = None):
        """Mesh-sharded form of fires_pairs_dispatch: dispatch the SPMD
        sweep NOW (async), return a handle whose .pairs() syncs and
        yields per-shard (rows, cols). Requires the feature N axis
        divisible by the mesh's "data" axis size (callers pad to a
        power-of-two bucket and gate on divisibility).

        Large sweeps take the double-buffered SLAB loop (_MeshSlabPairs:
        per-shard materialization overlaps the device sweep of the next
        slab, jax.block_until_ready only at slab boundaries); small
        sweeps keep the single monolithic dispatch. `slab` overrides the
        LOCAL (per-shard) slab size — must divide the per-shard row
        count and be a multiple of the chunk."""
        derived = derived or {}
        n_feat = (next(iter(next(iter(feats.values())).values())).shape[0]
                  if feats else 0)
        n_data = mesh.shape["data"]
        if not feats or n_feat % n_data:
            raise ValueError(f"N={n_feat} not shardable over data={n_data}")
        n = n_feat if n_true is None else min(n_feat, n_true)
        n_loc = n_feat // n_data
        chunk_eff = min(chunk, n_loc)
        if n_loc % chunk_eff:
            raise ValueError(f"n_loc={n_loc} not divisible by "
                             f"chunk={chunk_eff}")
        c = _param_c(params)
        if n_cons is not None:
            c = min(c, n_cons)
        lslab = slab
        if lslab is None and \
                n_loc >= self.MESH_SLAB_MIN_CHUNKS * chunk_eff:
            # power-of-two extraction buckets make this exact: aim for
            # MESH_SLABS slabs, never below one chunk each
            lslab = max(chunk_eff, n_loc // self.MESH_SLABS)
        if lslab is not None and lslab < n_loc:
            if n_loc % lslab or lslab % chunk_eff:
                raise ValueError(
                    f"slab={lslab} must divide n_loc={n_loc} and be a "
                    f"multiple of chunk={chunk_eff}")
            return _MeshSlabPairs(
                self, mesh, chunk_eff, lslab, n_loc // lslab,
                self._rows_cap_mesh,
                (feats, params, match_table, derived, np.int32(n), c))
        rcap = self._rows_cap_mesh
        fn = self._mesh_pairs_jit(mesh, chunk_eff, rcap)
        dev = fn(feats, params, match_table, derived, np.int32(n),
                 np.int32(c))
        return _MeshPairs(self, mesh, dev, rcap, chunk_eff,
                          (feats, params, match_table, derived,
                           np.int32(n), c))

    def fires_pairs_slabbed(self, feats: dict, params: dict,
                            match_table: np.ndarray,
                            derived: Optional[dict] = None,
                            chunk: int = 8192,
                            slab: int = 32768,
                            n_true: Optional[int] = None,
                            n_cons: Optional[int] = None):
        """Yield row-major (rows, cols) firing pairs per N-axis slab.
        See fires_pairs_dispatch; this is dispatch + immediate consume."""
        yield from self.fires_pairs_dispatch(
            feats, params, match_table, derived, chunk=chunk, slab=slab,
            n_true=n_true, n_cons=n_cons).pairs()

    def _gather_pairs(self, packed, n: int, c: int, pcap: int):
        """Device firing-PAIR gather: one [pcap+1, 2] uint32 block —
        header row carrying the pair count, then the (row, constraint)
        index pairs row-major (see _pair_expand).

        Audits are ~99.99% rejects, so the dense index pairs are tiny,
        the whole result is ONE device->host fetch (a network-tunneled
        chip pays ~0.1s per roundtrip, so scalar-count-then-data would
        double the cost), and the host does no bit unpacking at all.
        Rows >= n are extraction padding, masked before counting; cols
        >= c are C-bucket padding, masked on device too."""
        return self._pairs_jit(pcap)(packed, np.int32(n), np.int32(c))

    def _pairs_jit(self, pcap: int):
        fn = self._pairs_cache.get(("pairsg", pcap))
        if fn is None:
            def run(packed, n, c):
                npad = packed.shape[0]
                valid = jnp.arange(npad, dtype=jnp.int32) < n
                return _pair_expand(packed, valid, jnp.int32(0), c, pcap)
            fn = self._ajit("pairsg", (pcap,), run)
            self._pairs_cache[("pairsg", pcap)] = fn
        return fn
