"""Constraint-parameter encoding: spec.parameters dicts → tensors.

Constraints are DATA, not code (SURVEY.md §7 P0): one compiled program per
template, with the C (constraint) axis carried entirely by these encoded
parameter tensors — adding/removing a constraint never recompiles anything
(the reference's code/data split between PutModules and PutData,
client.go:362-578).

For parameter values used as string-match patterns (allowedRegex, repo
prefixes, …) the encoder allocates match-table rows (ops/strtab.py) and
stores row indices per cell, so the device evaluates dynamic per-constraint
patterns with one gather.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from ..ops.strtab import MatchTables, StringTable, canon_num
from .features import _MISSING, _bucket, _descend_fields, _entries, kind_of
from .prog import K_ARR, K_FALSE, K_NUM, K_OBJ, K_STR, K_TRUE, Program


class ParamEncodeError(Exception):
    pass


def encode_params(program: Program, param_dicts: list[Any],
                  table: StringTable, match: MatchTables) -> dict:
    """-> {slot: arrays}; list slots [C, P], scalars [C], counts [C]."""
    C = len(param_dicts)
    out: dict[int, dict] = {}
    for spec in program.param_slots:
        iters = [s for s in spec.segs if s.kind == "iter"]
        if len(iters) > 1:
            raise ParamEncodeError("nested parameter list iteration")
        if spec.mode == "count" or not iters:
            arrs = _encode_scalar(spec, param_dicts, table, match, C)
        else:
            arrs = _encode_list(spec, param_dicts, table, match, C)
        out[spec.slot] = arrs
    return out


def _cell(v: Any, table: StringTable):
    k = kind_of(v)
    sid = table.intern(v) if k == K_STR else 0
    num = np.nan
    nid = 0
    if k == K_NUM:
        num = float(v)
        nid = table.intern(canon_num(v))
    elif k in (K_TRUE, K_FALSE):
        num = 1.0 if k == K_TRUE else 0.0
    return sid, num, nid, k


def _rows(v: Any, k: int, spec, match: MatchTables) -> dict[str, int]:
    out = {}
    for op in spec.pattern_ops:
        out[op] = match.row(op, v) if k == K_STR else -1
    return out


def _encode_scalar(spec, param_dicts, table, match, C):
    ids = np.zeros((C,), dtype=np.int32)
    nums = np.full((C,), np.nan, dtype=np.float32)
    nids = np.zeros((C,), dtype=np.int32)
    kinds = np.zeros((C,), dtype=np.int8)
    counts = np.zeros((C,), dtype=np.float32)
    rows = {op: np.full((C,), -1, dtype=np.int32) for op in spec.pattern_ops}
    for c, params in enumerate(param_dicts):
        node, i = _descend_fields(params if isinstance(params, dict) else {},
                                  [s for s in spec.segs], 0)
        if node is _MISSING or i < len(spec.segs):
            continue
        k = kind_of(node)
        kinds[c] = k
        if spec.mode == "count":
            if k in (K_ARR, K_OBJ):
                counts[c] = len(node)
            elif k == K_STR:
                counts[c] = len(node)
            continue
        sid, num, nid, _ = _cell(node, table)
        ids[c], nums[c], nids[c] = sid, num, nid
        for op, r in _rows(node, k, spec, match).items():
            rows[op][c] = r
    out = {"id": ids, "num": nums, "nid": nids, "kind": kinds,
           "count": counts}
    for op, arr in rows.items():
        out[f"row:{op}"] = arr
    return out


def _encode_list(spec, param_dicts, table, match, C):
    # pass 1: sizes
    prefix = []
    suffix = []
    seen_iter = False
    for s in spec.segs:
        if s.kind == "iter":
            seen_iter = True
            continue
        (suffix if seen_iter else prefix).append(s)
    lists: list[list] = []
    maxp = 0
    for params in param_dicts:
        node, i = _descend_fields(params if isinstance(params, dict) else {},
                                  prefix, 0)
        kids = _entries(node) if node is not _MISSING and i == len(prefix) else []
        lists.append(kids)
        maxp = max(maxp, len(kids))
    P = _bucket(maxp)
    ids = np.zeros((C, P), dtype=np.int32)
    nums = np.full((C, P), np.nan, dtype=np.float32)
    nids = np.zeros((C, P), dtype=np.int32)
    kinds = np.zeros((C, P), dtype=np.int8)
    keys = np.zeros((C, P), dtype=np.int32)
    key_nums = np.full((C, P), np.nan, dtype=np.float32)
    key_nids = np.zeros((C, P), dtype=np.int32)
    counts = np.zeros((C,), dtype=np.float32)
    rows = {op: np.full((C, P), -1, dtype=np.int32) for op in spec.pattern_ops}
    for c, kids in enumerate(lists):
        counts[c] = len(kids)
        for p, (key, v) in enumerate(kids):
            if suffix:
                v, j = _descend_fields(v, suffix, 0)
                if v is _MISSING or j < len(suffix):
                    continue
            sid, num, nid, k = _cell(v, table)
            ids[c, p], nums[c, p], nids[c, p], kinds[c, p] = sid, num, nid, k
            if isinstance(key, str):
                keys[c, p] = table.intern(key)
            else:
                key_nums[c, p] = float(key)
                key_nids[c, p] = table.intern(canon_num(key))
            for op, r in _rows(v, k, spec, match).items():
                rows[op][c, p] = r
    out = {"id": ids, "num": nums, "nid": nids, "kind": kinds,
           "count": counts, "key_id": keys, "key_num": key_nums,
           "key_nid": key_nids}
    for op, arr in rows.items():
        out[f"row:{op}"] = arr
    return out
