from .compile import Uncompilable, compile_template
from .driver import TpuDriver
from .evaljax import CompiledTemplate
from .prog import Program

__all__ = ["CompiledTemplate", "Program", "TpuDriver", "Uncompilable",
           "compile_template"]
