"""AST specialization: expand rule indirection into flat violation clauses.

The policy corpus hides iteration unions behind local helper rules — the
`input_containers` partial set unioning containers/initContainers
(reference library/pod-security-policy/*/src.rego), the object-headed
`general_violation[{"msg": msg, "field": field}]` invocation
(library/general/containerlimits/src.rego:123-129), and path-valued
functions like `run_as_user` (pod-security-policy/users/src.rego:38-48).

The vectorized compiler wants none of that indirection: a device clause is
a flat conjunction over explicit iteration axes. This pass multiplies each
clause by the alternatives of every positively-referenced local rule,
substituting terms with capture-free renaming, so compile.py sees only
direct paths. Negated references are left alone — negation needs the
existential boundary that compile.py's helper inlining provides.

Pure AST -> AST; raises nothing (unexpandable shapes are left in place for
compile.py to reject into the interpreter fallback path).
"""

from __future__ import annotations

from dataclasses import replace
from typing import Optional

from ..rego import ast as A

_MAX_EXPANSIONS = 256  # per-rule alternative cap (explosion guard)


class _Fresh:
    def __init__(self):
        self.n = 0

    def var(self, base: str) -> str:
        self.n += 1
        return f"{base}__x{self.n}"


# ------------------------------------------------------------ substitution


def subst(t, m: dict):
    """Substitute Var names by terms, splicing refs into ref bases."""
    if t is None or isinstance(t, A.Scalar):
        return t
    if isinstance(t, A.Var):
        return m.get(t.name, t)
    if isinstance(t, A.Ref):
        base = subst(t.base, m)
        args = tuple(subst(a, m) for a in t.args)
        if isinstance(base, A.Ref):
            return A.Ref(base=base.base, args=base.args + args)
        return A.Ref(base=base, args=args)
    if isinstance(t, A.Call):
        return A.Call(fn=t.fn, args=tuple(subst(a, m) for a in t.args))
    if isinstance(t, A.BinOp):
        return A.BinOp(op=t.op, lhs=subst(t.lhs, m), rhs=subst(t.rhs, m))
    if isinstance(t, A.UnaryMinus):
        return A.UnaryMinus(term=subst(t.term, m))
    if isinstance(t, A.ArrayLit):
        return A.ArrayLit(items=tuple(subst(x, m) for x in t.items))
    if isinstance(t, A.SetLit):
        return A.SetLit(items=tuple(subst(x, m) for x in t.items))
    if isinstance(t, A.ObjectLit):
        return A.ObjectLit(items=tuple((subst(k, m), subst(v, m))
                                       for k, v in t.items))
    if isinstance(t, A.ArrayCompr):
        return A.ArrayCompr(head=subst(t.head, m),
                            body=tuple(subst_lit(l, m) for l in t.body))
    if isinstance(t, A.SetCompr):
        return A.SetCompr(head=subst(t.head, m),
                          body=tuple(subst_lit(l, m) for l in t.body))
    if isinstance(t, A.ObjectCompr):
        return A.ObjectCompr(key=subst(t.key, m), value=subst(t.value, m),
                             body=tuple(subst_lit(l, m) for l in t.body))
    if isinstance(t, A.Assign):
        return A.Assign(lhs=subst(t.lhs, m), rhs=subst(t.rhs, m))
    if isinstance(t, A.Unify):
        return A.Unify(lhs=subst(t.lhs, m), rhs=subst(t.rhs, m))
    if isinstance(t, A.SomeDecl):
        return t
    return t


def subst_lit(lit: A.Literal, m: dict) -> A.Literal:
    return replace(lit, expr=subst(lit.expr, m))


def _local_vars(rule: A.Rule) -> set:
    out: set = set()

    def walk(t):
        if isinstance(t, A.Var):
            if t.name not in ("input", "data"):
                out.add(t.name)
        elif isinstance(t, A.Ref):
            walk(t.base)
            for a in t.args:
                walk(a)
        elif isinstance(t, (A.Call,)):
            for a in t.args:
                walk(a)
        elif isinstance(t, A.BinOp):
            walk(t.lhs)
            walk(t.rhs)
        elif isinstance(t, A.UnaryMinus):
            walk(t.term)
        elif isinstance(t, (A.ArrayLit, A.SetLit)):
            for x in t.items:
                walk(x)
        elif isinstance(t, A.ObjectLit):
            for k, v in t.items:
                walk(k)
                walk(v)
        elif isinstance(t, (A.ArrayCompr, A.SetCompr)):
            walk(t.head)
            for l in t.body:
                walk(l.expr)
        elif isinstance(t, A.ObjectCompr):
            walk(t.key)
            walk(t.value)
            for l in t.body:
                walk(l.expr)
        elif isinstance(t, (A.Assign, A.Unify)):
            walk(t.lhs)
            walk(t.rhs)

    if rule.key is not None:
        walk(rule.key)
    if rule.value is not None:
        walk(rule.value)
    for a in rule.args:
        walk(a)
    for lit in rule.body:
        walk(lit.expr)
    return out


def _freshen(rule: A.Rule, fresh: _Fresh) -> A.Rule:
    ren = {v: A.Var(fresh.var(v)) for v in _local_vars(rule)
           if not v.startswith("$wc")}
    # wildcards stay wildcards but must not collide across copies
    for v in _local_vars(rule):
        if v.startswith("$wc"):
            ren[v] = A.Var(fresh.var("$wc"))
    return replace(
        rule,
        key=subst(rule.key, ren) if rule.key is not None else None,
        value=subst(rule.value, ren) if rule.value is not None else None,
        args=tuple(subst(a, ren) for a in rule.args),
        body=tuple(subst_lit(l, ren) for l in rule.body),
    )


# ------------------------------------------------------------ site finding


class _Site:
    """First expandable reference found in a literal."""

    def __init__(self, kind: str, name: str, term: Optional[A.Ref] = None):
        self.kind = kind  # "ps" | "objhead" | "pathfn"
        self.name = name
        self.term = term


def _find_ps_ref(t, ps_names: set) -> Optional[A.Ref]:
    """Deepest-first search for Ref(base=Var(ps), ...)."""
    if isinstance(t, A.Ref):
        inner = _find_ps_ref(t.base, ps_names)
        if inner is not None:
            return inner
        for a in t.args:
            inner = _find_ps_ref(a, ps_names)
            if inner is not None:
                return inner
        if isinstance(t.base, A.Var) and t.base.name in ps_names and t.args:
            return t
        return None
    if isinstance(t, A.Call):
        for a in t.args:
            inner = _find_ps_ref(a, ps_names)
            if inner is not None:
                return inner
        return None
    if isinstance(t, A.BinOp):
        return (_find_ps_ref(t.lhs, ps_names)
                or _find_ps_ref(t.rhs, ps_names))
    if isinstance(t, A.UnaryMinus):
        return _find_ps_ref(t.term, ps_names)
    if isinstance(t, (A.Assign, A.Unify)):
        return (_find_ps_ref(t.lhs, ps_names)
                or _find_ps_ref(t.rhs, ps_names))
    return None


def _replace_term(t, old, new):
    if t is old:
        return new
    if isinstance(t, A.Ref):
        base = _replace_term(t.base, old, new)
        args = tuple(_replace_term(a, old, new) for a in t.args)
        if isinstance(base, A.Ref):
            return A.Ref(base=base.base, args=base.args + args)
        return A.Ref(base=base, args=args)
    if isinstance(t, A.Call):
        return A.Call(fn=t.fn, args=tuple(_replace_term(a, old, new)
                                          for a in t.args))
    if isinstance(t, A.BinOp):
        return A.BinOp(op=t.op, lhs=_replace_term(t.lhs, old, new),
                       rhs=_replace_term(t.rhs, old, new))
    if isinstance(t, A.UnaryMinus):
        return A.UnaryMinus(term=_replace_term(t.term, old, new))
    if isinstance(t, (A.Assign,)):
        return A.Assign(lhs=_replace_term(t.lhs, old, new),
                        rhs=_replace_term(t.rhs, old, new))
    if isinstance(t, (A.Unify,)):
        return A.Unify(lhs=_replace_term(t.lhs, old, new),
                       rhs=_replace_term(t.rhs, old, new))
    return t


# ------------------------------------------------------------- expansion


class _Expander:
    def __init__(self, module: A.Module):
        self.module = module
        self.fresh = _Fresh()
        self.rules: dict[str, list[A.Rule]] = {}
        for r in module.rules:
            self.rules.setdefault(r.name, []).append(r)
        self.ps_names = {
            n for n, rs in self.rules.items()
            if all(r.kind == "partial_set" for r in rs)
        }
        # path-valued functions: every clause's head value is a Var whose
        # body binding (or the value itself) is a plain Ref/Var — inlining
        # them multiplies clauses without introducing uncompilable exprs
        self.pathfn_names = {
            n for n, rs in self.rules.items()
            if rs and all(r.kind == "function" and self._path_valued(r)
                          for r in rs)
        }

    def _path_valued(self, r: A.Rule) -> bool:
        v = r.value
        if v is None:
            return False
        if isinstance(v, A.Ref):
            return True
        if not isinstance(v, A.Var):
            return False
        for lit in r.body:
            e = lit.expr
            if not lit.negated and isinstance(e, (A.Assign, A.Unify)) and \
                    isinstance(e.lhs, A.Var) and e.lhs.name == v.name:
                return isinstance(e.rhs, (A.Ref, A.Var))
        return False

    # ------------------------------------------------------------- driver

    def expand_module(self) -> A.Module:
        out_rules: list[A.Rule] = []
        for name, rs in self.rules.items():
            if name in self.ps_names and name != "violation":
                # referenced partial sets stay (interpreter still needs
                # them for message materialization) and are also expanded
                # in place so compile-time helper inlining sees flat bodies
                out_rules.extend(self._expand_rule(r) for r in rs)
                continue
            for r in rs:
                out_rules.extend(self._expand_all(r))
        flat = []
        for x in out_rules:
            flat.extend(x if isinstance(x, list) else [x])
        return replace(self.module, rules=tuple(flat))

    def _expand_rule(self, r: A.Rule) -> list:
        return self._expand_all(r)

    def _expand_all(self, rule: A.Rule) -> list[A.Rule]:
        work = [rule]
        done: list[A.Rule] = []
        budget = _MAX_EXPANSIONS
        while work:
            r = work.pop()
            exp = self._expand_once(r)
            if exp is None:
                done.append(r)
                continue
            budget -= len(exp)
            if budget <= 0:
                return [rule]  # explosion: leave original for fallback
            work.extend(exp)
        done.reverse()
        return done

    def _expand_once(self, rule: A.Rule) -> Optional[list[A.Rule]]:
        for i, lit in enumerate(rule.body):
            if lit.negated or lit.withs:
                continue
            e = lit.expr
            # object-headed partial-set invocation:
            #   general_violation[{"msg": msg, "field": "containers"}]
            if isinstance(e, A.Ref) and isinstance(e.base, A.Var) \
                    and e.base.name in self.ps_names \
                    and len(e.args) == 1 \
                    and isinstance(e.args[0], A.ObjectLit):
                alts = self._expand_objhead(rule, i, e.base.name, e.args[0])
                if alts is not None:
                    return alts
                continue
            # value-function inlining at a positive binding site
            if isinstance(e, (A.Assign, A.Unify)) and \
                    isinstance(e.lhs, A.Var) and isinstance(e.rhs, A.Call) \
                    and len(e.rhs.fn) == 1 \
                    and e.rhs.fn[0] in self.pathfn_names:
                alts = self._expand_pathfn(rule, i, e.lhs, e.rhs)
                if alts is not None:
                    return alts
                continue
            site = _find_ps_ref(e, self.ps_names)
            if site is not None:
                alts = self._expand_ps(rule, i, lit, site)
                if alts is not None:
                    return alts
        return None

    # ------------------------------------------------------ ps expansion

    def _expand_ps(self, rule: A.Rule, i: int, lit: A.Literal,
                   site: A.Ref) -> Optional[list[A.Rule]]:
        name = site.base.name
        a0 = site.args[0]
        rest = site.args[1:]
        e = lit.expr
        out: list[A.Rule] = []
        for pc in self.rules[name]:
            pc = _freshen(pc, self.fresh)
            if not isinstance(pc.key, A.Var):
                return None  # non-var set element: not expandable
            head = pc.key.name
            pre = list(rule.body[:i])
            post = list(rule.body[i + 1:])
            body = list(pc.body)
            extra: list[A.Literal] = []
            if isinstance(a0, A.Var):
                if a0.name.startswith("$wc"):
                    bound = head
                else:
                    # rename the set-element var to the caller's var
                    ren = {head: A.Var(a0.name)}
                    body = [subst_lit(l, ren) for l in body]
                    bound = a0.name
            elif isinstance(a0, A.Scalar):
                extra = [A.Literal(expr=A.Unify(lhs=A.Var(head), rhs=a0))]
                bound = head
            else:
                return None
            # rebuild the literal with the site replaced
            if not rest and e is site:
                new_lits: list[A.Literal] = []  # bare membership: consumed
            elif not rest and isinstance(e, (A.Assign, A.Unify)) and \
                    isinstance(e.lhs, A.Var) and e.rhs is site:
                if isinstance(a0, A.Var) and not a0.name.startswith("$wc"):
                    # x := ps[y]: keep x alias to the element var
                    new_lits = [replace(lit, expr=A.Assign(
                        lhs=e.lhs, rhs=A.Var(bound)))]
                else:
                    ren2 = {bound: A.Var(e.lhs.name)}
                    body = [subst_lit(l, ren2) for l in body]
                    extra = [subst_lit(l, ren2) for l in extra]
                    new_lits = []
            else:
                repl = A.Var(bound) if not rest else \
                    A.Ref(base=A.Var(bound), args=rest)
                new_lits = [replace(lit, expr=_replace_term(e, site, repl))]
            out.append(replace(rule, body=tuple(
                pre + body + extra + new_lits + post)))
        return out

    # -------------------------------------------------- objhead expansion

    def _expand_objhead(self, rule: A.Rule, i: int, name: str,
                        pat: A.ObjectLit) -> Optional[list[A.Rule]]:
        pat_map = {}
        for k, v in pat.items:
            if not isinstance(k, A.Scalar) or not isinstance(k.value, str):
                return None
            pat_map[k.value] = v
        out: list[A.Rule] = []
        for pc in self.rules[name]:
            pc = _freshen(pc, self.fresh)
            if not isinstance(pc.key, A.ObjectLit):
                return None
            ren: dict = {}
            extra: list[A.Literal] = []
            ok = True
            for hk, hv in pc.key.items:
                if not isinstance(hk, A.Scalar) or hk.value not in pat_map:
                    ok = False
                    break
                pv = pat_map[hk.value]
                if isinstance(hv, A.Var):
                    # head var <- caller term (var or constant)
                    ren[hv.name] = pv
                elif isinstance(hv, A.Scalar):
                    if isinstance(pv, A.Scalar):
                        if pv.value != hv.value:
                            ok = False
                            break
                    elif isinstance(pv, A.Var):
                        extra.append(A.Literal(
                            expr=A.Assign(lhs=pv, rhs=hv)))
                    else:
                        ok = False
                        break
                else:
                    ok = False
                    break
            if not ok:
                continue
            body = [subst_lit(l, ren) for l in pc.body]
            out.append(replace(rule, body=tuple(
                list(rule.body[:i]) + body + extra +
                list(rule.body[i + 1:]))))
        return out if out else None

    # --------------------------------------------------- pathfn expansion

    def _expand_pathfn(self, rule: A.Rule, i: int, lhs: A.Var,
                       call: A.Call) -> Optional[list[A.Rule]]:
        name = call.fn[0]
        out: list[A.Rule] = []
        for fc in self.rules[name]:
            fc = _freshen(fc, self.fresh)
            if len(fc.args) != len(call.args):
                continue
            ren: dict = {}
            extra: list[A.Literal] = []
            ok = True
            for formal, actual in zip(fc.args, call.args):
                if isinstance(formal, A.Var):
                    ren[formal.name] = actual
                elif isinstance(formal, A.Scalar):
                    if isinstance(actual, A.Scalar):
                        if actual.value != formal.value:
                            ok = False
                            break
                    else:
                        extra.append(A.Literal(
                            expr=A.Unify(lhs=actual, rhs=formal)))
                else:
                    ok = False
                    break
            if not ok:
                continue
            body = [subst_lit(l, ren) for l in fc.body]
            value = subst(fc.value, ren)
            bind = A.Literal(expr=A.Assign(lhs=lhs, rhs=value))
            out.append(replace(rule, body=tuple(
                list(rule.body[:i]) + extra + body + [bind] +
                list(rule.body[i + 1:]))))
        return out if out else None


def specialize_module(module: A.Module) -> A.Module:
    """Expand local-rule indirection across the whole module (violation
    clauses AND helper bodies, so compile-time helper inlining also sees
    flat alternatives)."""
    return _Expander(module).expand_module()
