"""Host-side extraction: review dicts → fixed-shape feature tensors.

Turns ragged JSON into the dense arrays the device program consumes
(SURVEY.md §7 hard part 3): per object slot, cell arrays (string id /
number / kind code) shaped [N, K...] with per-axis pow2 bucketing so jit
recompiles are bounded (shapes only change when a bucket grows).

This is the ingest hot path; the C flattener (native/flatten.c) walks
the review dicts and fills the cell arrays ~an order of magnitude faster,
interning directly into the shared StringTable. This Python
implementation is the semantic reference and the fallback when no
compiler is available (differential tests pin exact equivalence,
including intern-id assignment order).
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np

from ..ops.strtab import StringTable, canon_num
from .prog import (
    K_ABSENT,
    K_ARR,
    K_FALSE,
    K_NULL,
    K_NUM,
    K_OBJ,
    K_STR,
    K_TRUE,
    Program,
)

_MISSING = object()


def _bucket(n: int) -> int:
    return 1 if n <= 1 else 1 << (n - 1).bit_length()


def kind_of(v: Any) -> int:
    if v is _MISSING:
        return K_ABSENT
    if v is None:
        return K_NULL
    if isinstance(v, bool):
        return K_TRUE if v else K_FALSE
    if isinstance(v, (int, float)):
        return K_NUM
    if isinstance(v, str):
        return K_STR
    if isinstance(v, (list, tuple)):
        return K_ARR
    if isinstance(v, dict):
        return K_OBJ
    return K_ABSENT


class Cells:
    """Column-major cell builder for one slot."""

    def __init__(self, shape: tuple, with_keys: bool):
        self.ids = np.zeros(shape, dtype=np.int32)
        self.nums = np.full(shape, np.nan, dtype=np.float32)
        self.nids = np.zeros(shape, dtype=np.int32)
        self.kinds = np.zeros(shape, dtype=np.int8)
        self.keys = np.zeros(shape, dtype=np.int32) if with_keys else None
        self.key_nums = (np.full(shape, np.nan, dtype=np.float32)
                         if with_keys else None)
        self.key_nids = np.zeros(shape, dtype=np.int32) if with_keys else None

    def put(self, idx: tuple, v: Any, table: StringTable):
        k = kind_of(v)
        self.kinds[idx] = k
        if k == K_STR:
            self.ids[idx] = table.intern(v)
        elif k == K_NUM:
            self.nums[idx] = float(v)
            self.nids[idx] = table.intern(canon_num(v))
        elif k in (K_TRUE, K_FALSE):
            self.nums[idx] = 1.0 if k == K_TRUE else 0.0

    def arrays(self) -> dict:
        out = {"id": self.ids, "num": self.nums, "nid": self.nids,
               "kind": self.kinds}
        if self.keys is not None:
            out["key_id"] = self.keys
            out["key_num"] = self.key_nums
            out["key_nid"] = self.key_nids
        return out


def _descend_fields(node: Any, segs, i: int):
    """Follow consecutive field segs; returns value or _MISSING."""
    while i < len(segs) and segs[i].kind == "field":
        if not isinstance(node, dict):
            return _MISSING, i
        node = node.get(segs[i].name, _MISSING)
        if node is _MISSING:
            return _MISSING, i
        i += 1
    return node, i


def _entries(node: Any):
    """(key, value) children of a collection, list indices as keys."""
    if isinstance(node, dict):
        return list(node.items())
    if isinstance(node, (list, tuple)):
        return list(enumerate(node))
    return []


class Extractor:
    """Extracts one Program's object slots from a batch of reviews."""

    def __init__(self, program: Program, table: StringTable,
                 native: Optional[bool] = None):
        self.program = program
        self.table = table
        # axis -> list position per slot computed from segs on the fly
        if native is False:
            self._native = None
        else:
            from ..native import flatten_ext

            self._native = flatten_ext()

    @staticmethod
    def _segs_wire(segs) -> tuple:
        return tuple((1, None) if s.kind == "iter" else (0, s.name)
                     for s in segs)

    def _root(self, review: dict, root: str) -> Any:
        if root == "review":
            return review
        v = review.get(root, _MISSING)
        return v if isinstance(v, dict) else _MISSING

    def axis_sizes(self, reviews: list[dict]) -> dict[str, int]:
        """Max collection length per axis over the batch (pre-pass)."""
        sizes: dict[str, int] = {}
        for spec in self.program.obj_slots:
            iters = [s for s in spec.segs if s.kind == "iter"]
            if not iters:
                continue
            if self._native is not None and isinstance(reviews, list):
                maxes = self._native.slot_sizes(
                    reviews, spec.root, self._segs_wire(spec.segs))
                for s, m in zip(iters, maxes):
                    if m > sizes.get(s.axis, 0):
                        sizes[s.axis] = m
                continue
            for review in reviews:
                node = self._root(review, spec.root)
                self._walk_sizes(node, spec.segs, 0, sizes)
        return sizes

    def _walk_sizes(self, node, segs, i, sizes: dict) -> None:
        node, i = _descend_fields(node, segs, i)
        if node is _MISSING or i >= len(segs):
            return
        seg = segs[i]
        if seg.kind != "iter":
            return
        kids = _entries(node)
        if len(kids) > sizes.get(seg.axis, 0):
            sizes[seg.axis] = len(kids)
        for _, v in kids:
            self._walk_sizes(v, segs, i + 1, sizes)

    def extract(self, reviews: list[dict], n_pad: int,
                axis_buckets: dict[str, int]) -> dict:
        """-> {slot: {id, num, kind[, key_id, key_num]}} arrays, N padded to
        n_pad, axis dims padded to their buckets."""
        out: dict[int, dict] = {}
        for spec in self.program.obj_slots:
            iter_axes = [s.axis for s in spec.segs if s.kind == "iter"]
            dims = tuple(axis_buckets.get(a, 1) for a in iter_axes)
            native = self._native if isinstance(reviews, list) else None
            if spec.mode == "count":
                counts = np.zeros((n_pad,), dtype=np.float32)
                kinds = np.zeros((n_pad,), dtype=np.int8)
                if native is not None:
                    if len(reviews) > n_pad:
                        raise IndexError(
                            f"{len(reviews)} reviews exceed n_pad={n_pad}")
                    native.fill_count(reviews, spec.root,
                                      self._segs_wire(spec.segs), counts,
                                      kinds)
                else:
                    for n, review in enumerate(reviews):
                        node, i = _descend_fields(
                            self._root(review, spec.root), spec.segs, 0)
                        if node is _MISSING or i < len(spec.segs):
                            continue
                        k = kind_of(node)
                        kinds[n] = k
                        if k in (K_ARR, K_OBJ, K_STR):
                            counts[n] = len(node)
                out[spec.slot] = {"count": counts, "kind": kinds}
                continue
            cells = Cells((n_pad,) + dims, with_keys=bool(iter_axes))
            if native is not None:
                if len(reviews) > n_pad:
                    raise IndexError(
                        f"{len(reviews)} reviews exceed n_pad={n_pad}")
                # epoch syncs from the actual table growth even if the
                # fill raises mid-batch (partial interns must not leave a
                # stale materialize_packed cache key behind)
                before = len(self.table._strs)
                try:
                    native.fill_slot(
                        reviews, spec.root, self._segs_wire(spec.segs),
                        tuple(int(d) for d in dims),
                        cells.ids, cells.nums, cells.nids, cells.kinds,
                        cells.keys, cells.key_nums, cells.key_nids,
                        self.table._ids, self.table._strs)
                finally:
                    self.table.epoch += len(self.table._strs) - before
            else:
                for n, review in enumerate(reviews):
                    self._fill(cells, (n,), self._root(review, spec.root),
                               spec.segs, 0, dims, 0)
            out[spec.slot] = cells.arrays()
        return out

    def _fill(self, cells: Cells, idx: tuple, node, segs, i, dims,
              depth: int) -> None:
        node, i = _descend_fields(node, segs, i)
        if node is _MISSING:
            return
        if i == len(segs):
            cells.put(idx, node, self.table)
            return
        # segs[i] is an iter seg
        last = i == len(segs) - 1
        for j, (k, v) in enumerate(_entries(node)):
            if j >= dims[depth]:
                break  # bucket overflow; caller sizes buckets from the batch
            sub = idx + (j,)
            if last:
                cells.put(sub, v, self.table)
                self._put_key(cells, sub, k, depth, len(dims))
            else:
                self._put_key(cells, sub, k, depth, len(dims))
                self._fill(cells, sub, v, segs, i + 1, dims, depth + 1)

    def _put_key(self, cells: Cells, idx: tuple, k, depth: int,
                 ndims: int) -> None:
        """Keys are recorded for the innermost axis only (the compiler
        rejects key-var bindings on outer axes of multi-axis slots)."""
        if cells.keys is None or depth != ndims - 1:
            return
        if isinstance(k, str):
            cells.keys[idx] = self.table.intern(k)
        else:
            cells.key_nums[idx] = float(k)
            cells.key_nids[idx] = self.table.intern(canon_num(k))


def extract_batch(program: Program, table: StringTable,
                  reviews: list[dict], n_bucket: int | None = None):
    """Convenience: size axes, bucket, extract. Returns (features,
    axis_buckets, n_pad)."""
    ex = Extractor(program, table)
    sizes = ex.axis_sizes(reviews)
    buckets = {a: _bucket(s) for a, s in sizes.items()}
    n_pad = n_bucket or _bucket(len(reviews))
    feats = ex.extract(reviews, n_pad, buckets)
    return feats, buckets, n_pad
