"""TpuDriver: the vectorized evaluation backend.

Drop-in Driver (same seam as client/drivers.py) that compiles templates to
device programs at PutModules time and evaluates Review/Audit queries as
batched tensor sweeps:

    reviews ──extract──▶ feature tensors ─┐
    constraints ─encode─▶ param tensors  ─┤─▶ fires[N, C]  (device)
    match masks (host, grouped)          ─┘        │
                                        firing pairs ──▶ interpreter
                                                         (exact msgs)

Templates outside the compilable subset (ir/compile.py) keep the inherited
interpreter path per-template; both kinds of template coexist in one audit.
The device filter may over-fire; the host materialization re-check is
authoritative, so results are identical to the interpreter driver's
(differential tests in tests/test_ir_compile.py assert exactly that).
"""

from __future__ import annotations

import logging
import re
import threading
import time
from typing import Any, Iterable, Optional

import numpy as np

from ..client.drivers import DriverError, RegoDriver
from ..client.types import Result
from ..ops.derived import (
    DerivedTables,
    builtin_unary,
    interp_pred,
    interp_unary,
    split_part,
    strip_prefix,
)
from ..ops.strtab import MatchTables, StringTable
from ..rego import ast as A
from ..utils import faults, profiling
from ..target.batch import match_masks
from .compile import Uncompilable, compile_template
from .evaljax import CompiledTemplate, EvalError, _param_c
from .features import extract_batch
from .params import encode_params

_PREFIX_RE = re.compile(r'^templates\["([^"]+)"\]\["([^"]+)"\]$')

log = logging.getLogger("gatekeeper_tpu.ir.driver")


def merge_template_modules(mods: list) -> Optional[A.Module]:
    """Flatten a template's entry + lib modules into one compile unit.

    The rewriter (client/rewriter.py) namespaces libs under
    `libs.<target>.<Kind>...` and turns lib calls into
    `data.libs...fn(...)` / refs into `data.libs...rule`. For the
    vectorized compiler we flatten each lib rule to a unique local name
    and redirect those data paths to it, so specialization and helper
    inlining see one module. Returns None when the shape is unexpected
    (falls back to the interpreter path)."""
    from dataclasses import replace as dc_replace

    entry = mods[0]
    if not entry.package or entry.package[0] != "templates":
        return None
    renames: dict[tuple, str] = {}
    rules = list(entry.rules)
    for m in mods[1:]:
        for r in m.rules:
            flat = "__lib_" + "_".join(m.package[3:]) + "__" + r.name
            renames[("data",) + tuple(m.package) + (r.name,)] = flat
            rules.append(dc_replace(r, name=flat))

    def fix_term(t):
        if isinstance(t, A.Call):
            if t.fn and t.fn[0] == "data":
                flat = renames.get(tuple(t.fn))
                if flat is not None:
                    return A.Call((flat,), tuple(fix_term(a)
                                                 for a in t.args))
            return A.Call(t.fn, tuple(fix_term(a) for a in t.args))
        if isinstance(t, A.Ref):
            if isinstance(t.base, A.Var) and t.base.name == "data":
                statics = []
                for a in t.args:
                    if isinstance(a, A.Scalar) and isinstance(a.value, str):
                        statics.append(a.value)
                    else:
                        break
                for ln in range(len(statics), 0, -1):
                    flat = renames.get(("data",) + tuple(statics[:ln]))
                    if flat is not None:
                        rest = tuple(fix_term(a) for a in t.args[ln:])
                        if not rest:
                            return A.Var(flat)
                        return A.Ref(base=A.Var(flat), args=rest)
            return A.Ref(base=fix_term(t.base),
                         args=tuple(fix_term(a) for a in t.args))
        if isinstance(t, A.BinOp):
            return A.BinOp(t.op, fix_term(t.lhs), fix_term(t.rhs))
        if isinstance(t, A.UnaryMinus):
            return A.UnaryMinus(fix_term(t.term))
        if isinstance(t, (A.ArrayLit, A.SetLit)):
            return type(t)(tuple(fix_term(x) for x in t.items))
        if isinstance(t, A.ObjectLit):
            return A.ObjectLit(tuple((fix_term(k), fix_term(v))
                                     for k, v in t.items))
        if isinstance(t, (A.ArrayCompr, A.SetCompr)):
            return type(t)(fix_term(t.head),
                           tuple(dc_replace(l, expr=fix_term(l.expr))
                                 for l in t.body))
        if isinstance(t, A.ObjectCompr):
            return A.ObjectCompr(fix_term(t.key), fix_term(t.value),
                                 tuple(dc_replace(l, expr=fix_term(l.expr))
                                       for l in t.body))
        if isinstance(t, (A.Assign, A.Unify)):
            return type(t)(fix_term(t.lhs), fix_term(t.rhs))
        return t

    fixed = [dc_replace(
        r,
        key=fix_term(r.key) if r.key is not None else None,
        value=fix_term(r.value) if r.value is not None else None,
        args=tuple(fix_term(a) for a in r.args),
        body=tuple(dc_replace(l, expr=fix_term(l.expr)) for l in r.body),
    ) for r in rules]
    return dc_replace(entry, rules=tuple(fixed))


def _module_reads_data(module: A.Module) -> bool:
    """Does any rule reference the data document (inventory reads)?
    Decides which compile stage's fallback reason is the actionable one:
    a data-reading template was always headed for the join compiler, so
    its join reason is reported; a review-pure template's dense reason
    is."""
    found = [False]

    def walk(t) -> None:
        if found[0]:
            return
        if isinstance(t, A.Var):
            if t.name == "data":
                found[0] = True
        elif isinstance(t, A.Ref):
            walk(t.base)
            for a in t.args:
                walk(a)
        elif isinstance(t, A.Call):
            if t.fn and t.fn[0] == "data":
                found[0] = True
            for a in t.args:
                walk(a)
        elif isinstance(t, A.BinOp):
            walk(t.lhs)
            walk(t.rhs)
        elif isinstance(t, A.UnaryMinus):
            walk(t.term)
        elif isinstance(t, (A.ArrayLit, A.SetLit)):
            for x in t.items:
                walk(x)
        elif isinstance(t, A.ObjectLit):
            for k, v in t.items:
                walk(k)
                walk(v)
        elif isinstance(t, (A.ArrayCompr, A.SetCompr, A.ObjectCompr)):
            for lit in t.body:
                if not isinstance(lit.expr, A.SomeDecl):
                    walk(lit.expr)
            for h in (getattr(t, "head", None), getattr(t, "key", None),
                      getattr(t, "value", None)):
                if h is not None:
                    walk(h)
        elif isinstance(t, (A.Assign, A.Unify)):
            walk(t.lhs)
            walk(t.rhs)

    for r in module.rules:
        for lit in r.body:
            if not isinstance(lit.expr, A.SomeDecl):
                walk(lit.expr)
        for h in (r.key, r.value):
            if h is not None:
                walk(h)
    return found[0]


def _expand_parameterless(rows, cols, c_dev: int, n_cons: int):
    """A parameterless program has no C axis on device (verdicts are
    [N, 1], constraint-independent); expand each firing row to every
    constraint, preserving row-major order, exactly as the dense
    [N, 1] & mask[N, C] broadcast did."""
    if c_dev == 1 and n_cons > 1:
        n_pairs = len(rows)
        rows = np.repeat(rows, n_cons)
        cols = np.tile(np.arange(n_cons, dtype=cols.dtype), n_pairs)
    return rows, cols


def _pad_cbucket(enc: dict, c: int) -> dict:
    """Pad encoded parameter tensors along the constraint axis to its
    power-of-two bucket, replicating the LAST real constraint into the
    padding columns (their verdicts are sliced off on device via n_cons
    — see evaljax fires_*). The C axis then only changes shape when a
    bucket boundary is crossed, so adding or removing one constraint to
    a library re-hits every cached/AOT device program instead of
    triggering a fresh XLA compile mid-serving (the same trick the
    vocab capacity and extraction axes already use)."""
    from .features import _bucket

    cap = _bucket(c)
    if cap == c or not enc:
        return enc
    pad = cap - c
    return {slot: {nm: np.pad(a, [(0, pad)] + [(0, 0)] * (a.ndim - 1),
                              mode="edge")
                   for nm, a in arrs.items()}
            for slot, arrs in enc.items()}


class _ServeHostThisRound(Exception):
    """Internal: a large review batch should evaluate on the host path
    this round (its device program is still warming in the background);
    NOT a demotion."""


# log the unusable-cache warning once per process, not once per driver
_cache_warned = False


def enable_compile_cache() -> bool:
    """Point JAX at a persistent compilation cache (idempotent). A cold
    audit pays ~20-40s of XLA compiles; with the cache, every later
    process on the same machine skips them. Production entrypoints and
    benchmarks both get this by constructing a TpuDriver.

    Returns whether the cache is active. Failure (unwritable volume,
    read-only image, env skew) degrades to recompile-every-boot — it is
    logged at WARNING with the attempted dir and exported as the
    `gatekeeper_tpu_compile_cache_enabled` gauge so the operator can
    see it, but never breaks serving."""
    global _cache_warned
    import os

    import jax

    path = None
    ok = False
    try:
        # threshold knobs apply wherever the cache lives (respecting an
        # explicit env override of the compile-time floor)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        if "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS" not in os.environ:
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              0.5)
        env_dir = os.environ.get("JAX_COMPILATION_CACHE_DIR")
        if env_dir:
            # the operator chose the location. JAX only reads this env
            # var at import time — a sitecustomize jax preimport makes
            # later os.environ writes silently no-ops — so re-apply it
            path = env_dir
            if jax.config.jax_compilation_cache_dir != env_dir:
                os.makedirs(env_dir, exist_ok=True)
                jax.config.update("jax_compilation_cache_dir", env_dir)
            ok = True
        else:
            path = os.environ.get("GATEKEEPER_TPU_COMPILE_CACHE")
            if not path:
                # per-platform default: a CPU process reloading AOT
                # results compiled for the TPU host (or vice versa)
                # warns about machine mismatches and risks SIGILL on
                # feature-gated code. (An operator-named dir is used
                # exactly as given.)
                path = os.path.join(os.path.expanduser("~"), ".cache",
                                    "gatekeeper_tpu_xla",
                                    jax.default_backend())
            os.makedirs(path, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", path)
            ok = True
    except Exception as e:
        if not _cache_warned:
            _cache_warned = True
            log.warning(
                "persistent XLA compile cache unavailable at %s — every "
                "process restart will pay full recompiles (fix the "
                "volume/permissions or point JAX_COMPILATION_CACHE_DIR "
                "elsewhere): %s: %s", path, type(e).__name__, e)
    try:
        from ..control.metrics import report_compile_cache

        report_compile_cache(ok)
    except Exception:  # metrics backend optional in embedders
        pass
    return ok


class TpuDriver(RegoDriver):
    def __init__(self, mesh=None, device=None, aot_dir=None):
        super().__init__()
        self.compile_cache_enabled = enable_compile_cache()
        # per-engine device pinning (the N-engine admission plane: one
        # engine process per chip): evaluation and device_put target
        # THIS device, and the audit mesh is disabled — a pinned engine
        # owns exactly one chip. `device` is a jax.Device or an int
        # index into jax.devices().
        self._device = None
        if device is not None:
            import jax as _jax

            devs = _jax.devices()
            self._device = (devs[int(device) % len(devs)]
                            if isinstance(device, int) else device)
        self.strtab = StringTable()
        self.match_tables = MatchTables(self.strtab)
        self.derived_tables = DerivedTables(self.strtab)
        self._compiled: dict[str, Optional[CompiledTemplate]] = {}
        self._programs: dict[str, Any] = {}
        # inventory-join templates (ir/join.py): kind -> JoinProgram /
        # lazily-built JoinCompiled
        self._join_progs: dict[str, Any] = {}
        self._join_compiled: dict[str, Any] = {}
        self._modules: dict[str, A.Module] = {}
        # (shard_id, shard_count) when this driver sweeps one slice of
        # a sharded audit plane (set_audit_shard); None = whole plane
        self._audit_shard = None
        self._derived_cols: dict[str, list[int]] = {}  # kind -> global cols
        # generation counters for cache invalidation
        self._constraint_gen = 0
        self._data_gen = 0
        # per-kind caches: {kind: {key: value}} so template updates can
        # invalidate with the bare kind
        self._param_cache: dict[str, dict] = {}
        self._feat_cache: dict[str, dict] = {}
        # host ndarray (by identity) -> device buffer: steady-state sweeps
        # must not re-upload cached tensors every audit (H2D costs seconds
        # when the chip sits behind a network tunnel)
        self._dev_cache: dict[int, tuple] = {}
        # audit match-mask cache: (target, kind) -> (gen-key, reviews,
        # mask). The mask is a pure function of (constraints, cached
        # review list, namespaces), all covered by the generation
        # counters — steady-state sweeps were rebuilding an identical
        # [N_reviews x N_cons] bool array every audit
        self._mask_cache: dict = {}
        # audit RESULTS delta cache: (target, kind) -> {"con_gen",
        # "reviews" (identity), "rev", "by_row": {review index ->
        # [Result]}}. Compiled templates are review-pure (the compiler
        # rejects inventory reads; cross-object templates go to the join
        # path), so when the patch journal covers the gap since the last
        # sweep only the DIRTY rows need re-evaluation — the device
        # sweep and the whole materialization tail are skipped for the
        # unchanged 99% of a churning cluster
        self._audit_results_cache: dict = {}
        self._review_idx_cache: tuple = (None, None, None)
        self._data_taint: dict[str, bool] = {}
        # vocab-capacity padding cache: id(src) -> (weakref, padded)
        self._vpad_cache: dict = {}
        # join steady-state caches, one data generation deep:
        # (data_rev, {id(review): (review, frozen)}, {(ci, id(frozen)):
        #  (keys, ident)})
        self._join_frz: tuple = (None, {}, {})
        # cost-based review_batch dispatch EMAs (_use_device_for_batch)
        self._dev_batch_lat_s: Optional[float] = None
        # initial estimate from measured codegen materialization
        # throughput (~130k pairs/s on this class of host); the EMA
        # refines it from real batches and audits
        self._host_pair_rate: float = 100_000.0
        self._dev_skips = 0
        # multi-device: audits shard over the mesh's "data" axis (the
        # object dimension — SURVEY §2.5's batch-parallel sweep) when
        # more than one device is visible. GATEKEEPER_TPU_MESH=off
        # disables; =<n> caps the data-axis width
        self._mesh = self._build_mesh(mesh)
        # async device warm-up: the FIRST audit at a new sweep shape
        # serves from the host path while a background thread runs the
        # device sweep once (XLA compile ~10-90s cold at audit scale —
        # the reference ingests templates in milliseconds, so template-
        # to-first-verdict must not block on the compiler); once warm,
        # audits hot-swap to the device. GATEKEEPER_TPU_ASYNC_COMPILE=0
        # restores compile-blocking dispatch (tests pin paths with it)
        import os as _os

        self.async_warm = _os.environ.get(
            "GATEKEEPER_TPU_ASYNC_COMPILE", "1") != "0"
        # mesh path tuning: the review-count floor below which a sweep
        # stays single-device, and an optional LOCAL slab-size override
        # for the double-buffered mesh slab loop (None = auto-sized in
        # fires_pairs_mesh_dispatch)
        self.MESH_MIN_REVIEWS = int(_os.environ.get(
            "GATEKEEPER_TPU_MESH_MIN_REVIEWS", self.MESH_MIN_REVIEWS))
        slab_env = _os.environ.get("GATEKEEPER_TPU_MESH_SLAB", "")
        self.mesh_slab_local: Optional[int] = \
            int(slab_env) if slab_env else None
        self.sweep_chunk = int(_os.environ.get(
            "GATEKEEPER_TPU_SWEEP_CHUNK", "8192"))
        self._warm_done: set = set()
        self._warm_inflight: dict = {}           # sig -> done Event
        # sigs adopted from the AOT store's manifest (not yet executed
        # in THIS process): their first dispatch runs under the
        # no-inline-compile guard — if the backing executable turns out
        # not to deserialize after all (save was refused, store GC'd),
        # the sig is un-adopted and the normal host-fallback/background
        # warm path serves, instead of an inline XLA stall
        self._warm_restored: set = set()
        self._warm_fail: dict = {}               # sig -> failure count
        self._warm_lock = threading.Lock()       # guards the warm sets
        self._warm_sem = threading.Semaphore(1)  # one compile at a time
        # sharded/replicated device placements for the mesh sweep,
        # keyed (id(leaf), data-leading?) with the _dev weakref pattern
        self._dev_mesh_cache: dict = {}
        # which path the last audit's compiled kinds took, for
        # observability (bench.py reports it): "mesh(data=N)" | "single"
        self.last_audit_path: Optional[str] = None
        # degraded-mode quarantine: an eval/compile failure benches the
        # kind's device program behind its own breaker (exponential
        # backoff, capped) instead of demoting it forever — affected
        # reviews serve from the interpreter, and a half-open probe
        # sweep restores the device path once it succeeds. kind ->
        # {"until", "fails", "reason", "probe_at"}; guarded by its own
        # lock (webhook flusher threads and the audit loop race here)
        self._quarantine: dict[str, dict] = {}
        self._quarantine_lock = threading.Lock()
        # failure-history memory: kind -> (fails, cleared_at). A kind
        # that re-quarantines shortly after clearing resumes its
        # exponential backoff instead of restarting at base — a
        # data-dependent failure mixed with successes must converge to
        # long benchings, not flap at base_s forever
        self._quarantine_hist: dict[str, tuple] = {}
        self.quarantine_base_s = float(_os.environ.get(
            "GATEKEEPER_TPU_QUARANTINE_BASE_S", "30"))
        self.quarantine_max_s = float(_os.environ.get(
            "GATEKEEPER_TPU_QUARANTINE_MAX_S", "600"))
        # optional observer wired by the control plane (template status)
        self.on_quarantine: Optional[Any] = None
        # per-(kind, path) evaluation counters for /debug/templates:
        # how many sweeps/batches each template served from the device,
        # the delta cache, or the interpreter fallback
        self._eval_counts: dict[tuple, int] = {}
        self._eval_counts_lock = threading.Lock()
        # duty cycle: eval wall clock accumulated since the last
        # duty_cycle() sample (device sweeps, batched admission evals,
        # join probes, interpreter fallback), EMA-smoothed per sample
        # window — "engine idle, edge saturated" must be readable off
        # one scrape (gatekeeper_tpu_device_duty_cycle{engine})
        self._busy_s = 0.0
        self._busy_t0 = time.monotonic()
        self._duty_ema = 0.0
        self._duty_sampled = False
        # vectorized message materialization (ir/vecmat.py): per-kind
        # message plans (None = exact path) and rendered witness
        # columns keyed (target, witness) — both rebuilt lazily
        self._msg_plans: dict[str, Any] = {}
        self._witcols: dict = {}
        # per-kind audit results observer (control/audit.py's streaming
        # status writer): called (target, kind, results) as each kind's
        # sweep completes, so status publishing overlaps the remaining
        # kinds' device sweeps
        self.on_kind_results: Optional[Any] = None
        # interpreter-bound kinds: kind -> {"reason", "dense", "join"}
        # — the stable Uncompilable taxonomy code (+ detail prose per
        # compile stage) recorded at ingestion, surfaced through
        # /debug/templates and gatekeeper_tpu_compile_fallback_total
        # so "why is this kind slow" is answerable without a debugger
        self._fallback: dict[str, dict] = {}
        # AOT program store (ir/aot.py): serialized compiled executables
        # + warm sweep signatures, persisted under the statestore's
        # state dir (<state-dir>/aot) so a warm boot deserializes the
        # exact device programs instead of recompiling them.
        # GATEKEEPER_TPU_AOT_DIR overrides for bench/test processes.
        from .aot import AotStore

        self.aot = AotStore()
        aot_dir = aot_dir or _os.environ.get("GATEKEEPER_TPU_AOT_DIR", "")
        if aot_dir:
            self.aot.set_dir(aot_dir)
        # constraint-count (C-axis) power-of-two bucketing: library
        # edits that stay inside a bucket re-hit every cached device
        # program (GATEKEEPER_TPU_CBUCKET=0 restores exact-C shapes
        # for differential comparisons)
        self.cbucket = _os.environ.get(
            "GATEKEEPER_TPU_CBUCKET", "1") != "0"

    def _build_mesh(self, mesh):
        import os

        if self._device is not None:
            return None  # a pinned engine owns exactly one chip
        if mesh is not None:
            return mesh
        cfg = os.environ.get("GATEKEEPER_TPU_MESH", "auto").lower()
        if cfg in ("off", "0", "none", ""):
            return None
        import jax

        devices = jax.devices()
        if cfg not in ("auto", "all"):
            try:
                devices = devices[: int(cfg)]
            except ValueError:
                log.warning("GATEKEEPER_TPU_MESH=%r not understood; "
                            "using all %d devices", cfg, len(devices))
        if len(devices) < 2:
            return None
        # the data axis must divide the power-of-two extraction buckets
        # (_mesh_shardable): round down so e.g. 6 visible devices shard
        # over 4 instead of silently never taking the mesh path
        pow2 = 1 << (len(devices).bit_length() - 1)
        if pow2 != len(devices):
            log.warning("mesh data axis rounded down to %d of %d devices "
                        "(power-of-two bucket divisibility)", pow2,
                        len(devices))
            devices = devices[:pow2]
        from ..parallel.mesh import make_mesh

        return make_mesh(devices=devices)

    # ------------------------------------------------------------- modules

    def put_modules(self, prefix: str, modules: Iterable[A.Module]) -> None:
        mods = list(modules)
        super().put_modules(prefix, mods)
        m = _PREFIX_RE.match(prefix)
        if not m:
            return
        kind = m.group(2)
        self._compiled.pop(kind, None)
        self._programs.pop(kind, None)
        self._modules.pop(kind, None)
        self._derived_cols.pop(kind, None)
        self._param_cache.pop(kind, None)
        self._feat_cache.pop(kind, None)
        self._join_progs.pop(kind, None)
        self._join_compiled.pop(kind, None)
        self._join_frz[2].pop(kind, None)  # template update: stale keys
        self._data_taint.pop(kind, None)
        self._msg_plans.pop(kind, None)
        self._drop_audit_results(kind)
        self._drop_warm(kind)  # new CompiledTemplate = cold jit caches
        self._fallback.pop(kind, None)
        module = mods[0] if len(mods) == 1 else merge_template_modules(mods)
        if module is None:
            self._compiled[kind] = None
            self._note_fallback(
                kind, dense=("module-shape",
                             "template entry/lib module merge failed"))
            return
        try:
            self._programs[kind] = compile_template(module, kind)
            self._modules[kind] = module
        except Uncompilable as de:
            self._compiled[kind] = None
            # cross-object templates: try the inventory-join compiler
            from .join import compile_join
            try:
                self._join_progs[kind] = compile_join(module, kind)
            except Uncompilable as je:
                self._note_fallback(kind, dense=(de.code, de.detail),
                                    join=(je.code, je.detail),
                                    reads_data=_module_reads_data(module))
        # off-path compilation starts at INGESTION: build the device
        # evaluator now (cheap host work on the ingesting thread — the
        # intern table is not thread-safe, so resolve_consts must not
        # run from a background thread) and deserialize any AOT-stored
        # executables for it in the background, so the first sweep at a
        # remembered shape dispatches with zero on-path compilation
        self._enqueue_prewarm(kind)

    def delete_modules(self, prefix: str) -> int:
        n = super().delete_modules(prefix)
        m = _PREFIX_RE.match(prefix)
        if m:
            self._compiled.pop(m.group(2), None)
            self._programs.pop(m.group(2), None)
            self._modules.pop(m.group(2), None)
            self._derived_cols.pop(m.group(2), None)
            self._join_progs.pop(m.group(2), None)
            self._join_compiled.pop(m.group(2), None)
            self._join_frz[2].pop(m.group(2), None)
            self._data_taint.pop(m.group(2), None)
            self._msg_plans.pop(m.group(2), None)
            self._fallback.pop(m.group(2), None)
            self._drop_audit_results(m.group(2))
            self._drop_warm(m.group(2))
        return n

    def _drop_audit_results(self, kind: str) -> None:
        for key in [k for k in self._audit_results_cache if k[1] == kind]:
            del self._audit_results_cache[key]

    def _drop_warm(self, kind: str) -> None:
        """Template update/delete: a fresh CompiledTemplate starts with
        empty jit caches, so its sweep shapes are NOT warm even when
        the tensor shapes match a previous generation's signature."""
        with self._warm_lock:
            self._warm_done = {s for s in self._warm_done
                               if self._sig_kind(s) != kind}
            self._warm_restored = {s for s in self._warm_restored
                                   if self._sig_kind(s) != kind}
            self._warm_fail = {s: c for s, c in self._warm_fail.items()
                               if self._sig_kind(s) != kind}

    @staticmethod
    def _sig_kind(sig: tuple):
        """The kind a sweep signature belongs to (dense-batch sigs are
        prefixed with "dense"; see _sweep_sig)."""
        return sig[1] if sig and sig[0] == "dense" else sig[0]

    def _enqueue_prewarm(self, kind: str) -> None:
        """Ingest-time off-path compile: build the device evaluator for
        `kind` inline (host-only work — program compile, const
        resolution), then deserialize its AOT-stored executables and
        mark the store's remembered sweep signatures warm on a
        background thread. After this, a warm boot's first sweep at a
        remembered shape dispatches straight onto the device — no
        lowering, no XLA, no host-fallback round."""
        if not self.async_warm:
            return  # deterministic-dispatch mode (tests) stays lazy
        try:
            ct = self.compiled_for(kind)
            jc = self.join_for(kind) if ct is None else None
        except Exception:  # lazy path will surface/demote properly
            return
        if (ct is None and jc is None) or not self.aot.enabled:
            return

        def run():
            try:
                if ct is not None:
                    loaded = ct.preload_aot(self._mesh)
                    n = sum(loaded.values())
                    if n:
                        self._mark_stored_sigs_warm(ct.fingerprint,
                                                    loaded)
                        log.info(
                            "%d AOT device programs for %s deserialized "
                            "at ingestion (warm sweep shapes dispatch "
                            "with zero compilation)", n, kind)
                elif jc is not None:
                    jc.preload_aot()
            except Exception as e:  # prewarm is best-effort
                log.debug("AOT prewarm for %s failed: %s", kind, e)

        threading.Thread(target=run, daemon=True,
                         name=f"aot-prewarm-{kind}").start()

    def prewarm_templates(self, kinds) -> int:
        """Re-run the ingest-time off-path AOT preload for the given
        template kinds (the adaptive controller's churn-triggered
        actuation: after a burst of library ops settles, every known
        kind's stored executables deserialize in the background so the
        first post-churn evaluation dispatches warm). Best-effort and
        cheap to repeat — kinds already warm re-adopt idempotently.
        Returns how many kinds were enqueued."""
        n = 0
        for kind in kinds:
            self._enqueue_prewarm(kind)
            n += 1
        return n

    def _mark_stored_sigs_warm(self, fingerprint: str,
                               loaded: dict) -> None:
        """Adopt the store's remembered sweep signatures as warm. Mesh
        signatures are only adopted when mesh programs actually
        deserialized (a topology drift would otherwise send the first
        audit into an inline compile)."""
        mesh_ok = bool(loaded.get("mesh") or loaded.get("mesh-slab"))
        sigs = self.aot.sigs_for(fingerprint)
        with self._warm_lock:
            for sig in sigs:
                use_mesh = (sig[2] if sig and sig[0] == "dense"
                            else (sig[1] if len(sig) > 1 else False))
                if use_mesh is True and not mesh_ok:
                    continue
                self._warm_done.add(sig)
                # adoption is optimistic: the sig's exact executable may
                # not have persisted (save refused, store GC'd), so its
                # first dispatch runs no-inline-compile guarded and
                # un-adopts on a miss rather than stalling on XLA
                self._warm_restored.add(sig)

    def compiled_for(self, kind: str) -> Optional[CompiledTemplate]:
        """Lazily wrap the Program in a device evaluator, registering its
        derived columns (host-interpreted unary fns) and interpreted
        predicate ops with the shared tables. A quarantined kind answers
        None (interpreter fallback) until its breaker half-opens."""
        if self._quarantine and self._quarantined(kind):
            return None
        if kind in self._compiled:
            return self._compiled[kind]
        prog = self._programs.get(kind)
        if prog is None:
            self._compiled[kind] = None
            return None
        try:
            module = self._modules[kind]
            cols: list[int] = []
            for spec in prog.derived:
                if spec.kind == "fn":
                    key = ("fn", kind, spec.arg)
                    fn = interp_unary(module, spec.arg)
                elif spec.kind == "split":
                    sep, i, k = spec.arg.rsplit("|", 2)
                    key = ("split", spec.arg)
                    fn = split_part(sep, int(i), int(k))
                elif spec.kind == "strip_prefix":
                    key = ("strip_prefix", spec.arg)
                    fn = strip_prefix(spec.arg)
                elif spec.kind == "builtin":
                    key = ("builtin", spec.arg)
                    fn = builtin_unary(spec.arg)
                else:
                    raise EvalError(f"unknown derived kind {spec.kind}")
                cols.append(self.derived_tables.col(key, fn))
            for op, fn_name in prog.pred_ops:
                pat_i = int(op.rsplit(":", 1)[1])
                self.match_tables.register_op(
                    op, interp_pred(module, fn_name, pat_i))
            ct = CompiledTemplate(prog, self.strtab, self.match_tables,
                                  aot=self.aot, kind=kind)
            self._derived_cols[kind] = cols
        except Exception as e:
            self._demote(kind, "lowering", e)
            ct = None
        self._compiled[kind] = ct
        return ct

    def _note_fallback(self, kind: str, dense: tuple,
                       join: Optional[tuple] = None,
                       reads_data: bool = False) -> None:
        """Record WHY a kind is interpreter-bound (both compile stages'
        taxonomy codes) and count it. The headline `reason` label picks
        the stage the template was actually headed for: a data-reading
        template fails usefully in the JOIN compiler (its dense failure
        is just "you read data"), a review-pure one in the dense
        compiler."""
        reason = (join[0] if join is not None and reads_data
                  else dense[0])
        self._fallback[kind] = {
            "reason": reason,
            "dense": {"code": dense[0], "detail": dense[1]},
            "join": ({"code": join[0], "detail": join[1]}
                     if join is not None else None),
        }
        log.info("template %s is interpreter-bound (%s): dense=%s join=%s",
                 kind, reason, dense, join)
        try:
            from ..control.metrics import report_compile_fallback

            report_compile_fallback(kind, reason)
        except Exception:  # metrics backend optional in embedders
            pass

    def fallback_reasons(self) -> dict:
        """kind -> {"reason", "dense": {code, detail}, "join": {...}}
        for every interpreter-bound kind (empty when the whole library
        is device-compiled)."""
        return {k: dict(v) for k, v in self._fallback.items()}

    def _demote(self, kind: str, reason: str, exc: Exception) -> None:
        """A device->interpreter demotion is a ~10^4x per-eval slowdown;
        it must never be silent (each one is logged and counted)."""
        from ..control.metrics import report_device_demotion

        log.warning(
            "template %s demoted to interpreter path (%s): %s: %s",
            kind, reason, type(exc).__name__, exc)
        report_device_demotion(kind, reason)

    # -------------------------------------------------- eval quarantine

    def _quarantine_kind(self, kind: str, reason: str,
                         exc: Exception) -> None:
        """Bench one kind's device program after an eval failure: the
        quarantine (NOT a permanent demotion) has exponential backoff
        with a cap, so one bad template degrades that template's latency
        — never the process's availability — and the device path heals
        itself when the failure was transient."""
        import time as _time

        why = f"{reason}: {type(exc).__name__}: {exc}"
        with self._quarantine_lock:
            ent = self._quarantine.get(kind)
            if ent is None:
                hist = self._quarantine_hist.pop(kind, None)
                base_fails = 0
                if hist is not None and \
                        _time.monotonic() - hist[1] < self.quarantine_max_s:
                    base_fails = hist[0]  # resume the backoff ladder
                ent = {"fails": base_fails}
            ent["fails"] += 1
            backoff = min(self.quarantine_base_s
                          * (2 ** (ent["fails"] - 1)),
                          self.quarantine_max_s)
            ent["until"] = _time.monotonic() + backoff
            ent["reason"] = why
            ent["probe_at"] = None
            self._quarantine[kind] = ent
            fails = ent["fails"]
        # forget the wrapper (compiled_for re-wraps from the kept
        # Program after the quarantine lifts) and its warm state
        self._compiled.pop(kind, None)
        self._drop_warm(kind)
        self._demote(kind, reason, exc)
        from ..control.metrics import report_template_quarantine

        report_template_quarantine(kind, True)
        log.warning("template %s quarantined %.0fs (failure #%d); its "
                    "reviews serve from the interpreter until a probe "
                    "sweep succeeds", kind, backoff, fails)
        self._notify_quarantine(kind, why)

    # a half-open probe that never resolves (e.g. the cost model routed
    # it to the host without touching the device) releases its lease
    # after this long, letting another caller probe
    QUARANTINE_PROBE_LEASE_S = 30.0

    def _quarantined(self, kind: str) -> bool:
        """True while the kind's device program is benched. After the
        backoff expires the state is HALF-OPEN: ONE caller at a time
        takes the probe lease and attempts the device path — success
        clears, failure re-quarantines with a doubled backoff — while
        every other caller stays on the interpreter (a thundering herd
        of doomed probes must not pay the failure latency N times on
        the admission path)."""
        import time as _time

        with self._quarantine_lock:
            ent = self._quarantine.get(kind)
            if ent is None:
                return False
            now = _time.monotonic()
            if now < ent["until"]:
                return True
            probe_at = ent.get("probe_at")
            if probe_at is not None and \
                    now - probe_at < self.QUARANTINE_PROBE_LEASE_S:
                # a probe is in flight; stay on the interpreter
                return True
            ent["probe_at"] = now
            return False

    def _quarantine_clear(self, kind: str) -> None:
        """A device eval of this kind succeeded: close the breaker —
        but ONLY for a sanctioned half-open probe (probe_at set). An
        eval that was already in flight when another thread quarantined
        the kind must not wipe the fresh entry milliseconds later."""
        import time as _time

        with self._quarantine_lock:
            ent = self._quarantine.get(kind)
            if ent is None or ent.get("probe_at") is None:
                return
            del self._quarantine[kind]
            self._quarantine_hist[kind] = (ent["fails"],
                                           _time.monotonic())
        from ..control.metrics import report_template_quarantine

        report_template_quarantine(kind, False)
        log.info("template %s recovered: device path restored after "
                 "quarantine (%d failures)", kind, ent["fails"])
        self._notify_quarantine(kind, None)

    def _notify_quarantine(self, kind: str, reason) -> None:
        """Run the control-plane observer OFF the serving thread: the
        callback writes template status through the kube API, and a
        quarantine raised mid-flush must never make co-batched
        admission verdicts wait on (possibly degraded) API I/O."""
        cb = self.on_quarantine
        if cb is None:
            return

        def run():
            try:
                cb(kind, reason)
            except Exception as e:
                # observability loss, not correctness: say so instead
                # of silently dropping the status update
                log.warning("quarantine status notification for %s "
                            "failed: %s: %s", kind, type(e).__name__, e)

        threading.Thread(target=run, daemon=True,
                         name=f"quarantine-note-{kind}").start()

    def quarantine_status(self) -> dict:
        """Observability: currently-benched kinds with reason, failure
        count, and remaining backoff (surfaced in audit logs, metrics,
        and template byPod status)."""
        import time as _time

        now = _time.monotonic()
        with self._quarantine_lock:
            return {k: {"reason": e.get("reason"),
                        "fails": e.get("fails", 0),
                        "remaining_s": max(0.0, e.get("until", now) - now)}
                    for k, e in self._quarantine.items()}

    def compiled_kinds(self) -> list[str]:
        return sorted(set(self._programs) | set(self._join_progs))

    def note_eval(self, kind: str, path: str,
                  seconds: Optional[float] = None) -> None:
        """Count one evaluation of `kind` via `path` (device / delta /
        interp / join): the per-template eval breakdown /debug/templates
        reports. `seconds` (eval wall clock, when the call site timed
        it) accumulates into the engine's busy fraction — the
        duty-cycle gauge's raw signal."""
        with self._eval_counts_lock:
            self._eval_counts[(kind, path)] = \
                self._eval_counts.get((kind, path), 0) + 1
        if seconds:
            self.note_busy(seconds)

    def note_busy(self, seconds: float) -> None:
        """Accumulate eval wall clock toward the duty-cycle sample."""
        if seconds <= 0:
            return
        with self._eval_counts_lock:
            self._busy_s += seconds

    def duty_cycle(self, ema_alpha: float = 0.3,
                   min_window_s: float = 0.05) -> float:
        """Busy-fraction EMA of this engine's evaluator, sampled per
        call (the metrics scrape probe): busy eval seconds since the
        last sample over elapsed wall clock, EMA-smoothed so one idle
        scrape interval doesn't zero a busy engine's reading.
        Concurrent evals can push a raw window past 1.0 (several
        threads blocked on one device); the fraction clamps because
        the gauge answers "is the engine busy", not "how oversubscribed
        is it"."""
        now = time.monotonic()
        with self._eval_counts_lock:
            elapsed = now - self._busy_t0
            if elapsed < min_window_s:
                return self._duty_ema  # scrape storm: keep the sample
            raw = min(1.0, self._busy_s / elapsed) if elapsed > 0 else 0.0
            self._busy_s = 0.0
            self._busy_t0 = now
            if not self._duty_sampled:
                # first sample seeds the EMA instead of decaying a
                # meaningless zero
                self._duty_ema = raw
                self._duty_sampled = True
            else:
                self._duty_ema = (ema_alpha * raw
                                  + (1.0 - ema_alpha) * self._duty_ema)
            return self._duty_ema

    def templates_debug(self) -> dict:
        """Per-template compile/serve state for /debug/templates: how
        each kind evaluates right now (device program, join program, or
        interpreter), its quarantine state, eval counts by path, and
        the HLO-dump pointer (profiling.compiled_hlo renders the exact
        device program; the XLA_FLAGS dump dir captures what the
        COMPILER emitted)."""
        quarantined = self.quarantine_status()
        with self._eval_counts_lock:
            counts = dict(self._eval_counts)
        out = {}
        # the program maps mutate from compile/eval threads (lazy
        # compiled_for inserts, background warms) with no shared lock;
        # snapshotting can race a resize mid-iteration, so retry the
        # cheap copy instead of 500ing the endpoint during exactly the
        # compile churn an operator is most likely to be inspecting
        for _attempt in range(5):
            try:
                programs = set(self._programs)
                joins = set(self._join_progs)
                kinds = (set(self._compiled) | programs | joins
                         | {k for (k, _p) in counts})
                break
            except RuntimeError:
                continue
        else:
            programs = joins = set()
            kinds = {k for (k, _p) in counts}
        for kind in sorted(kinds):
            if kind in programs:
                state = "compiled"
            elif kind in joins:
                state = "join"
            else:
                state = "interpreter"
            evals = {p: n for (k, p), n in sorted(counts.items())
                     if k == kind}
            out[kind] = {
                "state": state,
                # why an interpreter-bound kind didn't compile: the
                # stable taxonomy code + per-stage detail (None for
                # device-compiled kinds)
                "fallback": self._fallback.get(kind),
                "quarantine": quarantined.get(kind),
                "eval_counts": evals,
                # per-kind compile provenance: recent device-program
                # acquisitions with source (aot=deserialized, cache=
                # persistent-XLA-cache, fresh=cold compile), seconds,
                # and the (static-config, shape-bucket) key
                "compile": self.aot.events_for(kind),
                "hlo_dump": ("gatekeeper_tpu.utils.profiling."
                             f"compiled_hlo(driver.compiled_for({kind!r})"
                             ", ...) renders the device program; set "
                             "XLA_FLAGS=--xla_dump_to=<dir> to capture "
                             "the compiler's own dumps"),
            }
        return {"templates": out,
                "warm": self.warm_status(),
                "mesh": None if self._mesh is None
                else dict(self._mesh.shape)}

    def join_for(self, kind: str):
        """Lazily wrap a JoinProgram in its runtime evaluator. A
        quarantined kind answers None (interpreter fallback) until its
        breaker half-opens — same self-healing as compiled_for."""
        if self._quarantine and self._quarantined(kind):
            return None
        if kind in self._join_compiled:
            return self._join_compiled[kind]
        prog = self._join_progs.get(kind)
        jc = None
        if prog is not None:
            from .join import JoinCompiled
            try:
                jc = JoinCompiled(prog, self.strtab, aot=self.aot,
                                  kind=kind)
            except Exception as e:
                self._demote(kind, "join-lowering", e)
                jc = None
        self._join_compiled[kind] = jc
        return jc

    # ------------------------------------------------------ audit sharding

    def set_audit_shard(self, shard_id: Optional[int],
                        shard_count: int = 1, vnodes: int = 64) -> None:
        """Scope this driver's audit review set to one consistent-hash
        slice of the inventory (control/shardmap.py). shard_id=None or
        shard_count<=1 clears the filter. The data TREE is not filtered
        here — the sharded plane feeds each shard its owned objects
        plus the join/namespace broadcast set, and review building is
        what decides which objects this shard actually sweeps."""
        if shard_id is None or shard_count <= 1:
            self._audit_shard = None
            self.set_audit_review_filter(None)
            return
        from ..control.shardmap import ShardMap

        smap = ShardMap(shard_count, vnodes)
        sid = int(shard_id)
        self._audit_shard = (sid, int(shard_count))

        def owns(gv: str, kind: str, namespace: str) -> bool:
            group, _, version = gv.rpartition("/")
            return smap.owner((group, version, kind), namespace) == sid

        self.set_audit_review_filter(owns)

    def audit_broadcast_spec(self) -> dict:
        """What the leader must replicate to EVERY shard for non-owned
        objects, derived from the loaded templates:

          {"full": bool,             # give up: broadcast all, whole
           "kinds": {kind: columns}} # kind "*" = any kind; columns:
                                     # list of path tuples, or None =
                                     # whole object

        Join templates (ir/join.py) reach other objects only through
        data.inventory generator bindings (`other := data.inventory.
        namespace[ns][apiv][kind][name]`) and then read a handful of
        columns off the bound object — directly (`other.spec.selector`)
        or through helper functions (`selector_key(other)`). Tracing
        those reads (including one level of helper-param dataflow)
        yields exactly the columns a foreign shard's copy must carry —
        the sik join-key inputs — so 10M-object broadcasts ship pruned
        skeletons, not manifests. Anything the walk cannot prove
        degrades conservatively (whole object, or full-inventory
        broadcast for interpreted data-reading templates): sharding
        must never change a verdict. Namespace objects are always
        broadcast whole — namespaceSelector matching reads their
        labels on every shard."""
        from .join import _split_inv_ref as _join_split

        spec: dict = {"full": False, "kinds": {"Namespace": None}}

        def add_kind(kind: str, columns) -> None:
            cur = spec["kinds"].get(kind)
            if kind not in spec["kinds"]:
                cur = []
                spec["kinds"][kind] = cur
            if columns is None or cur is None:
                spec["kinds"][kind] = None
                return
            for c in columns:
                if c not in cur:
                    cur.append(c)

        for prog in self._join_progs.values():
            if prog is None:
                continue
            rules_by_name: dict[str, list] = {}
            for r in prog.module.rules:
                rules_by_name.setdefault(r.name, []).append(r)
            memo: dict = {}

            def var_columns(rule, vname: str, stack):
                """Column paths `rule` reads off the object bound to
                `vname`; None when the object escapes the analysis
                (used bare, aliased, or fed to an unknown function)."""
                cols: list = []
                whole = [False]

                def walk(t) -> None:
                    if isinstance(t, A.Ref) and \
                            isinstance(t.base, A.Var) and \
                            t.base.name == vname:
                        prefix = []
                        for a in t.args:
                            if isinstance(a, A.Scalar) and \
                                    isinstance(a.value, str):
                                prefix.append(a.value)
                            else:
                                break
                        if prefix:
                            cols.append(tuple(prefix))
                        else:
                            whole[0] = True
                        for a in t.args:
                            walk(a)
                        return
                    if isinstance(t, A.Call):
                        for i, a in enumerate(t.args):
                            if isinstance(a, A.Var) and a.name == vname:
                                c = param_columns(t.fn, i, stack)
                                if c is None:
                                    whole[0] = True
                                else:
                                    cols.extend(c)
                            else:
                                walk(a)
                        return
                    if isinstance(t, A.Var):
                        if t.name == vname:
                            whole[0] = True
                        return
                    if isinstance(t, (list, tuple)):
                        for x in t:
                            walk(x)
                    elif hasattr(t, "__dataclass_fields__"):
                        for f in t.__dataclass_fields__:
                            walk(getattr(t, f))

                walk(rule.key)
                walk(rule.value)
                for lit in rule.body or ():
                    e = lit.expr
                    if isinstance(e, (A.Assign, A.Unify)):
                        # skip the generator binding itself; flag any
                        # OTHER alias of the object as an escape
                        sides = (e.lhs, e.rhs)
                        if any(isinstance(s, A.Var) and s.name == vname
                               for s in sides):
                            if any(_inv_gen_of(s) for s in sides):
                                continue
                            whole[0] = True
                            continue
                    walk(lit)
                return None if whole[0] else cols

            def param_columns(fn, idx: int, stack):
                """Columns function `fn` reads off positional param
                `idx`, across all its clauses; None = escapes."""
                if len(fn) != 1 or fn[0] not in rules_by_name:
                    return None
                key = (fn[0], idx)
                if key in memo:
                    return memo[key]
                if key in stack:
                    return []  # recursive clause adds nothing new
                cols: list = []
                for r in rules_by_name[fn[0]]:
                    if not r.args or idx >= len(r.args) or \
                            not isinstance(r.args[idx], A.Var):
                        memo[key] = None
                        return None
                    c = var_columns(r, r.args[idx].name, stack + (key,))
                    if c is None:
                        memo[key] = None
                        return None
                    cols.extend(c)
                memo[key] = cols
                return cols

            def _inv_gen_of(t):
                """(kind-or-*, ok) when t is an inventory object ref
                addressing exactly one object; None otherwise."""
                if not (isinstance(t, A.Ref) and isinstance(t.base, A.Var)
                        and t.base.name == "data" and t.args
                        and isinstance(t.args[0], A.Scalar)
                        and t.args[0].value == "inventory"):
                    return None
                split = _join_split(t)
                if split is None or split[1]:
                    return ("*", False)  # odd shape: give up later
                scope = t.args[1].value
                kind_arg = t.args[4 if scope == "namespace" else 3]
                if isinstance(kind_arg, A.Scalar) and \
                        isinstance(kind_arg.value, str):
                    return (kind_arg.value, True)
                return ("*", True)

            bound_refs: set = set()
            for rule in prog.module.rules:
                for lit in rule.body or ():
                    e = lit.expr
                    if not isinstance(e, (A.Assign, A.Unify)):
                        continue
                    for var_side, ref_side in ((e.lhs, e.rhs),
                                               (e.rhs, e.lhs)):
                        gen = _inv_gen_of(ref_side)
                        if gen is None or not isinstance(var_side,
                                                         A.Var):
                            continue
                        bound_refs.add(id(ref_side))
                        kind, ok = gen
                        if not ok:
                            spec["full"] = True
                            continue
                        add_kind(kind, var_columns(rule, var_side.name,
                                                   ()))
            # any inventory ref NOT consumed as a generator binding
            # (inline residual reads, negated absence checks, odd
            # shapes) is handled from its own split — or gives up

            def sweep(t) -> None:
                if isinstance(t, A.Ref) and isinstance(t.base, A.Var) \
                        and t.base.name == "data" and t.args \
                        and isinstance(t.args[0], A.Scalar) \
                        and t.args[0].value == "inventory":
                    if id(t) not in bound_refs:
                        split = _join_split(t)
                        kind = None
                        if split is not None:
                            scope = t.args[1].value
                            ka = t.args[4 if scope == "namespace"
                                        else 3]
                            kind = ka.value \
                                if isinstance(ka, A.Scalar) and \
                                isinstance(ka.value, str) else "*"
                        if split is None:
                            spec["full"] = True
                        else:
                            prefix = []
                            for a in split[1]:
                                if isinstance(a, A.Scalar) and \
                                        isinstance(a.value, str):
                                    prefix.append(a.value)
                                else:
                                    break
                            add_kind(kind,
                                     [tuple(prefix)] if prefix
                                     else None)
                if isinstance(t, (list, tuple)):
                    for x in t:
                        sweep(x)
                elif hasattr(t, "__dataclass_fields__"):
                    for f in t.__dataclass_fields__:
                        sweep(getattr(t, f))

            sweep(prog.module.rules)
        # interpreted (non-join) templates that read `data` see the raw
        # tree — a shard's partial tree would change their answers
        for kind in self._modules:
            if kind not in self._join_progs and \
                    self._template_reads_data(kind):
                spec["full"] = True
        return spec

    # ---------------------------------------------------------------- data

    def put_data(self, path: tuple, data: Any) -> None:
        super().put_data(path, data)
        self._bump(path)

    def delete_data(self, path: tuple) -> bool:
        out = super().delete_data(path)
        self._bump(path)
        return out

    def drop_inventory_caches(self) -> None:
        """Full re-encode backstop (see RegoDriver): additionally drops
        the encoded feature tensors, match masks, and join caches so the
        next audit re-extracts and re-uploads everything. Device buffers
        of the dropped host arrays self-evict via their weakrefs."""
        super().drop_inventory_caches()
        self._data_gen += 1
        self._feat_cache.clear()
        self._mask_cache.clear()
        self._join_frz = (None, {}, {})
        self._audit_results_cache.clear()
        self._review_idx_cache = (None, None, None)
        self._witcols.clear()

    # --------------------------------------------- warm-restart snapshots

    def vocab_snapshot(self) -> dict:
        """The intern table, for the durable state snapshot. Restoring
        it on boot keeps string ids — and the vocab-capacity buckets
        XLA program shapes are specialized on — identical across
        restarts, so both the persisted encoded rows and the persistent
        compilation cache stay valid."""
        return {"strings": self.strtab.dump()}

    def vocab_restore(self, snap: dict) -> None:
        """Replay a vocab snapshot onto this driver's FRESH strtab
        (boot-time only; StringTable.restore refuses otherwise)."""
        self.strtab.restore(snap.get("strings") or [])

    def encoded_rows_snapshot(self) -> Optional[dict]:
        """Per-kind encoded feature tensors whose cache provably matches
        the current data tree (meta rev == data rev: no unapplied
        journal entries). Restored rows let the first warm audit skip
        re-extraction entirely. None when nothing is current."""
        out = {}
        for kind, fcache in self._feat_cache.items():
            meta = fcache.get("__meta__")
            if meta is None or meta.get("cand") is None:
                continue
            if meta.get("rev") != self._data_rev:
                continue  # stale vs the tree; next audit refreshes it
            out[kind] = {"feats": meta["feats"], "cand": meta["cand"],
                         "buckets": meta["buckets"],
                         "n_pad": meta["n_pad"]}
        return out or None

    def mark_rows_restore_base(self) -> None:
        """Pin the no-writes-since-restore generation NOW (called
        synchronously right after inventory_restore, BEFORE the rows
        blob loads on a background thread): a delta applied while the
        blob is still loading must invalidate the stashed rows, so the
        guard generation cannot be captured at load-completion time."""
        self._restored_rows_base = self._data_gen

    def encoded_rows_restore(self, rows: dict) -> None:
        """Stash snapshotted feature tensors for lazy adoption: the
        first audit adopts a kind's rows iff its freshly-computed
        candidate set matches the snapshot AND no inventory write
        happened since the restore BASE (mark_rows_restore_base, or
        now for synchronous callers — any delta means the rows may be
        stale; extraction rebuilds them, the safe cold path). Requires
        the vocab snapshot to have been restored first: the tensors
        hold interned string ids."""
        self._restored_rows = dict(rows or {})
        base = getattr(self, "_restored_rows_base", None)
        self._restored_rows_gen = \
            base if base is not None else self._data_gen
        self.restored_rows_adopted = 0

    def _adopt_restored_rows(self, kind: str, cand, feat_key,
                             fcache: dict):
        stash_all = getattr(self, "_restored_rows", None)
        if not stash_all or cand is None:
            return None
        if self._data_gen != getattr(self, "_restored_rows_gen", -1):
            # inventory changed since restore: every stashed kind is
            # suspect — drop the lot and let extraction rebuild
            self._restored_rows = {}
            return None
        stash = stash_all.pop(kind, None)
        if stash is None:
            return None
        try:
            if not np.array_equal(np.asarray(stash["cand"]),
                                  np.asarray(cand)):
                return None  # constraints/inventory drifted: re-extract
            feats = stash["feats"]
        except Exception:
            return None
        fcache.clear()
        fcache["__meta__"] = {
            "key": feat_key, "feats": feats,
            "cand": np.asarray(cand), "buckets": stash["buckets"],
            "n_pad": stash["n_pad"], "rev": self._data_rev,
        }
        self.restored_rows_adopted = \
            getattr(self, "restored_rows_adopted", 0) + 1
        return feats

    def _bump(self, path: tuple) -> None:
        if path and path[0] == "constraints":
            self._constraint_gen += 1
            self._param_cache.clear()
        else:
            self._data_gen += 1
            # feature/device caches survive: single-object replacements
            # replay through the patch journal (_notes_between) against
            # the cached tensors; uncovered ranges rebuild lazily on the
            # next audit. Device buffers of superseded host arrays
            # self-evict via their weakrefs.

    def _dev(self, tree):
        """Device-resident view of a tree of host ndarrays, cached by leaf
        identity. Entries hold the host array WEAKLY and self-evict when
        the producing cache drops it — a strong ref would pin superseded
        arrays (and their device buffers) until the next data mutation,
        an unbounded leak on a long-running webhook whose vocab grows."""
        import weakref

        import jax

        cache = self._dev_cache

        device = self._device

        def put(arr):
            key = id(arr)
            hit = cache.get(key)
            if hit is not None and hit[0]() is arr:
                return hit[1]
            d = jax.device_put(arr, device)
            try:
                ref = weakref.ref(arr, lambda _r, k=key: cache.pop(k, None))
            except TypeError:
                return d  # unweakrefable leaf: use without caching
            cache[key] = (ref, d)
            return d

        return jax.tree_util.tree_map(put, tree)

    # mesh placement cache bound: entries weak-evict with their host
    # arrays, but a churn-heavy long-lived audit can cycle through many
    # LIVE host arrays (per-kind feature trees, padded vocab copies),
    # growing device-placement entries without bound — LRU-evict past
    # this many leaves (each eviction only drops a resident sharded
    # buffer; the next sweep re-distributes that leaf)
    DEV_MESH_CACHE_MAX = 512

    def _dev_mesh(self, tree, data_leading: bool):
        """Mesh placement twin of _dev: leaves are device_put with a
        NamedSharding — leading axis split over "data" for feature
        tensors, fully replicated for params/tables — and cached weakly
        by host-array identity (LRU-bounded by DEV_MESH_CACHE_MAX), so
        steady-state mesh audits re-dispatch over resident sharded
        buffers instead of re-distributing every sweep."""
        import weakref

        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = self._mesh
        cache = self._dev_mesh_cache

        def put(arr):
            key = (id(arr), data_leading)
            hit = cache.get(key)
            if hit is not None and hit[0]() is arr:
                # LRU: refresh recency (dicts keep insertion order only,
                # so a hit must re-insert to move to the back)
                del cache[key]
                cache[key] = hit
                return hit[1]
            if data_leading and getattr(arr, "ndim", 0) >= 1:
                spec = P("data", *([None] * (arr.ndim - 1)))
            else:
                spec = P(*([None] * getattr(arr, "ndim", 0)))
            d = jax.device_put(arr, NamedSharding(mesh, spec))
            try:
                ref = weakref.ref(arr, lambda _r, k=key: cache.pop(k, None))
            except TypeError:
                return d
            cache[key] = (ref, d)
            while len(cache) > self.DEV_MESH_CACHE_MAX:
                cache.pop(next(iter(cache)), None)
            return d

        return jax.tree_util.tree_map(put, tree)

    # --------------------------------------------------------------- audit

    def _eval_audit(self, target: str, trace: Optional[list]) -> list[Result]:
        self._in_audit_sweep = True  # arms the pre-materialization
        try:                         # violations cap (when set)
            return self._eval_audit_inner(target, trace)
        finally:
            self._in_audit_sweep = False

    def _eval_audit_inner(self, target: str,
                          trace: Optional[list]) -> list[Result]:
        constraints = self._constraints(target)
        self._audit_used_mesh = False
        # one latency sample per audit, from the FIRST consumed kind:
        # later kinds' dispatch->consume gaps include earlier kinds'
        # host materialization (the pipeline window), which would
        # overstate device latency and bias the cost model to the host
        self._lat_sampled = False
        if not constraints:
            return []
        lookup_ns = self._namespace_lookup(target)
        inventory = self._inventory_tree(target)
        reviews = self._inventory_reviews(target)
        by_kind: dict[str, list[dict]] = {}
        for c in constraints:
            by_kind.setdefault(c.get("kind"), []).append(c)
        results: list[Result] = []
        # review match-signatures shared across kinds AND across audits
        # (valid for the cached review list of this data revision)
        sig_cache = self._audit_sig_cache(target)
        # two-phase across kinds: dispatch EVERY compiled kind's device
        # sweep first (async), then consume+materialize — the chip works
        # through kind k+1's slabs while the host renders kind k's
        # messages, so a 16-template audit costs ~max(Σ device, Σ host)
        by_res: dict[str, list] = {}
        pending: list = []
        # dispatch window: overlap device work across kinds. The big
        # tensors (features) are device-resident via the persistent
        # feature cache whether or not a sweep is in flight; dispatching
        # ahead only adds each kind's packed verdict + gather buffers
        # (hundreds of KB), so the window exists purely as a runaway
        # bound for pathological template counts
        window = 64
        delta_served: set = set()

        # per-kind completion observer (the streaming status writer):
        # fired as soon as a kind's results exist, so constraint-status
        # publishing overlaps the remaining kinds' device sweeps
        cb = self.on_kind_results if trace is None else None

        def emit(k: str) -> None:
            if cb is None:
                return
            try:
                cb(target, k, by_res.get(k, []))
            except Exception as e:  # observer only; never fail the sweep
                log.warning("audit kind-results observer failed for %s: "
                            "%s: %s", k, type(e).__name__, e)

        for kind in sorted(by_kind):
            cons = by_kind[kind]
            ct = self.compiled_for(kind)
            if ct is not None and trace is None and \
                    not self._template_reads_data(kind):
                with profiling.timers().phase("delta_serve"):
                    served = self._audit_delta_serve(target, kind, cons,
                                                     reviews, lookup_ns,
                                                     sig_cache, inventory)
                if served is not None:
                    by_res[kind] = served
                    delta_served.add(kind)
                    self.note_eval(kind, "delta")
                    emit(kind)
                    continue
            if ct is not None and trace is None:
                while len(pending) >= window:
                    k0, st0 = pending.pop(0)
                    by_res[k0] = self._audit_consume(target, k0, st0,
                                                     by_kind[k0], reviews,
                                                     lookup_ns, inventory,
                                                     sig_cache)
                    emit(k0)
                st = self._audit_dispatch(target, kind, ct, cons, reviews,
                                          lookup_ns, sig_cache)
                if st is not None:
                    pending.append((kind, st))
                    continue
                by_res[kind] = self._audit_interp(target, kind, cons,
                                                  reviews, lookup_ns,
                                                  inventory, trace,
                                                  sig_cache)
                emit(kind)
                continue
            if ct is not None:
                by_res[kind] = self._audit_compiled(target, kind, ct, cons,
                                                    reviews, lookup_ns,
                                                    inventory, trace,
                                                    sig_cache)
                emit(kind)
                continue
            jc = self.join_for(kind)
            if jc is not None:
                by_res[kind] = self._audit_join(target, kind, jc, cons,
                                                reviews, lookup_ns,
                                                inventory, trace, sig_cache)
                emit(kind)
                continue
            by_res[kind] = self._audit_interp(target, kind, cons, reviews,
                                              lookup_ns, inventory, trace,
                                              sig_cache)
            emit(kind)
        for kind, st in pending:
            by_res[kind] = self._audit_consume(target, kind, st,
                                               by_kind[kind], reviews,
                                               lookup_ns, inventory,
                                               sig_cache)
            emit(kind)
        if trace is None:
            for kind in sorted(by_kind):
                # seed/refresh the delta cache from this full sweep —
                # only for kinds that stayed compiled (a mid-sweep
                # demotion means the interpreter path, whose templates
                # may read inventory) and are review-pure
                if kind not in delta_served and \
                        self._compiled.get(kind) is not None and \
                        not self._template_reads_data(kind):
                    self._audit_delta_store(target, kind,
                                            by_res.get(kind, []), reviews)
        for kind in sorted(by_kind):
            results.extend(by_res.get(kind, []))
        self.last_audit_path = (
            f"delta({len(delta_served)}/{len(by_kind)})"
            if delta_served else
            f"mesh(data={self._mesh.shape['data']})"
            if self._audit_used_mesh else "single")
        return results

    # audits below this many candidate reviews stay single-device: a
    # mesh dispatch only pays off once per-shard slabs are substantial
    MESH_MIN_REVIEWS = 8192

    # async warm-up serves the host path only while its estimated cost
    # stays under this; beyond it, blocking on the compile once is
    # cheaper than minutes of interpretation
    ASYNC_WARM_MAX_HOST_S = 30.0

    def _mesh_shardable(self, n_reviews: int) -> bool:
        """Mesh path gate: enough rows, and the power-of-two extraction
        bucket divides evenly over the data axis."""
        if self._mesh is None or n_reviews < self.MESH_MIN_REVIEWS:
            return False
        from .features import _bucket

        return _bucket(n_reviews) % self._mesh.shape["data"] == 0

    @staticmethod
    def _sweep_slab(n_true: int, chunk: int = 8192) -> int:
        half = (n_true + 1) // 2
        return max(chunk * 4, ((half + chunk - 1) // chunk) * chunk)

    def _sweep_sig(self, kind, feats, enc, table, derived, n_true,
                   use_mesh) -> tuple:
        """Shape signature of one sweep's jit cache keys: a device
        program is "warm" once these exact shapes executed. The slab
        (derived from n_true) is a STATIC jit key on the single-device
        path — two sweeps in the same feature bucket but different
        slabs compile different programs."""
        def shapes(tree):
            out = []
            if isinstance(tree, dict):
                for k in sorted(tree):
                    out.append((k, shapes(tree[k])))
                return tuple(out)
            return tuple(getattr(tree, "shape", ()))
        slab = 0 if use_mesh else self._sweep_slab(n_true)
        return (kind, use_mesh, slab, shapes(feats), shapes(enc),
                tuple(getattr(table, "shape", ())), shapes(derived))

    def _unadopt(self, sig) -> None:
        """A warm-boot-adopted sweep signature turned out not to be
        backed by a deserializable executable: forget the adoption so
        the normal cold-sig machinery (background warm + host fallback,
        or block-when-cheaper) takes over."""
        with self._warm_lock:
            self._warm_done.discard(sig)
            self._warm_restored.discard(sig)

    def _dispatch_guarded(self, sig, ct, feats, enc, table, derived,
                          n_true, use_mesh, n_cons):
        """_dispatch_handle, but when `sig` was adopted from the AOT
        store (never executed in THIS process) the dispatch runs under
        the no-inline-compile guard: a store miss returns None (caller
        re-gates the sig as cold) instead of stalling the serving
        thread on XLA. Small-N lazy paths that defer their jit call to
        consume time are outside the guard — they are below the device
        cost threshold in practice and bounded to one chunk."""
        from . import aot as aot_mod

        with self._warm_lock:
            restored = sig in self._warm_restored
        if not restored:
            return self._dispatch_handle(ct, feats, enc, table, derived,
                                         n_true, use_mesh, n_cons=n_cons)
        try:
            with aot_mod.no_inline_compile():
                h = self._dispatch_handle(ct, feats, enc, table,
                                          derived, n_true, use_mesh,
                                          n_cons=n_cons)
        except aot_mod.WouldCompile:
            log.info("adopted sweep signature for %s not backed by a "
                     "stored executable after all; re-warming it off "
                     "the serving path", self._sig_kind(sig))
            self._unadopt(sig)
            return None
        with self._warm_lock:
            self._warm_restored.discard(sig)
        return h

    def _dispatch_handle(self, ct, feats, enc, table, derived, n_true,
                         use_mesh, chunk=None, n_cons=None):
        chunk = chunk or self.sweep_chunk
        if use_mesh:
            return ct.fires_pairs_mesh_dispatch(
                feats, enc, table, self._mesh, derived, chunk=chunk,
                n_true=n_true, slab=self.mesh_slab_local, n_cons=n_cons)
        return ct.fires_pairs_dispatch(feats, enc, table, derived,
                                       chunk=chunk,
                                       slab=self._sweep_slab(n_true, chunk),
                                       n_true=n_true, n_cons=n_cons)

    def _spawn_warm(self, sig, kind, run_fn, fingerprint=None,
                    what=""):
        """Run one cold device program (`run_fn`: a full sweep/batch
        evaluation thunk) in the background so its jit caches populate
        off the serving path; results are discarded (the foreground
        already answered from the host path this round). On success the
        sweep signature is persisted to the AOT store, so a future warm
        boot marks this shape warm BEFORE its first sweep. Returns the
        completion Event (callers whose host alternative is worse than
        the compile may choose to wait on it)."""
        with self._warm_lock:
            ev = self._warm_inflight.get(sig)
            if ev is not None or sig in self._warm_done:
                return ev
            ev = threading.Event()
            self._warm_inflight[sig] = ev

        def run():
            import time as _time

            t0 = _time.monotonic()
            try:
                with self._warm_sem:
                    run_fn()
                with self._warm_lock:
                    self._warm_done.add(sig)
                if fingerprint:
                    self.aot.record_sig(fingerprint, sig)
                log.info("device program for %s warm after %.1fs "
                         "(%s); next audit hot-swaps off the host "
                         "path", kind, _time.monotonic() - t0,
                         what or "sweep")
            except Exception as e:
                # do NOT demote from here: the warm sweep runs
                # concurrently with foreground device work, so a
                # transient resource failure may be contention the
                # serving path would never see. Retry once; after that,
                # mark warm so the FOREGROUND dispatch surfaces the
                # real error through its own demotion path.
                with self._warm_lock:
                    n_fail = self._warm_fail.get(sig, 0) + 1
                    self._warm_fail[sig] = n_fail
                    if n_fail >= 2:
                        self._warm_done.add(sig)
                log.warning(
                    "background warm sweep for %s failed (attempt %d)"
                    "%s: %s: %s", kind, n_fail,
                    "; next audit dispatches in the foreground"
                    if n_fail >= 2 else "; will retry",
                    type(e).__name__, e)
            finally:
                with self._warm_lock:
                    self._warm_inflight.pop(sig, None)
                ev.set()

        threading.Thread(target=run, daemon=True,
                         name=f"warm-{kind}").start()
        return ev

    def warm_status(self) -> dict:
        """Observability: how many device programs are warm/in-flight
        (bench.py reports it alongside which path served), plus the AOT
        program store's acquisition stats (aot/cache/fresh counts and
        seconds)."""
        with self._warm_lock:
            out = {"warm": len(self._warm_done),
                   "compiling": len(self._warm_inflight)}
        out["aot"] = self.aot.stats_snapshot()
        return out

    def _audit_dispatch(self, target, kind, ct, cons, reviews, lookup_ns,
                        sig_cache):
        """Phase 1 for one compiled kind: mask, feature prep, and ASYNC
        device dispatch of every slab — SPMD over the mesh's data axis
        when one is available and the sweep is large enough, else the
        single-device slab pipeline. A sweep shape that has never
        executed serves from the host path while a background thread
        warms the device program (XLA compile must not stall the
        audit). Returns consume state, or None for the host path."""
        try:
            faults.fire("eval.device", kind=kind)
            mask = self._match_mask(target, kind, cons, reviews, lookup_ns,
                                    sig_cache)
            cand = np.flatnonzero(mask.any(axis=1))
            if cand.size == 0:
                return ("empty",)
            # same cost model as the webhook: a small audit's masked
            # pairs clear the host codegen path faster than one device
            # roundtrip (~0.1s over a tunnel) — stay on host WITHOUT
            # demoting (the template remains compiled for big sweeps)
            if not self._use_device_for_batch(int(mask.sum())):
                return None
            cand_reviews = [reviews[int(i)] for i in cand]
            use_mesh = self._mesh_shardable(len(cand_reviews))
            feat_key = (self._data_gen, hash(cand.tobytes()))
            with profiling.timers().phase("encode"):
                feats, enc, table, derived = self._prepare_eval(
                    ct, kind, cand_reviews, cons, feat_key, cand=cand,
                    target=target, mesh=use_mesh)
            c_dev = _param_c(enc)
            n_cons = len(cons)
            sig = self._sweep_sig(kind, feats, enc, table, derived,
                                  len(cand_reviews), use_mesh)
            def warm_run():
                h = self._dispatch_handle(ct, feats, enc, table,
                                          derived, len(cand_reviews),
                                          use_mesh, n_cons=n_cons)
                for _ in h.pairs():
                    pass
            if self.async_warm:
                # host fallback only when it is actually cheaper than
                # waiting out the compile: at audit scale (e.g. 50M
                # masked pairs) minutes of interpretation would be far
                # worse than blocking ~10-90s once
                if not self._warm_gate(sig, kind, warm_run,
                                       int(mask.sum()),
                                       fingerprint=ct.fingerprint,
                                       what=f"mesh={use_mesh}"):
                    return None  # host path serves this audit
            import time as _time

            handle = self._dispatch_guarded(sig, ct, feats, enc, table,
                                            derived, len(cand_reviews),
                                            use_mesh, n_cons)
            if handle is None:
                # the adopted signature didn't hold: re-gate it as cold
                # (background warm + host fallback, or block-when-
                # cheaper per the cost model)
                if self.async_warm and not self._warm_gate(
                        sig, kind, warm_run, int(mask.sum()),
                        fingerprint=ct.fingerprint,
                        what=f"mesh={use_mesh}"):
                    return None
                handle = self._dispatch_handle(
                    ct, feats, enc, table, derived, len(cand_reviews),
                    use_mesh, n_cons=n_cons)
            # the program(s) for this shape are compiled/deserialized by
            # now (dispatch traces them): remember the signature so a
            # restarted process marks it warm before its first sweep
            self.aot.record_sig(ct.fingerprint, sig)
            if use_mesh:
                self._audit_used_mesh = True
            self.note_eval(kind, "device")
            return ("h", mask, cand, cand_reviews, handle, c_dev,
                    _time.monotonic())
        except DriverError:
            raise
        except Exception as e:
            self._quarantine_kind(kind, "audit-eval", e)
            return None

    # ------------------------------------------- vectorized materialize

    def _msg_plan(self, kind: str):
        """The kind's message plan (ir/vecmat.py), or None for the exact
        per-pair path. Cached per template generation; metrics count the
        cache so plan-compile churn is visible."""
        if kind in self._msg_plans:
            self._note_msg_cache("hit")
            return self._msg_plans[kind]
        self._note_msg_cache("miss")
        plan = None
        prog = self._programs.get(kind)
        module = self._modules.get(kind)
        if prog is not None and module is not None:
            from .vecmat import plan_messages
            try:
                plan = plan_messages(module, prog)
            except Exception as e:
                # a planner bug must degrade to the exact path, never
                # break materialization
                log.warning("message plan for %s failed (%s: %s); using "
                            "the exact evaluator", kind,
                            type(e).__name__, e)
                plan = None
        self._msg_plans[kind] = plan
        if plan is not None:
            log.info("template %s messages vectorized (%d witnesses)",
                     kind, len(plan.witnesses))
        return plan

    @staticmethod
    def _note_msg_cache(outcome: str) -> None:
        try:
            from ..control.metrics import report_msg_template_cache

            report_msg_template_cache(outcome)
        except Exception:  # metrics backend optional in embedders
            pass

    def _witness_col(self, target: str, w, base_reviews: list):
        """Rendered witness column (+ veto mask) over `base_reviews`,
        cached until the data revision moves or the list is replaced —
        steady-state audits fancy-index resident columns instead of
        re-walking the inventory."""
        key = (target, w)
        base_key = (id(base_reviews), len(base_reviews))
        ent = self._witcols.get(key)
        if ent is not None and ent[0] == self._data_rev and \
                ent[1] == base_key:
            return ent[2], ent[3]
        from .vecmat import build_row_witness

        arr, veto = build_row_witness(base_reviews, w)
        self._witcols[key] = (self._data_rev, base_key, arr, veto)
        return arr, veto

    def _vec_msgs(self, target, kind, cons, pair_reviews, rows, cols,
                  cand):
        """ir/vecmat.py plan evaluation for one pair batch: fill the
        plan's witnesses for every firing pair via numpy fancy-indexing
        and assemble messages as U-dtype concatenation — no per-pair
        Python. Returns (status[P] int8, msgs[P] list, details) or None
        (exact path). status: 1 = message ready, 0 = vetoed to the
        exact evaluator (absent / non-string / oversize witness), 2 =
        provably no violation (msg witness undefined for that
        constraint's parameters)."""
        plan = self._msg_plan(kind)
        if plan is None:
            return None
        program = self._programs.get(kind)
        if program is None:
            return None
        from . import vecmat

        if plan.conditions and not vecmat.check_conditions(
                program, plan.conditions, cons):
            return None
        rows_np = np.asarray(rows, dtype=np.int64)
        cols_np = np.asarray(cols, dtype=np.int64)
        # witness columns build over the STABLE full review list when
        # the caller maps pair rows through `cand` (identity
        # spot-checked), so steady-state sweeps reuse them; otherwise
        # over this call's pair_reviews
        base = pair_reviews
        base_rows = rows_np
        if cand is not None:
            full = self._inventory_reviews(target)
            cand_arr = np.asarray(cand, dtype=np.int64)
            if len(cand_arr) == len(pair_reviews) and (
                    not len(pair_reviews)
                    or (pair_reviews[0] is full[int(cand_arr[0])]
                        and pair_reviews[-1] is full[int(cand_arr[-1])])):
                base = full
                base_rows = cand_arr[rows_np]
        status = np.ones(len(rows_np), dtype=np.int8)
        parts: list = []
        for w in plan.witnesses:
            if w.kind == "const":
                parts.append(None)  # folded into the literal segments
                continue
            if w.kind == "param":
                strs = []
                dead = np.zeros(len(cons), dtype=bool)
                for k, c in enumerate(cons):
                    spec = c.get("spec")
                    spec = spec if isinstance(spec, dict) else {}
                    p = spec.get("parameters")
                    frz = self._freeze_params(c, p if p is not None
                                              else {})
                    s = vecmat.render_param_witness(w, frz)
                    if s is None:
                        dead[k] = True
                        strs.append("")
                    else:
                        strs.append(s)
                col_arr = (np.array(strs, dtype=str) if strs
                           else np.zeros(0, dtype="U1"))
                parts.append(col_arr[cols_np])
                if dead.any():
                    status[dead[cols_np]] = 2
                continue
            arr, veto = self._witness_col(target, w, base)
            status[veto[base_rows]] = 0
            parts.append(arr[base_rows])
        # assembly: literal segments merge into their neighbors; each
        # np.char.add runs as one C loop over the whole pair batch
        msgs = None
        lit = plan.segments[0]
        for w, part, seg in zip(plan.witnesses, parts,
                                plan.segments[1:]):
            if part is None:  # const witness: pure literal
                lit = lit + w.text + seg
                continue
            if lit:
                part = np.char.add(lit, part)
            lit = seg
            msgs = part if msgs is None else np.char.add(msgs, part)
        if msgs is None:
            msg_list = [lit] * len(rows_np)
        else:
            if lit:
                msgs = np.char.add(msgs, lit)
            msg_list = msgs.tolist()
        return status, msg_list, plan.details

    def _audit_consume(self, target, kind, st, cons, reviews, lookup_ns,
                       inventory, sig_cache):
        """Phase 2: sync the dispatched slabs in order, materialize."""
        if st[0] == "empty":
            return []
        _tag, mask, cand, cand_reviews, handle, c_dev, t_dispatch = st
        import time as _time

        out: list[Result] = []
        first_sync = True
        # two stopwatches through one slab loop: time blocked on the
        # device (generator next) vs host materialization — the audit
        # trace's device_sweep / materialize phases (a context manager
        # per slab would mis-nest across the interleaving)
        t_dev = t_mat = 0.0
        # mesh handles label blocks with their data-shard index: the
        # per-shard materialize histograms ride that, and — since the
        # SLAB loop's blocks are not globally row-major — results are
        # reassembled by each block's first global row (disjoint
        # contiguous ranges per block, sorted within)
        labeled = getattr(handle, "pairs_labeled", None)
        blocks: list = []
        try:
            it = iter(labeled()) if labeled is not None \
                else iter(handle.pairs())
            while True:
                t0 = _time.monotonic()
                try:
                    item = next(it)
                except StopIteration:
                    t_dev += _time.monotonic() - t0
                    break
                t_dev += _time.monotonic() - t0
                shard = None
                if labeled is not None:
                    shard, rows, cols = item
                else:
                    rows, cols = item
                if first_sync:
                    # DISPATCH->first-result latency, sampled only for
                    # the audit's first consumed kind (later kinds'
                    # gaps include earlier kinds' host materialization
                    # under the pipeline window; measuring from consume
                    # time instead understated it — both biases skew
                    # _use_device_for_batch)
                    if not getattr(self, "_lat_sampled", True):
                        self._lat_sampled = True
                        self._observe("_dev_batch_lat_s",
                                      _time.monotonic() - t_dispatch)
                    first_sync = False
                t0 = _time.monotonic()
                rows, cols = _expand_parameterless(rows, cols, c_dev,
                                                   len(cons))
                keep = mask[cand[rows], cols]
                res = self.materialize_pairs(
                    target, cons, cand_reviews, rows[keep], cols[keep],
                    inventory, cand=cand)
                dt = _time.monotonic() - t0
                t_mat += dt
                if shard is None:
                    out.extend(res)
                else:
                    blocks.append((int(rows[0]) if len(rows) else -1,
                                   res))
                    if res or dt > 0.001:
                        from ..control.metrics import report_audit_shard
                        report_audit_shard("materialize", shard, dt)
            if blocks:
                blocks.sort(key=lambda b: b[0])
                for _r0, res in blocks:
                    out.extend(res)
        except DriverError:
            raise
        except Exception as e:
            self._quarantine_kind(kind, "audit-eval", e)
            return self._audit_interp(target, kind, cons, reviews,
                                      lookup_ns, inventory, None, sig_cache)
        finally:
            timers = profiling.timers()
            if t_dev > 0:
                timers.add("device_sweep", t_dev)
            if t_mat > 0:
                timers.add("materialize", t_mat)
            self.note_busy(t_dev + t_mat)
        if self._quarantine:
            self._quarantine_clear(kind)
        return out

    def _audit_join(self, target, kind, jc, cons, reviews, lookup_ns,
                    inventory, trace, sig_cache=None) -> list[Result]:
        """Audit one inventory-join kind: exact aggregated-key join on
        device/host (ir/join.py) selects firing reviews; materialization
        re-checks and renders each firing pair exactly."""
        from ..utils.values import freeze

        mask = self._match_mask(target, kind, cons, reviews, lookup_ns,
                                sig_cache)
        cand = np.flatnonzero(mask.any(axis=1))
        if cand.size == 0:
            return []
        self.note_eval(kind, "join")
        _t_join0 = time.monotonic()
        cand_reviews = [reviews[int(i)] for i in cand]
        if self._join_frz[0] != self._data_rev:
            self._join_frz = (self._data_rev, {}, {})
        rev_cache = self._join_frz[1]
        key_cache = self._join_frz[2].setdefault(kind, {})
        frz = []
        for r in cand_reviews:
            ent = rev_cache.get(id(r))
            if ent is None or ent[0] is not r:
                ent = (r, freeze(r))
                rev_cache[id(r)] = ent
            frz.append(ent[1])
        try:
            try:
                fires = jc.fires(frz, self._inventory_tree(target),
                                 self._data_gen, key_cache=key_cache)
            finally:
                # monotonic + finally: an NTP step must not inflate the
                # duty cycle, and a failed eval still burned wall clock
                self.note_busy(time.monotonic() - _t_join0)
        except Exception as e:
            # transient-capable quarantine, not a permanent demotion —
            # join templates heal the same way compiled ones do
            self._join_compiled.pop(kind, None)
            self._quarantine_kind(kind, "join-eval", e)
            return self._audit_interp(target, kind, cons, reviews,
                                      lookup_ns, inventory, trace,
                                      sig_cache)
        if self._quarantine:
            self._quarantine_clear(kind)
        hit = np.flatnonzero(fires)
        if hit.size == 0:
            return []
        # join programs are parameter-independent: expand each firing
        # review to every constraint its match allows
        sub = mask[cand[hit]]
        rows_rep, cols = np.nonzero(sub)
        rows = hit[rows_rep]
        if trace is None:
            return self.materialize_pairs(target, cons, cand_reviews,
                                          rows, cols, inventory,
                                          cand=cand)
        out: list[Result] = []
        for ri, ci in zip(rows, cols):
            constraint = cons[int(ci)]
            spec = constraint.get("spec")
            spec = spec if isinstance(spec, dict) else {}
            out.extend(self._eval_template_violations(
                target, constraint, cand_reviews[int(ri)],
                spec.get("enforcementAction") or "deny", inventory, trace))
        return out

    # ------------------------------------------------- audit results delta

    def _template_reads_data(self, kind: str) -> bool:
        """Conservative taint check: does the (merged) template module
        reference `data` anywhere — e.g. a head-only binding reading
        data.inventory that the compiler skipped? Such a template's
        MESSAGES can change when other objects change, so its audit
        results must not be delta-served. Cached per compiled module."""
        tainted = self._data_taint.get(kind)
        if tainted is not None:
            return tainted
        module = self._modules.get(kind)

        def walk(t) -> bool:
            if isinstance(t, A.Var):
                return t.name == "data"
            if isinstance(t, (list, tuple)):
                return any(walk(x) for x in t)
            if hasattr(t, "__dataclass_fields__"):
                return any(walk(getattr(t, f))
                           for f in t.__dataclass_fields__)
            return False

        tainted = module is None or any(
            walk(r.key) or walk(r.value) or walk(r.args) or walk(r.body)
            for r in module.rules)
        self._data_taint[kind] = tainted
        return tainted

    def _review_index(self, reviews) -> dict:
        """id(review) -> global index map for the current review list,
        cached per (list identity, data revision) — rebuilding it costs
        one O(N) pass per sweep only when something changed."""
        ent = self._review_idx_cache
        if ent[0] is reviews and ent[1] == self._data_rev:
            return ent[2]
        idx = {id(rv): i for i, rv in enumerate(reviews)}
        self._review_idx_cache = (reviews, self._data_rev, idx)
        return idx

    def _audit_delta_serve(self, target, kind, cons, reviews, lookup_ns,
                           sig_cache, inventory):
        """Serve one kind's audit results from the delta cache: valid
        when constraints are unchanged, the review list is the same
        (patched-in-place) object, and the patch journal covers every
        write since the cached sweep. Only journal-dirty rows re-
        evaluate (on the host — the dirty set is orders of magnitude
        below the device-dispatch crossover); everything else, including
        the materialization tail, is reused. Returns the ordered result
        list or None when a full sweep is required."""
        ent = self._audit_results_cache.get((target, kind))
        if ent is None or ent["con_gen"] != self._constraint_gen or \
                ent["reviews"] is not reviews:
            return None
        if ent.get("capped") and self.audit_violations_cap is None:
            # cached results carry count-only (capped) messages from a
            # manager sweep: an UNCAPPED caller must re-materialize
            return None
        notes = self._notes_between(ent["rev"], self._data_rev)
        if notes is None:
            return None
        dirty: dict[int, dict] = {}
        for n in notes:
            if n[2] == target:
                dirty[n[3]] = n[5]
        by_row = ent["by_row"]
        if dirty:
            mask = self._match_mask(target, kind, cons, reviews, lookup_ns,
                                    sig_cache)
            for r_idx in sorted(dirty):
                review = reviews[r_idx]
                out: list[Result] = []
                for ci in np.flatnonzero(mask[r_idx]):
                    constraint = cons[int(ci)]
                    spec = constraint.get("spec")
                    spec = spec if isinstance(spec, dict) else {}
                    out.extend(self._eval_template_violations(
                        target, constraint, review,
                        spec.get("enforcementAction") or "deny",
                        inventory, None))
                if out:
                    by_row[r_idx] = out
                else:
                    by_row.pop(r_idx, None)
        ent["rev"] = self._data_rev
        flat: list[Result] = []
        for r_idx in sorted(by_row):
            flat.extend(by_row[r_idx])
        return flat

    def _audit_delta_store(self, target, kind, results, reviews) -> None:
        """Populate the delta cache from a full sweep's per-kind results
        (already row-major: grouping by the review object each Result
        carries preserves the exact order a delta-served sweep emits)."""
        idx = self._review_index(reviews)
        by_row: dict[int, list] = {}
        for res in results:
            i = idx.get(id(res.review))
            if i is None:
                return  # foreign review object: do not cache
            by_row.setdefault(i, []).append(res)
        self._audit_results_cache[(target, kind)] = {
            "con_gen": self._constraint_gen, "reviews": reviews,
            "rev": self._data_rev, "by_row": by_row,
            # capped sweeps cache count-only messages past the per-
            # constraint limit; delta-serve refuses them to uncapped
            # callers (the reverse — full messages to a capped sweep —
            # is a superset and serves fine)
            "capped": self.audit_violations_cap is not None}

    def _match_mask(self, target, kind, cons, reviews, lookup_ns,
                    sig_cache):
        key = (self._data_rev, self._constraint_gen)
        ent = self._mask_cache.get((target, kind))
        if ent is not None and ent[0] == key and ent[1] is reviews:
            return ent[2]
        if ent is not None and ent[1] is reviews and \
                ent[0][1] == self._constraint_gen:
            # replay object replacements onto the cached mask: all dirty
            # rows (last write wins) re-matched in ONE batched call
            notes = self._notes_between(ent[0][0], self._data_rev)
            if notes is not None:
                mask = ent[2]
                dirty: dict[int, dict] = {}
                for n in notes:
                    if n[2] == target:
                        dirty[n[3]] = n[5]
                if dirty:
                    idxs = sorted(dirty)
                    sub = match_masks(cons, [dirty[i] for i in idxs],
                                      lookup_ns, sig_cache)
                    mask[np.asarray(idxs)] = sub
                self._mask_cache[(target, kind)] = (key, reviews, mask)
                return mask
        mask = match_masks(cons, reviews, lookup_ns, sig_cache)
        self._mask_cache[(target, kind)] = (key, reviews, mask)
        return mask

    def _audit_interp(self, target, kind, cons, reviews, lookup_ns,
                      inventory, trace, sig_cache=None) -> list[Result]:
        import time as _time

        self.note_eval(kind, "interp")
        out: list[Result] = []
        mask = self._match_mask(target, kind, cons, reviews, lookup_ns,
                                sig_cache)
        n_masked = 0
        t0 = _time.monotonic()
        for r, review in enumerate(reviews):
            for c, constraint in enumerate(cons):
                if not mask[r, c]:
                    continue
                n_masked += 1
                spec = constraint.get("spec")
                spec = spec if isinstance(spec, dict) else {}
                enforcement = spec.get("enforcementAction") or "deny"
                out.extend(self._eval_template_violations(
                    target, constraint, review, enforcement, inventory, trace))
        # feed the cost model in its own units (masked pairs per second)
        el = _time.monotonic() - t0
        if el > 0:
            profiling.timers().add("interp_eval", el)
            self.note_busy(el)
        if trace is None and el > 0.005 and n_masked >= 256:
            self._observe("_host_pair_rate", n_masked / el)
        return out

    def _audit_compiled(self, target, kind, ct: CompiledTemplate, cons,
                        reviews, lookup_ns, inventory, trace,
                        sig_cache=None) -> list[Result]:
        mask = self._match_mask(target, kind, cons, reviews, lookup_ns,
                                sig_cache)
        cand = np.flatnonzero(mask.any(axis=1))
        if cand.size == 0:
            return []
        cand_reviews = [reviews[int(i)] for i in cand]
        # key pins the exact candidate set; constraint churn that does not
        # change membership keeps the (expensive) extraction cached
        feat_key = (self._data_gen, hash(cand.tobytes()))
        # trace-None audits route through _audit_dispatch/_audit_consume
        # (the cross-kind pipeline); this method serves the traced path
        try:
            rows, cols = self.eval_compiled_pairs(ct, kind, cand_reviews,
                                                  cons, feat_key=feat_key,
                                                  cand=cand, target=target)
        except Exception as e:
            # eval-time failures (shapes/ops outside the evaluator's
            # envelope) quarantine the template's device program
            self._quarantine_kind(kind, "audit-eval", e)
            return self._audit_interp(target, kind, cons, reviews,
                                      lookup_ns, inventory, trace, sig_cache)
        if self._quarantine:
            self._quarantine_clear(kind)
        keep = mask[cand[rows], cols]
        out = []
        for ri, ci in zip(rows[keep], cols[keep]):
            review = cand_reviews[int(ri)]
            constraint = cons[int(ci)]
            spec = constraint.get("spec")
            spec = spec if isinstance(spec, dict) else {}
            enforcement = spec.get("enforcementAction") or "deny"
            out.extend(self._eval_template_violations(
                target, constraint, review, enforcement, inventory, trace))
        return out

    # ------------------------------------------------------- compiled eval

    def eval_compiled(self, ct: CompiledTemplate, kind: str,
                      reviews: list[dict], cons: list[dict],
                      feat_key=None) -> np.ndarray:
        """fires[len(reviews), len(cons)] via the device program.
        feat_key, when given, caches extraction until inventory changes."""
        faults.fire("eval.device", kind=kind)
        feats, enc, table, derived = self._prepare_eval(ct, kind, reviews,
                                                        cons, feat_key)
        # chunked: keeps [N, axes..., C] intermediates bounded on large
        # audits; falls through to a single dispatch for small batches
        fires = ct.fires_chunked(feats, enc, table, derived,
                                 n_cons=len(cons))
        return fires[: len(reviews)]

    def _eval_compiled_gated(self, ct: CompiledTemplate, kind: str,
                             reviews: list[dict],
                             cons: list[dict]) -> np.ndarray:
        """eval_compiled with the off-path compile gate: a dense batch
        shape whose device program has never executed serves from the
        host THIS round (raises _ServeHostThisRound) while a background
        thread warms it — an admission request must never block on an
        XLA compile, however small."""
        faults.fire("eval.device", kind=kind)
        feats, enc, table, derived = self._prepare_eval(ct, kind, reviews,
                                                        cons, None)
        if self.async_warm:
            sig = ("dense",) + self._sweep_sig(
                kind, feats, enc, table, derived, len(reviews), False)

            def warm_run():
                ct.fires_chunked(feats, enc, table, derived,
                                 n_cons=len(cons))
            with self._warm_lock:
                warm = sig in self._warm_done
                restored = sig in self._warm_restored
            if not warm:
                self._spawn_warm(sig, kind, warm_run,
                                 fingerprint=ct.fingerprint,
                                 what="dense batch")
                raise _ServeHostThisRound()
            if restored:
                # adopted from the AOT store, never executed here: run
                # no-inline-compile guarded — if the backing executable
                # is missing after all, serve host and warm off-path
                # rather than stall this admission batch on XLA
                from . import aot as aot_mod
                try:
                    with aot_mod.no_inline_compile():
                        fires = ct.fires_chunked(feats, enc, table,
                                                 derived,
                                                 n_cons=len(cons))
                except aot_mod.WouldCompile:
                    self._unadopt(sig)
                    self._spawn_warm(sig, kind, warm_run,
                                     fingerprint=ct.fingerprint,
                                     what="dense batch")
                    raise _ServeHostThisRound()
                with self._warm_lock:
                    self._warm_restored.discard(sig)
                return fires[: len(reviews)]
        fires = ct.fires_chunked(feats, enc, table, derived,
                                 n_cons=len(cons))
        return fires[: len(reviews)]

    def eval_compiled_pairs(self, ct: CompiledTemplate, kind: str,
                            reviews: list[dict], cons: list[dict],
                            feat_key=None, cand=None,
                            target=None) -> tuple:
        """(rows, cols) firing pairs, row-major — the sparse form of
        eval_compiled (audits are ~99% rejects; see fires_pairs)."""
        feats, enc, table, derived = self._prepare_eval(ct, kind, reviews,
                                                        cons, feat_key,
                                                        cand=cand,
                                                        target=target)
        rows, cols = ct.fires_pairs(feats, enc, table, derived,
                                    n_true=len(reviews),
                                    n_cons=len(cons))
        return _expand_parameterless(rows, cols, _param_c(enc), len(cons))

    def eval_compiled_pairs_slabbed(self, ct: CompiledTemplate, kind: str,
                                    reviews: list[dict], cons: list[dict],
                                    feat_key=None, cand=None, target=None):
        """Iterator form of eval_compiled_pairs over N-axis slabs, with
        every slab's device work dispatched before the first yield (see
        CompiledTemplate.fires_pairs_slabbed) — the audit's
        sweep/materialize pipeline."""
        feats, enc, table, derived = self._prepare_eval(ct, kind, reviews,
                                                        cons, feat_key,
                                                        cand=cand,
                                                        target=target)
        c_dev = _param_c(enc)
        # two slabs: the second sweep overlaps the first slab's host
        # materialization. More slabs lose to the per-fetch roundtrip on
        # a network-tunneled chip (~0.1s each)
        chunk = 8192
        half = (len(reviews) + 1) // 2
        slab = max(chunk * 4, ((half + chunk - 1) // chunk) * chunk)
        for rows, cols in ct.fires_pairs_slabbed(feats, enc, table, derived,
                                                 chunk=chunk, slab=slab,
                                                 n_true=len(reviews),
                                                 n_cons=len(cons)):
            yield _expand_parameterless(rows, cols, c_dev, len(cons))

    def _prepare_eval(self, ct: CompiledTemplate, kind: str,
                      reviews: list[dict], cons: list[dict], feat_key,
                      cand=None, target=None, mesh: bool = False):
        params_key = (self._constraint_gen,
                      tuple((c.get("metadata") or {}).get("name", "")
                            for c in cons))
        kind_cache = self._param_cache.setdefault(kind, {})
        enc = kind_cache.get(params_key)
        if enc is None:
            param_dicts = []
            for c in cons:
                spec = c.get("spec")
                spec = spec if isinstance(spec, dict) else {}
                p = spec.get("parameters")
                param_dicts.append(p if p is not None else {})
            enc = encode_params(ct.program, param_dicts, self.strtab,
                                self.match_tables)
            if self.cbucket:
                # C-axis bucketing: pad the constraint dim to its
                # power-of-two bucket so a within-bucket library edit
                # re-hits every cached/AOT device program (consumers
                # slice back to the true C via n_cons)
                enc = _pad_cbucket(enc, len(cons))
            kind_cache.clear()
            kind_cache[params_key] = enc
        feats = None
        if feat_key is not None:
            fcache = self._feat_cache.setdefault(kind, {})
            meta = fcache.get("__meta__")
            if meta is not None and meta["key"] == feat_key:
                feats = meta["feats"]
            elif meta is not None and cand is not None and \
                    target is not None:
                # replay single-object replacements onto the cached
                # tensors: patch the changed rows (host + device)
                # instead of re-extracting and re-uploading everything
                feats = self._patch_feats(ct, meta, cand, target)
                if feats is not None:
                    meta["key"] = feat_key
                    meta["rev"] = self._data_rev
            elif meta is None:
                # warm restart: adopt snapshotted rows when the
                # candidate set still matches (statestore restore path)
                feats = self._adopt_restored_rows(kind, cand, feat_key,
                                                  fcache)
        if feats is None:
            feats, buckets, n_pad = extract_batch(ct.program, self.strtab,
                                                  reviews)
            if feat_key is not None:
                fcache.clear()
                fcache["__meta__"] = {
                    "key": feat_key, "feats": feats,
                    "cand": None if cand is None else np.asarray(cand),
                    "buckets": buckets, "n_pad": n_pad,
                    "rev": self._data_rev,
                }
        derived = self._derived_arrays(kind, ct)
        table = self.match_tables.materialize_packed()
        if mesh:
            # SPMD sweep: features split over the data axis, everything
            # else replicated across the mesh, all kept resident
            if feat_key is not None:
                feats = self._dev_mesh(feats, data_leading=True)
            return (feats, self._dev_mesh(enc, False),
                    self._dev_mesh(table, False),
                    self._dev_mesh(derived, False))
        if feat_key is not None:
            # steady-state audit: keep the cached tensors device-resident.
            # One-shot feats (webhook micro-batches) stay host-side — the
            # identity cache would grow one dead entry per request.
            feats = self._dev(feats)
        return feats, self._dev(enc), self._dev(table), self._dev(derived)

    def _patch_feats(self, ct: CompiledTemplate, meta: dict, cand,
                     target: str):
        """Apply journaled object replacements to the cached feature
        tensors as ONE batched patch: the dirty rows (last write wins
        per row) are re-extracted together with the ORIGINAL buckets —
        overflow falls back to a full rebuild, since _fill truncates
        silently — and scattered into the host arrays and any device-
        resident copies in a single dispatch per leaf. A 1%-churn sweep
        over 50k objects patches ~500 rows; the per-row loop this
        replaces paid one device round-trip per (row, leaf). Returns the
        patched tensors or None when a rebuild is required."""
        if meta["cand"] is None or not np.array_equal(meta["cand"], cand):
            return None
        notes = self._notes_between(meta["rev"], self._data_rev)
        if notes is None:
            return None
        # dirty row positions, deduped keeping the LATEST replacement
        by_pos: dict[int, dict] = {}
        for n in notes:
            if n[2] != target:
                continue
            i, new = n[3], n[5]
            pos = int(np.searchsorted(cand, i))
            if not (pos < len(cand) and int(cand[pos]) == i):
                continue  # never a candidate: no feature row
            by_pos[pos] = new
        feats = meta["feats"]
        if not by_pos:
            return feats
        from .features import Extractor, _bucket

        ex = Extractor(ct.program, self.strtab)
        buckets = meta["buckets"]
        positions = sorted(by_pos)
        dirty = [by_pos[p] for p in positions]
        sizes = ex.axis_sizes(dirty)
        if any(sizes.get(a, 0) > buckets.get(a, 0) for a in sizes):
            return None  # outgrew a bucket: rebuild
        m = len(dirty)
        rows = ex.extract(dirty, _bucket(m), buckets)
        pos_arr = np.asarray(positions, dtype=np.int32)
        for slot, arrs in rows.items():
            dst = feats[slot]
            for nm, a in arrs.items():
                dst[nm][pos_arr] = a[:m]
                self._dev_patch_rows(dst[nm], pos_arr, a[:m])
        return feats

    def _dev_patch_rows(self, arr, pos: np.ndarray, rows) -> None:
        """Refresh device-resident leaves after an in-place host patch:
        transfer only the dirty ROWS and scatter them into each resident
        buffer — the single-device copy and any mesh-sharded copy (a
        full re-upload costs seconds on a tunneled chip) — in one
        dispatch per buffer. The row count pads to its power-of-two
        bucket (repeating the last row, so duplicate scatter indices
        carry identical values) to keep the scatter jit shape-stable
        under varying dirty-set sizes. The sharded result is pinned back
        to the original sharding so steady-state mesh sweeps keep
        dispatching over resident buffers."""
        m = len(pos)
        if m == 0:
            return
        ent = self._dev_cache.get(id(arr))
        ment = self._dev_mesh_cache.get((id(arr), True))
        hit = ent is not None and ent[0]() is arr
        mhit = ment is not None and ment[0]() is arr
        if not hit and not mhit:
            return  # no resident copies to refresh
        import jax

        from .features import _bucket

        mp = _bucket(m)
        if mp != m:
            pad = mp - m
            pos = np.concatenate([pos, np.full(pad, pos[-1],
                                               dtype=pos.dtype)])
            rows = np.concatenate(
                [rows, np.broadcast_to(rows[m - 1:m],
                                       (pad,) + rows.shape[1:])])
        fns = getattr(self, "_rows_update_fns", None)
        if fns is None:
            from .aot import AotJit

            def upd(d, r, p):
                return d.at[p].set(r)
            # rides the AOT store like every other ir/ program (the
            # fingerprint is a constant: the program text is fixed, so
            # identity is its version tag + the arg signature). One
            # wrapper PER LAYOUT: arg_sig ignores sharding, so the
            # single-device and mesh-sharded resident copies — same
            # shapes — would otherwise collide on one executable key
            # and permanently bounce the loser to the plain jit.
            fns = self._rows_update_fns = tuple(
                AotJit(upd, store=self.aot,
                       fingerprint="rows-update-v1",
                       tag="rows_update", static=(layout,),
                       kind="__rows_update__")
                for layout in ("single", "mesh"))
        if hit:
            self._dev_cache[id(arr)] = (ent[0],
                                        fns[0](ent[1], rows, pos))
        if mhit:
            d = fns[1](ment[1], rows, pos)
            if d.sharding != ment[1].sharding:
                d = jax.device_put(d, ment[1].sharding)
            self._dev_mesh_cache[(id(arr), True)] = (ment[0], d)

    def _derived_arrays(self, kind: str, ct: CompiledTemplate) -> dict:
        """Program-local derived columns, extended to the current vocab.
        Must run after extraction/encoding interned this batch's strings
        (same ordering contract as materialize_packed). Arrays are padded
        to the vocab capacity bucket so their shapes stay stable under
        vocab growth (see ops.strtab.vocab_cap)."""
        cols = self._derived_cols.get(kind) or []
        if not cols:
            return {}
        global_arrays = self.derived_tables.materialize(cols)
        return {spec.col: {nm: self._pad_vocab(a)
                           for nm, a in global_arrays[g].items()}
                for spec, g in zip(ct.program.derived, cols)}

    def _pad_vocab(self, arr):
        """Pad a vocab-indexed array to the capacity bucket (cached by
        source identity so steady-state audits reuse one padded copy and
        its device buffer). Float pads are NaN (no number), others 0
        (pad sid / K_ABSENT)."""
        from ..ops.strtab import vocab_cap

        cap = vocab_cap(len(self.strtab))
        if arr.shape[0] >= cap:
            return arr
        import weakref

        ent = self._vpad_cache.get(id(arr))
        if ent is not None and ent[0]() is arr and \
                ent[1].shape[0] == cap:
            return ent[1]
        pad = np.zeros((cap,) + arr.shape[1:], dtype=arr.dtype)
        if arr.dtype.kind == "f":
            pad[:] = np.nan
        pad[: arr.shape[0]] = arr
        try:
            ref = weakref.ref(arr,
                              lambda _r, k=id(arr):
                              self._vpad_cache.pop(k, None))
        except TypeError:
            return pad
        self._vpad_cache[id(arr)] = (ref, pad)
        return pad

    # ----------------------------------------------------- batched reviews

    # batches below this size never pay a device dispatch
    MIN_DEVICE_BATCH = 4

    # below this estimated host cost, a device dispatch can only add tail
    # latency (a probe may even carry a fresh XLA compile)
    PROBE_FLOOR_S = 0.05

    def _use_device_for_batch(self, n_masked_pairs: int) -> bool:
        """Cost-based dispatch: a device sweep has a fixed per-call
        latency (milliseconds on local chips, ~100ms over a network
        tunnel) while the host codegen path costs per evaluated pair.
        Both are measured as EMAs at runtime, so the crossover adapts to
        wherever the chip actually is. Probing (the first device sample,
        and the periodic re-probe that keeps a skewed EMA from shunning
        the device forever) happens ONLY on batches the host would take
        >= PROBE_FLOOR_S to clear — a probe can carry a one-off jit
        compile, which must never land in a latency-bound micro-batch."""
        host_est = n_masked_pairs / self._host_pair_rate
        if self._dev_batch_lat_s is not None and \
                self._dev_batch_lat_s < host_est:
            self._dev_skips = 0
            return True
        if host_est < self.PROBE_FLOOR_S:
            return False
        if self._dev_batch_lat_s is None:
            return True  # measure the device once, then decide from data
        self._dev_skips += 1
        if self._dev_skips >= 256:
            self._dev_skips = 0
            return True
        return False

    # review batches at or above this candidate count use the sparse
    # firing-pair gather (and the mesh when shardable) instead of the
    # dense verdict tensor — the discovery-mode audit stages the whole
    # cluster through review_batch, the same scale as cached audits
    SPARSE_BATCH_MIN = 4096

    def _warm_gate(self, sig, kind, run_fn, n_masked_pairs,
                   fingerprint=None, what="") -> bool:
        """Shared block-when-cheaper policy for a cold sweep shape:
        kick the background warm and return False (serve host) when the
        host alternative is tolerable, else wait the compile out and
        return whether the program is now warm. True = dispatch on the
        device."""
        with self._warm_lock:
            if sig in self._warm_done:
                return True
        ev = self._spawn_warm(sig, kind, run_fn, fingerprint=fingerprint,
                              what=what)
        if n_masked_pairs / self._host_pair_rate <= \
                self.ASYNC_WARM_MAX_HOST_S:
            return False
        if ev is not None:
            ev.wait(timeout=600)
        with self._warm_lock:
            return sig in self._warm_done

    def _review_batch_sparse(self, ct, kind, cand, cand_reviews, cons,
                             mask) -> list:
        """(review_index, constraint_index) firing pairs for one kind of
        a large batch, via the audit dispatch machinery (sparse gather,
        mesh sharding, async warm-up with the same block-when-cheaper
        rule)."""
        import time as _time

        faults.fire("eval.device", kind=kind)
        use_mesh = self._mesh_shardable(len(cand_reviews))
        feats, enc, table, derived = self._prepare_eval(
            ct, kind, cand_reviews, cons, feat_key=None, mesh=use_mesh)
        n_cons = len(cons)
        sig = self._sweep_sig(kind, feats, enc, table, derived,
                              len(cand_reviews), use_mesh)
        def warm_run():
            h = self._dispatch_handle(ct, feats, enc, table, derived,
                                      len(cand_reviews), use_mesh,
                                      n_cons=n_cons)
            for _ in h.pairs():
                pass
        if self.async_warm:
            if not self._warm_gate(sig, kind, warm_run, int(mask.sum()),
                                   fingerprint=ct.fingerprint,
                                   what=f"batch mesh={use_mesh}"):
                raise _ServeHostThisRound()
        # latency EMA measured from DISPATCH (post-warm): folding a
        # compile wait into it would steer batches to the host for ages
        t0 = _time.monotonic()
        handle = self._dispatch_guarded(sig, ct, feats, enc, table,
                                        derived, len(cand_reviews),
                                        use_mesh, n_cons)
        if handle is None:
            # the adopted signature didn't hold: this is a cold shape —
            # serve host while it warms in the background (an admission
            # batch must never block on XLA)
            if self.async_warm:
                self._spawn_warm(sig, kind, warm_run,
                                 fingerprint=ct.fingerprint,
                                 what=f"batch mesh={use_mesh}")
                raise _ServeHostThisRound()
            handle = self._dispatch_handle(ct, feats, enc, table,
                                           derived, len(cand_reviews),
                                           use_mesh, n_cons=n_cons)
        self.aot.record_sig(ct.fingerprint, sig)
        c_dev = _param_c(enc)
        pairs = []
        first = True
        for rows, cols in handle.pairs():
            if first:
                self._observe("_dev_batch_lat_s", _time.monotonic() - t0)
                first = False
            rows, cols = _expand_parameterless(rows, cols, c_dev,
                                               len(cons))
            keep = mask[cand[rows], cols]
            pairs.extend(zip((int(x) for x in cand[rows[keep]]),
                             (int(x) for x in cols[keep])))
        if use_mesh:
            # only after the sweep actually completed on the mesh — a
            # warm-gate bailout or demotion must not report a mesh path
            self._batch_used_mesh = True
        return pairs

    def _observe(self, attr: str, value: float, alpha: float = 0.3) -> None:
        prev = getattr(self, attr)
        setattr(self, attr, value if prev is None
                else prev + alpha * (value - prev))

    def review_batch(self, target: str, reviews: list[dict]
                     ) -> list[list[Result]]:
        """Evaluate many admission reviews at once (the webhook
        micro-batcher's entry point). Compiled kinds go through the device
        when the measured device-dispatch latency beats the measured host
        per-pair rate for this batch's workload; the rest through the
        interpreter per review."""
        t0 = time.monotonic()
        try:
            return self._review_batch(target, reviews)
        finally:
            # finally, not the happy path: an engine burning its wall
            # clock on FAILING evals must still read busy, or the duty
            # gauge attributes the stall to the edge
            self.note_busy(time.monotonic() - t0)

    def _review_batch(self, target: str, reviews: list[dict]
                      ) -> list[list[Result]]:
        constraints = self._constraints(target)
        lookup_ns = self._namespace_lookup(target)
        inventory = self._inventory_tree(target)
        out: list[list[Result]] = [[] for _ in reviews]
        if not constraints:
            return out
        # autoreject applies per review before matching (regolib/src.go:7-20)
        from ..target.matcher import needs_autoreject
        from ..utils.values import freeze, thaw
        by_kind: dict[str, list[dict]] = {}
        for c in constraints:
            by_kind.setdefault(c.get("kind"), []).append(c)
        self._batch_used_mesh = False
        # results accumulate per (review, constraint) and assemble in
        # GLOBAL constraint order at the end, so a review's result list
        # is ordered exactly as the per-review violation query orders it
        # (_eval_violation: per constraint, autoreject then evals) — a
        # batched Review must not be distinguishable by result order
        auto: dict[tuple[int, int], Result] = {}
        acc: dict[tuple[int, int], list] = {}
        touched: dict[int, set] = {}  # review -> constraint ids with results
        for r, review in enumerate(reviews):
            for c in constraints:
                spec = c.get("spec")
                spec = spec if isinstance(spec, dict) else {}
                match = spec.get("match")
                match = match if isinstance(match, dict) else {}
                if needs_autoreject(match, review, lookup_ns):
                    touched.setdefault(r, set()).add(id(c))
                    auto[(r, id(c))] = Result(
                        msg="Namespace is not cached in OPA.",
                        metadata={"details": {}},
                        constraint=thaw(freeze(c)),
                        review=review,
                        enforcement_action=spec.get("enforcementAction")
                        or "deny",
                    )
        import time as _time

        batch_frz: dict = {}  # id(review) -> frozen, shared across kinds
        for kind in sorted(by_kind):
            cons = by_kind[kind]
            mask = match_masks(cons, reviews, lookup_ns)
            # autorejected pairs must not also evaluate; the matcher already
            # fails them (unresolvable namespaceSelector), so no extra work
            ct = self.compiled_for(kind)
            pairs = None
            n_masked = int(mask.sum())
            jc = self.join_for(kind) if ct is None and n_masked else None
            if jc is not None:
                try:
                    jcand = np.flatnonzero(mask.any(axis=1))
                    frz = []
                    for i in jcand:
                        r = reviews[int(i)]
                        f = batch_frz.get(id(r))
                        if f is None:
                            f = batch_frz[id(r)] = freeze(r)
                        frz.append(f)
                    fires = jc.fires(frz, inventory, self._data_gen)
                    pairs = [(int(jcand[k]), c)
                             for k in np.flatnonzero(fires)
                             for c in range(len(cons))
                             if mask[int(jcand[k]), c]]
                    if self._quarantine:
                        self._quarantine_clear(kind)
                except Exception as e:
                    self._join_compiled.pop(kind, None)
                    self._quarantine_kind(kind, "join-eval", e)
                    pairs = None
            if ct is not None and n_masked and \
                    len(reviews) >= self.MIN_DEVICE_BATCH and \
                    self._use_device_for_batch(n_masked):
                cand = np.flatnonzero(mask.any(axis=1))
                cand_reviews = [reviews[int(i)] for i in cand]
                try:
                    if len(cand_reviews) >= self.SPARSE_BATCH_MIN:
                        # audit-scale batch (discovery-mode sweeps stage
                        # the whole cluster here): the sparse firing-row
                        # gather — mesh-sharded when available — beats
                        # shipping a dense [N, C] verdict tensor; it
                        # records its own dispatch-based latency sample
                        pairs = self._review_batch_sparse(
                            ct, kind, cand, cand_reviews, cons, mask)
                    else:
                        t0 = _time.monotonic()
                        fires = self._eval_compiled_gated(ct, kind,
                                                          cand_reviews,
                                                          cons)
                        self._observe("_dev_batch_lat_s",
                                      _time.monotonic() - t0)
                        hits = np.logical_and(fires, mask[cand])
                        pairs = [(int(cand[ri]), int(ci))
                                 for ri, ci in zip(*np.nonzero(hits))]
                    if self._quarantine:
                        self._quarantine_clear(kind)
                except _ServeHostThisRound:
                    pass  # host path below; the warm continues
                except Exception as e:
                    self._quarantine_kind(kind, "review-eval", e)
            if pairs is None:
                pairs = [(r, c) for r in range(len(reviews))
                         for c in range(len(cons)) if mask[r, c]]
                t0 = _time.monotonic()
            else:
                t0 = None
            for r, ci in pairs:
                constraint = cons[ci]
                spec = constraint.get("spec")
                spec = spec if isinstance(spec, dict) else {}
                enforcement = spec.get("enforcementAction") or "deny"
                res = self._eval_template_violations(
                    target, constraint, reviews[r], enforcement,
                    inventory, None)
                if res:
                    touched.setdefault(r, set()).add(id(constraint))
                    acc.setdefault((r, id(constraint)), []).extend(res)
            if t0 is not None and pairs:
                host_s = _time.monotonic() - t0
                if host_s > 0:
                    self._observe("_host_pair_rate", len(pairs) / host_s)
        # assemble per review over only the POPULATED constraints (the
        # full reviews x constraints cross product would add an O(R*C)
        # Python pass to the audit-scale hot path), ordered by global
        # constraint position to match the per-review violation query
        order = {id(c): k for k, c in enumerate(constraints)}
        for r, cids in touched.items():
            for cid in sorted(cids, key=order.__getitem__):
                a = auto.get((r, cid))
                if a is not None:
                    out[r].append(a)
                out[r].extend(acc.get((r, cid), ()))
        # observability parity with _eval_audit — on a SEPARATE field:
        # webhook micro-batches also land here, and they must not
        # clobber last_audit_path (the cached audit's record) between
        # an audit finishing and its log line reading the field
        self.last_review_batch_path = (
            f"mesh(data={self._mesh.shape['data']})"
            if self._batch_used_mesh else "single")
        return out

    # ---------------------------------------------------- what-if preview

    def audit_kind(self, target: str, kind: str,
                   cons: list[dict]) -> tuple[list, str]:
        """Sweep ONE kind's constraints over the full cached inventory
        — the what-if preview's evaluation core. `kind` is normally a
        preview ALIAS (control/preview.py compiles the candidate
        template under a content-hashed alias kind), so every per-kind
        cache this rides — match mask, extracted feature rows, device
        programs, delta patching — is isolated from (and shaped exactly
        like) the serving library's. Reuses the audit dispatch/consume
        pipeline: sparse firing-pair gather, mesh sharding when the
        inventory is large enough, async warm with block-when-cheaper.
        Returns (results, path) with path in device|join|interp|empty.

        The device-latency EMA is NOT sampled here: preview sweeps may
        carry one-off compiles and must not steer admission batches to
        the host."""
        self._lat_sampled = True
        lookup_ns = self._namespace_lookup(target)
        inventory = self._inventory_tree(target)
        reviews = self._inventory_reviews(target)
        sig_cache = self._audit_sig_cache(target)
        if not reviews:
            return [], "empty"
        ct = self.compiled_for(kind)
        if ct is not None:
            st = self._audit_dispatch(target, kind, ct, cons, reviews,
                                      lookup_ns, sig_cache)
            if st is not None:
                return (self._audit_consume(target, kind, st, cons,
                                            reviews, lookup_ns,
                                            inventory, sig_cache),
                        "empty" if st[0] == "empty" else "device")
            return (self._audit_interp(target, kind, cons, reviews,
                                       lookup_ns, inventory, None,
                                       sig_cache), "interp")
        jc = self.join_for(kind)
        if jc is not None:
            return (self._audit_join(target, kind, jc, cons, reviews,
                                     lookup_ns, inventory, None,
                                     sig_cache), "join")
        return (self._audit_interp(target, kind, cons, reviews,
                                   lookup_ns, inventory, None,
                                   sig_cache), "interp")
