"""Inventory-join templates on device: cross-object policy evaluation.

Templates like uniqueingresshost / uniqueserviceselector join each review
against the whole synced cluster state
(/root/reference/library/general/uniqueingresshost/src.rego:8-18,
 /root/reference/library/general/uniqueserviceselector/src.rego:8-22) —
quadratic through any per-pair evaluator, and the last two general-library
templates with no device story. This module recognizes the join shape in
the merged template AST and splits the clause:

  review side   filters + join-key extraction, compiled to a codegen'd
                Python fn (exact; microseconds per review);
  inventory side  enumerate + filter + key extraction, one interpreter
                pass per data generation over the whole inventory
                (exact, O(M), cached until data changes);
  join          interned key ids, aggregated per unique key: the device
                answers "does some OTHER object share my key" with a
                searchsorted membership test against the sorted unique-key
                table carrying per-key object counts and (for singleton
                keys) the owner's identity key — O(N·H·log K) total,
                instead of the interpreter's O(N·M) rescan.

The `not identical(other, input.review)` exclusion becomes an identity-key
comparison: a review never fires on a key whose only holder is its own
stored copy. Identity fns may have ANY arity and MULTIPLE clauses — each
clause becomes an identity GROUP, and a pair is "identical" when any
group's tuples match; inline self-exclusion disequalities
(`name != input.review.object.metadata.name`) compile as single-pair
groups. `some`-decls are accepted, and an inventory ref used inline in a
literal (rather than bound `other := ...` first) is extracted into a
synthesized generator binding. The join decision is exact except in the
degenerate case of distinct inventory objects sharing one identity key
(then it may only OVER-fire); host materialization re-checks every firing
pair, the same authority contract as ir/evaljax.py.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field, replace as dc_replace
from typing import Optional

import numpy as np

from ..rego import ast as A
from ..rego.builtins import BUILTINS
from ..utils.values import thaw
from .compile import Uncompilable

REV_KEYS = "__join_rev_keys"
REV_IDENT = "__join_rev_ident"
INV_ENTRIES = "__join_inv_entries"
INV_IDENT = "__join_inv_ident"

# identity-key sentinels: never equal to any interned sid or to each other
IK_INV_MISSING = -1  # inventory object with undefined identity components
IK_REV_MISSING = -2  # review with undefined identity components
IK_MULTI = -3        # key held by >= 2 objects (identity irrelevant)
KEY_PAD = -5


# ------------------------------------------------------------- AST helpers


def _names(t, out: set) -> None:
    """All Var names appearing in a term (no fn names)."""
    if isinstance(t, A.Var):
        out.add(t.name)
    elif isinstance(t, A.Ref):
        _names(t.base, out)
        for a in t.args:
            _names(a, out)
    elif isinstance(t, A.Call):
        for a in t.args:
            _names(a, out)
    elif isinstance(t, A.BinOp):
        _names(t.lhs, out)
        _names(t.rhs, out)
    elif isinstance(t, A.UnaryMinus):
        _names(t.term, out)
    elif isinstance(t, (A.ArrayLit, A.SetLit)):
        for x in t.items:
            _names(x, out)
    elif isinstance(t, A.ObjectLit):
        for k, v in t.items:
            _names(k, out)
            _names(v, out)
    elif isinstance(t, (A.ArrayCompr, A.SetCompr)):
        _names(t.head, out)
        for lit in t.body:
            if not isinstance(lit.expr, A.SomeDecl):
                _names(lit.expr, out)
    elif isinstance(t, A.ObjectCompr):
        _names(t.key, out)
        _names(t.value, out)
        for lit in t.body:
            if not isinstance(lit.expr, A.SomeDecl):
                _names(lit.expr, out)
    elif isinstance(t, (A.Assign, A.Unify)):
        _names(t.lhs, out)
        _names(t.rhs, out)


def _subst(t, env: dict):
    """Replace Var occurrences by replacement ASTs (capture-naive; the
    substituted bodies are tiny field-projection chains)."""
    if isinstance(t, A.Var):
        return env.get(t.name, t)
    if isinstance(t, A.Ref):
        return A.Ref(base=_subst(t.base, env),
                     args=tuple(_subst(a, env) for a in t.args))
    if isinstance(t, A.Call):
        return A.Call(t.fn, tuple(_subst(a, env) for a in t.args))
    if isinstance(t, A.BinOp):
        return A.BinOp(t.op, _subst(t.lhs, env), _subst(t.rhs, env))
    if isinstance(t, A.UnaryMinus):
        return A.UnaryMinus(_subst(t.term, env))
    if isinstance(t, (A.ArrayLit, A.SetLit)):
        return type(t)(tuple(_subst(x, env) for x in t.items))
    if isinstance(t, A.ObjectLit):
        return A.ObjectLit(tuple((_subst(k, env), _subst(v, env))
                                 for k, v in t.items))
    return t


def _is_inventory_ref(t) -> Optional[A.Ref]:
    if isinstance(t, A.Ref) and isinstance(t.base, A.Var) and \
            t.base.name == "data" and t.args and \
            isinstance(t.args[0], A.Scalar) and t.args[0].value == "inventory":
        return t
    return None


# --------------------------------------------------------------- programs


@dataclass
class JoinClause:
    rev_keys: str     # partial-set rule: {[k1, k2, ...]} join-key tuples
    # identity-fn clauses become GROUPS: one (rev complete rule, inv
    # partial-set rule) pair per clause of the identity fn. A pair is
    # "identical" when ANY group's tuples match, so the exclusion
    # `not identical(...)` holds when EVERY group mismatches.
    rev_ident: list   # complete-rule names: [i1, i2, ...] per group
    inv_entries: str  # partial-set rule: {[[path...], [k...]]}
    inv_ident: list   # partial-set rules: {[[path...], [i...]]} per group


@dataclass
class JoinProgram:
    kind: str
    module: A.Module            # helpers + synthesized join rules
    clauses: list[JoinClause] = field(default_factory=list)


def _rule_flags(rules_by_name: dict) -> dict:
    """Transitive {'input','data'} read flags per rule/function name."""
    direct: dict[str, set] = {}
    deps: dict[str, set] = {}
    for name, rs in rules_by_name.items():
        flags: set = set()
        dep: set = set()

        def walk(t) -> None:
            if isinstance(t, A.Var):
                if t.name == "input":
                    flags.add("input")
                elif t.name == "data":
                    flags.add("data")
                elif t.name in rules_by_name:
                    dep.add(t.name)
            elif isinstance(t, A.Ref):
                walk(t.base)
                for a in t.args:
                    walk(a)
            elif isinstance(t, A.Call):
                if len(t.fn) == 1 and t.fn[0] in rules_by_name:
                    dep.add(t.fn[0])
                elif t.fn[0] == "data":
                    flags.add("data")
                for a in t.args:
                    walk(a)
            elif isinstance(t, A.BinOp):
                walk(t.lhs)
                walk(t.rhs)
            elif isinstance(t, A.UnaryMinus):
                walk(t.term)
            elif isinstance(t, (A.ArrayLit, A.SetLit)):
                for x in t.items:
                    walk(x)
            elif isinstance(t, A.ObjectLit):
                for k, v in t.items:
                    walk(k)
                    walk(v)
            elif isinstance(t, (A.ArrayCompr, A.SetCompr, A.ObjectCompr)):
                for lit in t.body:
                    if not isinstance(lit.expr, A.SomeDecl):
                        walk(lit.expr)
                for h in (getattr(t, "head", None), getattr(t, "key", None),
                          getattr(t, "value", None)):
                    if h is not None:
                        walk(h)
            elif isinstance(t, (A.Assign, A.Unify)):
                walk(t.lhs)
                walk(t.rhs)

        for r in rs:
            for lit in r.body:
                if not isinstance(lit.expr, A.SomeDecl):
                    walk(lit.expr)
            for h in (r.key, r.value):
                if h is not None:
                    walk(h)
            for a in r.args:
                walk(a)
        direct[name] = flags
        deps[name] = dep
    out = {n: set(f) for n, f in direct.items()}
    changed = True
    while changed:
        changed = False
        for n in out:
            for d in deps[n]:
                add = out.get(d, {"input", "data"}) - out[n]
                if add:
                    out[n] |= add
                    changed = True
    return out


def _rejects_parameters(module: A.Module) -> None:
    """Join programs are parameter-independent by construction (one
    fires[] per kind serves every constraint): any input.parameters
    reference — or a dynamic input reference that could reach it —
    makes the template uncompilable as a join."""

    def walk(t) -> None:
        if isinstance(t, A.Var):
            if t.name == "input":
                raise Uncompilable("join-input", "bare input reference")
        elif isinstance(t, A.Ref):
            if isinstance(t.base, A.Var) and t.base.name == "input":
                if not (t.args and isinstance(t.args[0], A.Scalar)
                        and t.args[0].value == "review"):
                    raise Uncompilable(
                        "join-input",
                        "input reference outside input.review "
                        "(parameterized join templates cannot share one "
                        "fires[] per kind)")
                for a in t.args:
                    walk(a)
                return
            walk(t.base)
            for a in t.args:
                walk(a)
        elif isinstance(t, A.Call):
            for a in t.args:
                walk(a)
        elif isinstance(t, A.BinOp):
            walk(t.lhs)
            walk(t.rhs)
        elif isinstance(t, A.UnaryMinus):
            walk(t.term)
        elif isinstance(t, (A.ArrayLit, A.SetLit)):
            for x in t.items:
                walk(x)
        elif isinstance(t, A.ObjectLit):
            for k, v in t.items:
                walk(k)
                walk(v)
        elif isinstance(t, (A.ArrayCompr, A.SetCompr, A.ObjectCompr)):
            for lit in t.body:
                if not isinstance(lit.expr, A.SomeDecl):
                    walk(lit.expr)
            for h in (getattr(t, "head", None), getattr(t, "key", None),
                      getattr(t, "value", None)):
                if h is not None:
                    walk(h)
        elif isinstance(t, (A.Assign, A.Unify)):
            walk(t.lhs)
            walk(t.rhs)

    for r in module.rules:
        for lit in r.body:
            if not isinstance(lit.expr, A.SomeDecl):
                walk(lit.expr)
        for h in (r.key, r.value):
            if h is not None:
                walk(h)
        for a in r.args:
            walk(a)


# -------------------------------------------------------------- recognizer


def _drop_head_only(body: list, head_names: set, rules: dict) -> list:
    """Remove Assign literals that only feed the violation head (the
    device decides fire/no-fire; host materialization recomputes msg)."""
    body = list(body)
    changed = True
    while changed:
        changed = False
        for i, lit in enumerate(body):
            e = lit.expr
            if lit.negated or not isinstance(e, A.Assign) or \
                    not isinstance(e.lhs, A.Var):
                continue
            name = e.lhs.name
            if name not in head_names:
                continue
            used = set()
            for j, other in enumerate(body):
                if j != i and not isinstance(other.expr, A.SomeDecl):
                    _names(other.expr, used)
            if name not in used:
                body.pop(i)
                changed = True
                break
    return body


def _find_inv_refs(t, out: list) -> None:
    """Collect inventory Ref nodes (by identity) anywhere in a term."""
    if isinstance(t, A.Ref):
        if _is_inventory_ref(t) is not None:
            out.append(t)
            return
        _find_inv_refs(t.base, out)
        for a in t.args:
            _find_inv_refs(a, out)
    elif isinstance(t, A.Call):
        for a in t.args:
            _find_inv_refs(a, out)
    elif isinstance(t, A.BinOp):
        _find_inv_refs(t.lhs, out)
        _find_inv_refs(t.rhs, out)
    elif isinstance(t, A.UnaryMinus):
        _find_inv_refs(t.term, out)
    elif isinstance(t, (A.ArrayLit, A.SetLit)):
        for x in t.items:
            _find_inv_refs(x, out)
    elif isinstance(t, A.ObjectLit):
        for k, v in t.items:
            _find_inv_refs(k, out)
            _find_inv_refs(v, out)
    elif isinstance(t, (A.Assign, A.Unify)):
        _find_inv_refs(t.lhs, out)
        _find_inv_refs(t.rhs, out)


def _replace_node(t, old, new):
    """Replace a node found by identity (splicing ref-into-ref bases)."""
    if t is old:
        return new
    if isinstance(t, A.Ref):
        base = _replace_node(t.base, old, new)
        args = tuple(_replace_node(a, old, new) for a in t.args)
        if isinstance(base, A.Ref):
            return A.Ref(base=base.base, args=base.args + args)
        return A.Ref(base=base, args=args)
    if isinstance(t, A.Call):
        return A.Call(t.fn, tuple(_replace_node(a, old, new)
                                  for a in t.args))
    if isinstance(t, A.BinOp):
        return A.BinOp(t.op, _replace_node(t.lhs, old, new),
                       _replace_node(t.rhs, old, new))
    if isinstance(t, A.UnaryMinus):
        return A.UnaryMinus(_replace_node(t.term, old, new))
    if isinstance(t, (A.ArrayLit, A.SetLit)):
        return type(t)(tuple(_replace_node(x, old, new) for x in t.items))
    if isinstance(t, A.ObjectLit):
        return A.ObjectLit(tuple((_replace_node(k, old, new),
                                  _replace_node(v, old, new))
                                 for k, v in t.items))
    if isinstance(t, (A.Assign, A.Unify)):
        return type(t)(_replace_node(t.lhs, old, new),
                       _replace_node(t.rhs, old, new))
    return t


def _split_inv_ref(ref: A.Ref):
    """Split an inline inventory ref at the object boundary:
    data.inventory.namespace[ns][apiv][kind][name](.residual...) — the
    first 5 (namespaced) / 4 (cluster) post-"inventory" segments address
    the object; the rest descend into it. None when the shape is off."""
    args = ref.args
    if len(args) < 2 or not isinstance(args[1], A.Scalar):
        return None
    n = {"namespace": 6, "cluster": 5}.get(args[1].value)
    if n is None or len(args) < n:
        return None
    head = A.Ref(base=ref.base, args=args[:n])
    return head, args[n:]


def _extract_inline_generators(body: list, idx: int) -> list:
    """Binding introduction for upstream-canonical clauses that use the
    inventory ref INLINE (`data.inventory.namespace[ns][_][\"Service\"]
    [name].spec.selector == sel`) instead of binding `other :=` first:
    each inline ref becomes a fresh generator binding plus a residual
    ref through the fresh var, which the side-splitter then classifies
    normally."""
    out: list = []
    n_fresh = 0
    for lit in body:
        e = lit.expr
        # a NEGATED inventory ref asserts absence — introducing a
        # positive generator binding for it would invert the semantics;
        # leave it for the generator locator to reject
        if lit.negated:
            out.append(lit)
            continue
        # the canonical binding form is left alone (the generator
        # locator owns it)
        if isinstance(e, (A.Assign, A.Unify)) and (
                _is_inventory_ref(e.rhs) is not None
                or _is_inventory_ref(e.lhs) is not None):
            out.append(lit)
            continue
        refs: list = []
        _find_inv_refs(e, refs)
        changed = False
        for ref in refs:
            split = _split_inv_ref(ref)
            if split is None:
                continue
            head, rest = split
            fresh = f"__jg{idx}_{n_fresh}"
            n_fresh += 1
            repl = A.Ref(base=A.Var(fresh), args=rest) if rest \
                else A.Var(fresh)
            e = _replace_node(e, ref, repl)
            out.append(A.Literal(expr=A.Assign(A.Var(fresh), head)))
            changed = True
        out.append(dc_replace(lit, expr=e) if changed else lit)
    return out


def _compile_clause(rule: A.Rule, rules_by_name: dict, idx: int,
                    new_rules: list, arg_pure: set) -> JoinClause:
    head_names: set = set()
    _names(rule.key, head_names)
    body = _drop_head_only(list(rule.body), head_names, rules_by_name)
    # `some ns, apiv, name` declarations scope vars the generator walk
    # names anyway — they carry no constraints of their own
    body = [lit for lit in body if not isinstance(lit.expr, A.SomeDecl)]
    body = _extract_inline_generators(body, idx)

    # locate the inventory generator
    gen_i = None
    for i, lit in enumerate(body):
        e = lit.expr
        tgt = None
        if isinstance(e, (A.Assign, A.Unify)):
            tgt = _is_inventory_ref(e.rhs) or _is_inventory_ref(e.lhs)
        else:
            tgt = _is_inventory_ref(e)
        if tgt is not None:
            if gen_i is not None:
                raise Uncompilable("join-generator", "multiple inventory generators")
            if lit.negated:
                raise Uncompilable("join-generator", "negated inventory generator")
            gen_i = i
    if gen_i is None:
        raise Uncompilable("join-generator", "no inventory generator")
    gen_lit = body[gen_i]
    ge = gen_lit.expr
    if not (isinstance(ge, (A.Assign, A.Unify)) and isinstance(ge.lhs, A.Var)
            and _is_inventory_ref(ge.rhs) is not None):
        raise Uncompilable("join-generator", "generator must bind a var")
    other_var = ge.lhs.name
    inv_ref = ge.rhs
    # name the path segments (wildcards get fresh names so the object id
    # tuple is always fully bound)
    path_vars: list[str] = []
    new_args: list = []
    for k, a in enumerate(inv_ref.args[1:]):  # skip the "inventory" segment
        if isinstance(a, A.Var):
            nm = a.name
            if nm.startswith("$wc"):
                nm = f"__jw{idx}_{k}"
            path_vars.append(nm)
            new_args.append(A.Var(nm))
        elif isinstance(a, A.Scalar):
            new_args.append(a)
        else:
            raise Uncompilable("join-generator", "complex inventory path segment")
    gen_expr = A.Assign(A.Var(other_var),
                        A.Ref(base=A.Var("data"),
                              args=(A.Scalar("inventory"),) + tuple(new_args)))
    gen_lit = A.Literal(expr=gen_expr)

    inv_vars = {other_var, *path_vars}
    rev_vars: set = set()
    rev_lits: list = []
    inv_lits: list = []
    join_pairs: list = []     # (inv_expr, rev_expr)
    ident_groups: list = []   # per identity-fn clause: [(inv, rev), ...]

    builtin1 = {fn[0] for fn in BUILTINS}
    rule_names = set(rules_by_name)
    fn_flags = _rule_flags(rules_by_name)

    def reads_of(t, out: set) -> None:
        """Var reads INCLUDING 'input'/'data' markers; calls to user
        functions and document-rule references propagate their bodies'
        transitive input/data reads (a 1-arg is_self helper reads input;
        a helper peeking at data.inventory reads data — the latter is
        rejected outside the generator, since both side evaluators run
        with only their own document mounted)."""
        if isinstance(t, A.Var):
            if t.name.startswith("$wc") or t.name in builtin1:
                return
            if t.name in rule_names:
                out |= fn_flags.get(t.name, {"input", "data"})
                return
            out.add(t.name)
            return
        if isinstance(t, A.Ref):
            reads_of(t.base, out)
            for a in t.args:
                reads_of(a, out)
            return
        if isinstance(t, A.Call):
            f = t.fn
            if len(f) == 1 and f[0] in rule_names:
                if f[0] not in arg_pure:
                    out |= fn_flags.get(f[0], {"input", "data"})
            elif f[0] == "data":
                out.add("data")
            for a in t.args:
                reads_of(a, out)
            return
        if isinstance(t, A.BinOp):
            reads_of(t.lhs, out)
            reads_of(t.rhs, out)
            return
        if isinstance(t, A.UnaryMinus):
            reads_of(t.term, out)
            return
        if isinstance(t, (A.ArrayLit, A.SetLit)):
            for x in t.items:
                reads_of(x, out)
            return
        if isinstance(t, A.ObjectLit):
            for k, v in t.items:
                reads_of(k, out)
                reads_of(v, out)
            return
        if isinstance(t, (A.ArrayCompr, A.SetCompr, A.ObjectCompr)):
            # inline comprehension: local binders over-approximate as
            # reads, which can only force a literal toward rev/mixed
            # (never silently into inv)
            for lit2 in getattr(t, "body", ()):
                if not isinstance(lit2.expr, A.SomeDecl):
                    reads_of(lit2.expr, out)
            for h in (getattr(t, "head", None), getattr(t, "key", None),
                      getattr(t, "value", None)):
                if h is not None:
                    reads_of(h, out)
            return
        if isinstance(t, (A.Assign, A.Unify)):
            reads_of(t.lhs, out)
            reads_of(t.rhs, out)
            return

    def var_reads(t) -> set:
        s: set = set()
        reads_of(t, s)
        return s

    def side_of(t) -> str:
        reads = var_reads(t)
        in_inv = bool(reads & inv_vars)
        in_rev = bool((reads - inv_vars) - {"data"})
        if in_inv and in_rev:
            return "mixed"
        if in_inv:
            return "inv"
        return "rev"

    for i, lit in enumerate(body):
        if i == gen_i:
            continue
        e = lit.expr
        if isinstance(e, A.SomeDecl):  # pragma: no cover - filtered above
            continue
        if lit.withs:
            raise Uncompilable("join-with", "with modifier")
        # exclusion: `not identical(other, input.review)` /
        # `not is_self(other)` — any arity: substitute formals with the
        # actual args, then each body equality must split into a pure
        # inventory-side and a pure review-side expression
        if lit.negated and isinstance(e, A.Call) and len(e.fn) == 1 and \
                e.fn[0] in rules_by_name and \
                rules_by_name[e.fn[0]][0].kind == "function" and \
                any(side_of(a) == "inv" for a in e.args):
            # each clause of the identity fn becomes its own GROUP of
            # (inv, rev) equality pairs — "identical" when any group's
            # tuples fully match, so the negation excludes exactly the
            # union of the clauses
            for fr in rules_by_name[e.fn[0]]:
                if fr.kind != "function":
                    raise Uncompilable("join-identity",
                                       "identity fn clause mix")
                if len(fr.args) != len(e.args) or \
                        not all(isinstance(a, A.Var) for a in fr.args):
                    raise Uncompilable("join-identity", "identity fn arg shape")
                env = {fa.name: aa for fa, aa in zip(fr.args, e.args)}
                pairs: list = []
                for bl in fr.body:
                    be = bl.expr
                    if bl.negated or not isinstance(be, (A.BinOp, A.Unify)) \
                            or (isinstance(be, A.BinOp) and be.op != "=="):
                        raise Uncompilable("join-identity", "identity fn body")
                    lhs = _subst(be.lhs, env)
                    rhs = _subst(be.rhs, env)
                    if "data" in (var_reads(lhs) | var_reads(rhs)):
                        raise Uncompilable("join-identity",
                                           "data read in identity fn")
                    ls, rs = side_of(lhs), side_of(rhs)
                    if ls == "inv" and rs == "rev":
                        pairs.append((lhs, rhs))
                    elif rs == "inv" and ls == "rev":
                        pairs.append((rhs, lhs))
                    else:
                        raise Uncompilable("join-identity", "identity eq shape")
                if not pairs:
                    raise Uncompilable("join-identity",
                                       "empty identity fn clause")
                ident_groups.append(pairs)
            continue
        if "data" in var_reads(e):
            raise Uncompilable("join-data", "data reference outside generator")
        # fresh-var assignments side with their rhs (the bound lhs is a
        # definition, not a cross-side read)
        if not lit.negated and isinstance(e, (A.Assign, A.Unify)) and \
                isinstance(e.lhs, A.Var) and \
                e.lhs.name not in (inv_vars | rev_vars):
            rhs_side = side_of(e.rhs)
            if rhs_side != "mixed":
                fresh = var_reads(e.rhs) | {e.lhs.name}
                if rhs_side == "inv":
                    inv_lits.append(lit)
                    inv_vars |= fresh
                else:
                    rev_lits.append(lit)
                    rev_vars |= fresh
                continue
        side = side_of(e)
        if side == "rev":
            rev_lits.append(lit)
            if not lit.negated:
                rev_vars |= var_reads(e)
            continue
        if side == "inv":
            inv_lits.append(lit)
            if not lit.negated:
                inv_vars |= var_reads(e)
            continue
        # mixed disequality (`name != input.review...name`, or
        # `not a == b`): an INLINE self-exclusion — exactly a
        # single-pair identity group (the pair is excluded when the
        # sides are equal). Inventory-side undefinedness over-fires
        # (missing sentinel mismatches), never under-fires.
        neq = None
        if not lit.negated and isinstance(e, A.BinOp) and e.op == "!=":
            neq = (e.lhs, e.rhs)
        elif lit.negated and (isinstance(e, A.Unify) or
                              (isinstance(e, A.BinOp) and e.op == "==")):
            neq = (e.lhs, e.rhs)
        if neq is not None:
            for a, b in (neq, neq[::-1]):
                if side_of(a) == "inv" and side_of(b) == "rev":
                    ident_groups.append([(a, b)])
                    break
            else:
                raise Uncompilable("join-mixed",
                                   "mixed disequality is not inv != rev")
            continue
        # mixed: must be a join equality with one pure side each
        if lit.negated or not isinstance(e, (A.BinOp, A.Unify)) or \
                (isinstance(e, A.BinOp) and e.op != "=="):
            raise Uncompilable("join-mixed", "unsupported mixed literal")
        for a, b in ((e.lhs, e.rhs), (e.rhs, e.lhs)):
            if side_of(a) == "inv" and side_of(b) == "rev":
                join_pairs.append((a, b))
                break
        else:
            raise Uncompilable("join-mixed", "mixed literal is not inv==rev")

    if not join_pairs:
        raise Uncompilable("join-shape", "no join predicate")

    # ---- synthesized rules ------------------------------------------
    path_tuple = A.ArrayLit(tuple(A.Var(v) for v in path_vars))
    inv_key = A.ArrayLit(tuple(p[0] for p in join_pairs))
    rev_key = A.ArrayLit(tuple(p[1] for p in join_pairs))

    rk = f"{REV_KEYS}_{idx}"
    ie = f"{INV_ENTRIES}_{idx}"

    new_rules.append(A.Rule(name=rk, kind="partial_set", key=rev_key,
                            body=tuple(rev_lits)))
    new_rules.append(A.Rule(
        name=ie, kind="partial_set",
        key=A.ArrayLit((path_tuple, inv_key)),
        body=(gen_lit,) + tuple(inv_lits)))
    ris: list = []
    iis: list = []
    for g, pairs in enumerate(ident_groups):
        ri = f"{REV_IDENT}_{idx}_{g}"
        ii = f"{INV_IDENT}_{idx}_{g}"
        ris.append(ri)
        iis.append(ii)
        new_rules.append(A.Rule(
            name=ri, kind="complete",
            value=A.ArrayLit(tuple(p[1] for p in pairs)), body=()))
        new_rules.append(A.Rule(
            name=ii, kind="partial_set",
            key=A.ArrayLit((path_tuple,
                            A.ArrayLit(tuple(p[0] for p in pairs)))),
            body=(gen_lit,) + tuple(inv_lits)))
    return JoinClause(rev_keys=rk, rev_ident=ris, inv_entries=ie,
                      inv_ident=iis)


def compile_join(module: A.Module, kind: str) -> JoinProgram:
    """Compile a merged template module whose violation clauses are
    inventory joins. Raises Uncompilable outside the join shape."""
    rules_by_name: dict[str, list] = {}
    for r in module.rules:
        rules_by_name.setdefault(r.name, []).append(r)
    vio = rules_by_name.get("violation")
    if not vio:
        raise Uncompilable("join-shape", "no violation rule")
    _rejects_parameters(module)
    from ..rego.codegen import ModuleCompiler
    arg_pure = ModuleCompiler(module).arg_pure
    new_rules: list = [r for r in module.rules if r.name != "violation"]
    clauses = []
    for idx, r in enumerate(vio):
        if r.kind != "partial_set" or r.key is None:
            raise Uncompilable("join-shape", "violation shape")
        clauses.append(_compile_clause(r, rules_by_name, idx, new_rules,
                                       arg_pure))
    prog = JoinProgram(kind=kind,
                       module=dc_replace(module, rules=tuple(new_rules)),
                       clauses=clauses)
    return prog


# ----------------------------------------------------------------- runtime


def _canon_sid(strtab, v) -> int:
    """Intern a frozen value as a join-key id. Strings take a fast path;
    composites go through canonical JSON, type-prefixed so e.g. the
    string '1' and the number 1 never collide."""
    if isinstance(v, str):
        return strtab.intern("k:s:" + v)
    return strtab.intern("k:j:" + json.dumps(thaw(v), sort_keys=True))


class JoinCompiled:
    """Driver-facing evaluator for one join template."""

    def __init__(self, prog: JoinProgram, strtab, aot=None,
                 kind: str = ""):
        from ..rego.codegen import compile_module
        from ..rego.interp import Interpreter
        from .aot import program_fingerprint

        self.prog = prog
        self.strtab = strtab
        # AOT program store (ir/aot.py): the join membership program
        # persists across restarts like the template sweep programs do
        self.aot = aot
        self.kind = kind
        self.fingerprint = program_fingerprint(prog.module,
                                               "join:" + kind)
        self._pkg = tuple(prog.module.package)
        self._interp = Interpreter({"join": prog.module})
        self._rev_fns = []
        for c in prog.clauses:
            fk = compile_module(prog.module, entry=c.rev_keys)
            fis = tuple(compile_module(prog.module, entry=ri)
                        for ri in c.rev_ident)
            self._rev_fns.append((fk, fis))
        # (data_gen, id(inventory_tree)) -> tabs; the tree identity keeps
        # two targets at the same data generation from sharing tables
        self._inv_cache: dict = {}
        # clause -> (inv_key, kb, device (u_p, cnt_p, sik_p))
        self._dev_inv_cache: dict = {}
        # clause -> (karr bytes, iks bytes, device (karr, iks)) — review
        # tensors are only reused when their CONTENT matches; keying by
        # shape alone returned stale fires when the candidate set changed
        # membership at equal size
        self._dev_rev_cache: dict = {}
        self._jit = None

    # ------------------------------------------------ inventory tables

    def inv_tables(self, inventory_tree, data_gen) -> list:
        """Per clause: (U sorted unique key sids, CNT objects per key,
        SIK [G, K] per-identity-group sid when CNT==1 else IK_MULTI,
        host dict). G >= 1 always — a template without an identity fn
        gets one group of missing sentinels, which never match."""
        cache_key = (data_gen, id(inventory_tree))
        hit = self._inv_cache.get(cache_key)
        # the entry pins the tree, so an id() hit can only be the same
        # object — the identity check guards against a tree freed and
        # re-allocated at the same address before this entry existed
        if hit is not None and hit[0] is inventory_tree:
            return hit[1]
        from ..rego.interp import UNDEF

        tabs = []
        for c in self.prog.clauses:
            entries = self._interp.eval_rule(
                self._pkg, c.inv_entries, None,
                overrides={("inventory",): inventory_tree})
            G = max(1, len(c.inv_ident))
            idents: dict = {}
            for g, ii in enumerate(c.inv_ident):
                iv = self._interp.eval_rule(
                    self._pkg, ii, None,
                    overrides={("inventory",): inventory_tree})
                if iv is not UNDEF:
                    for path, ident in iv:
                        ent = idents.setdefault(
                            path, [IK_INV_MISSING] * G)
                        ent[g] = self.strtab.intern(
                            "i:" + json.dumps(thaw(ident), sort_keys=True))
            by_key: dict[int, list] = {}
            if entries is not UNDEF:
                per_obj: dict = {}
                for path, key in entries:
                    per_obj.setdefault(path, set()).add(
                        _canon_sid(self.strtab, key))
                missing = (IK_INV_MISSING,) * G
                for path, ksids in per_obj.items():
                    ik = tuple(idents.get(path, missing))
                    for ks in ksids:
                        by_key.setdefault(ks, []).append(ik)
            u = np.array(sorted(by_key), dtype=np.int64)
            cnt = np.array([len(by_key[k]) for k in u], dtype=np.int32)
            sik = np.full((G, len(u)), IK_MULTI, dtype=np.int64)
            for j, k in enumerate(u):
                holders = by_key[k]
                if len(holders) == 1:
                    sik[:, j] = holders[0]
            host = {int(k): (int(c_), tuple(int(s) for s in sik[:, j]))
                    for j, (k, c_) in enumerate(zip(u, cnt))}
            tabs.append((u, cnt, sik, host))
        # stale generations (and their device tensors) can't be reused;
        # drop them so long-running audits don't accumulate tables
        if any(k[0] != data_gen for k in self._inv_cache):
            self._inv_cache = {k: v for k, v in self._inv_cache.items()
                               if k[0] == data_gen}
            self._dev_inv_cache.clear()
        self._inv_cache[cache_key] = (inventory_tree, tabs)
        return tabs

    # ------------------------------------------------------ review keys

    def _rev_eval(self, fn, frz_review, frozen_empty):
        from ..rego.interp import UNDEF
        from ..utils.values import FrozenDict

        if fn.__sections__:
            return fn(frz_review, FrozenDict(), frozen_empty)
        return fn(FrozenDict((("review", frz_review),)), frozen_empty)

    def review_keys(self, clause_i: int, frz_review) -> tuple:
        """(key sids list, per-group ident sid tuple) for one review;
        empty list when the review-side filters fail."""
        from ..rego.interp import UNDEF
        from ..utils.values import FrozenDict

        fk, fis = self._rev_fns[clause_i]
        G = max(1, len(fis))
        empty = FrozenDict()
        ks = self._rev_eval(fk, frz_review, empty)
        if ks is UNDEF or not ks:
            return [], (IK_REV_MISSING,) * G
        sids = sorted({_canon_sid(self.strtab, k) for k in ks})
        iks = [IK_REV_MISSING] * G
        for g, fi in enumerate(fis):
            iv = self._rev_eval(fi, frz_review, empty)
            if iv is not UNDEF:
                iks[g] = self.strtab.intern(
                    "i:" + json.dumps(thaw(iv), sort_keys=True))
        return sids, tuple(iks)

    # ------------------------------------------------------------ fires

    # below this many reviews a host dict probe beats a device dispatch
    MIN_DEVICE_REVIEWS = 2048

    def fires(self, frz_reviews: list, inventory_tree, data_gen,
              key_cache: Optional[dict] = None) -> np.ndarray:
        """bool[N]: does some OTHER inventory object share a join key.
        key_cache (id(review) -> per-clause (keys, ident)), valid for one
        data generation, makes steady-state audits skip re-extraction."""
        tabs = self.inv_tables(inventory_tree, data_gen)
        n = len(frz_reviews)
        out = np.zeros(n, dtype=bool)
        for ci, (u, cnt, sik, host) in enumerate(tabs):
            if not len(u):
                continue
            G = sik.shape[0]
            keys = []
            iks = np.full((n, G), IK_REV_MISSING, dtype=np.int32)
            hmax = 0
            for r in range(n):
                rv = frz_reviews[r]
                hit = key_cache.get((ci, id(rv))) if key_cache is not None \
                    else None
                if hit is None:
                    hit = self.review_keys(ci, rv)
                    if key_cache is not None:
                        key_cache[(ci, id(rv))] = hit
                ks, ik = hit
                keys.append(ks)
                iks[r, :] = ik
                hmax = max(hmax, len(ks))
            if hmax == 0:
                continue
            if n >= self.MIN_DEVICE_REVIEWS:
                out |= self._fires_device(
                    ci, u, cnt, sik, keys, iks, hmax,
                    (data_gen, id(inventory_tree)))
            else:
                for r in range(n):
                    if out[r]:
                        continue
                    for k in keys[r]:
                        hit = host.get(k)
                        # fires unless the key's single holder is
                        # identical to the review under SOME group
                        if hit is not None and (
                                hit[0] >= 2
                                or not any(hs == int(ig) for hs, ig
                                           in zip(hit[1], iks[r]))):
                            out[r] = True
                            break
        return out

    def _fires_device(self, ci, u, cnt, sik, keys, iks, hmax,
                      inv_key) -> np.ndarray:
        """Device membership: pad keys to [N, H], searchsorted into the
        padded unique-key table, apply count/identity rules. One jit per
        (H bucket, K bucket) shape. Inventory tensors are cached per
        (clause, data generation, tree identity); review tensors are
        rebuilt on host every call and their device copies reused only
        when the BYTES match — steady-state audits (same candidate list)
        skip the H2D upload, while a changed candidate set of equal size
        never sees stale keys."""
        import jax

        # int32 throughout: jax runs with x64 disabled, which would
        # silently truncate int64 inputs (interned sids always fit)
        n = len(keys)
        G = sik.shape[0]
        h = 1
        while h < hmax:
            h *= 2
        kb = 1
        while kb < len(u):
            kb *= 2
        ent = self._dev_inv_cache.get(ci)
        if ent is not None and ent[0] == inv_key and ent[1] == (kb, G):
            inv_args = ent[2]
        else:
            big = np.iinfo(np.int32).max
            u_p = np.full(kb, big, dtype=np.int32)
            u_p[:len(u)] = u
            cnt_p = np.zeros(kb, dtype=np.int32)
            cnt_p[:len(u)] = cnt
            sik_p = np.full((G, kb), IK_MULTI, dtype=np.int32)
            sik_p[:, :len(u)] = sik
            inv_args = tuple(jax.device_put(a) for a in (u_p, cnt_p, sik_p))
            self._dev_inv_cache[ci] = (inv_key, (kb, G), inv_args)

        karr = np.full((n, h), KEY_PAD, dtype=np.int32)
        for r, ks in enumerate(keys):
            karr[r, :len(ks)] = ks
        iks32 = iks.astype(np.int32)
        kb_bytes, ik_bytes = karr.tobytes(), iks32.tobytes()
        rev = self._dev_rev_cache.get(ci)
        if rev is not None and rev[0] == kb_bytes and rev[1] == ik_bytes:
            rev_args = rev[2]
        else:
            rev_args = (jax.device_put(karr), jax.device_put(iks32))
            self._dev_rev_cache[ci] = (kb_bytes, ik_bytes, rev_args)
        args = inv_args + rev_args

        return np.asarray(self._jit_wrapper()(*args))

    def _jit_wrapper(self):
        if self._jit is None:
            import jax
            import jax.numpy as jnp

            def run(u_p, cnt_p, sik_p, karr, iks):
                pos = jnp.searchsorted(u_p, karr)
                pos = jnp.clip(pos, 0, u_p.shape[0] - 1)
                found = (u_p[pos] == karr) & (karr != KEY_PAD)
                # identical under SOME identity group blocks the fire;
                # sik_p is [G, Kb], iks is [N, G]
                ident_any = jnp.any(
                    sik_p[:, pos] == iks.T[:, :, None], axis=0)
                fire = found & ((cnt_p[pos] >= 2) | ~ident_any)
                return jnp.any(fire, axis=1)
            from .aot import AotJit

            # store=None (no AOT dir) degrades to the plain jit inside
            # the wrapper — one code path, and the gklint jit checker
            # can see every join program rides the AOT store when on
            self._jit = AotJit(run, store=self.aot,
                               fingerprint=self.fingerprint,
                               tag="join", kind=self.kind)
        return self._jit

    def preload_aot(self) -> dict:
        """Deserialize stored join executables for this program's
        fingerprint (ingest-time background prewarm; see
        CompiledTemplate.preload_aot). Returns programs loaded by tag."""
        loaded: dict[str, int] = {}
        if self.aot is None or not self.aot.enabled:
            return loaded
        w = self._jit_wrapper()
        for ent in self.aot.entries_for(self.fingerprint):
            if ent["tag"] != "join":
                continue
            try:
                key = self.aot.entry_key(self.fingerprint, "join",
                                         ent["static"], ent["asig"])
                if w.preload(ent["asig"], key):
                    loaded["join"] = loaded.get("join", 0) + 1
            except Exception:  # pragma: no cover - prewarm best-effort
                continue
        return loaded
