"""Vectorized violation-message materialization.

The audit's device sweep answers "which (object, constraint) pairs fire"
in ~0.3s; turning those pairs into violation MESSAGES was ~3x slower
than the sweep itself (BENCH_r04/r05: `materialize_s` ~ 3x
`sweep_wall_s`) because every firing pair re-ran the template's codegen
evaluator in Python just to rebuild a string the clause head already
determines. This module removes that Python-per-pair work for the
common head shape:

    violation[{"msg": msg, "details": {}}] {
      ...body...
      msg := sprintf("... %v ... %v ...", [<witness>, <witness>])
    }

by compiling the head ONCE per template into a `MsgPlan` — constant
fmt segments plus typed witness fillers — and filling the witnesses for
all firing pairs at once with numpy fancy-indexing over fixed-width
unicode columns (the same technique ops/strtab.py uses for its
pattern-window caches):

  * const args render once at plan time;
  * `input.parameters...` args are constraint-constant: rendered once
    per constraint with the EXACT host sprintf verb logic;
  * `input.review...` scalar paths become per-row witness columns
    (built in one pass over the review list, cached per data revision);
  * `{v | v = input.review...[_][k]}` set comprehensions become per-row
    pre-rendered set strings (the forbidden-sysctls shape).

Assembly is then `seg0 + wit0[rows] + seg1_c[cols] + ...` over U-dtype
arrays — numpy C loops, no per-pair Python.

Correctness contract (differential-tested bit-equal against the exact
per-pair evaluator, tests/test_materialize_vec.py):

  * the plan only applies when the compiled device program is EXACT —
    `program_exactness` proves the filter can never over-fire, so a
    firing pair IS a violation (plus per-constraint runtime conditions
    for param slots whose values must be non-composite);
  * witnesses outside the representable subset VETO their pair back to
    the exact evaluator: absent / non-string row values, strings past
    the fixed-width window cap, constraints whose param path is
    undefined;
  * templates whose messages read anything else (per-axis witnesses
    like container names, helper-function msgs, non-const details)
    produce no plan at all and keep the exact path wholesale.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

import numpy as np

from ..rego import ast as A
from ..rego.builtins import BuiltinError, bi_sprintf
from ..utils.values import freeze
from .prog import (
    And,
    Arith,
    Cmp,
    Const,
    DerivedVal,
    Exists,
    KindIs,
    MatchLookup,
    Not,
    Or,
    OrReduce,
    OVal,
    Program,
    PVal,
    SumReduce,
    Truthy,
)

# fixed-width unicode columns cost O(rows x max_len); past this length
# the padded array is a bad trade and the pair vetoes to the exact path
# (same constant family as ops.strtab.MatchTables.MAX_VECTOR_STRLEN)
MAX_WITNESS_STRLEN = 512


# ----------------------------------------------------------- exactness


def _num_exactness(e) -> Optional[tuple]:
    """-> (point, nid_free, conditions) for a numeric operand, or None
    when unsupported. Mirrors evaljax._eval_num: cell leaves are point
    values carrying a canonical-number id (tie-capable); SumReduce /
    count leaves are nid-free; Arith widens to an interval."""
    if isinstance(e, SumReduce):
        conds = _bool_exactness(e.e)
        if conds is None:
            return None
        return (True, True, conds)
    if isinstance(e, OVal) and e.f in ("count", "countz"):
        return (True, True, set())
    if isinstance(e, PVal) and e.f == "count":
        return (True, True, set())
    if isinstance(e, Arith):
        return (False, True, None)  # interval-widened: never exact
    if isinstance(e, (OVal, PVal, Const, DerivedVal)):
        return (True, False, set())  # point value, tie-capable nid
    return None


def _never_composite(e) -> bool:
    """Can this cell expr statically never hold an array/object?"""
    return isinstance(e, Const)


def _bool_exactness(e) -> Optional[set]:
    """The set of runtime conditions under which this expr's BPair is
    exact (lo == hi), or None when it can over-fire regardless.

    Conditions are ("pval_scalar", slot): every encoded value of that
    param slot must be non-composite — checked per constraint set at
    materialize time (composite "maybe"-equality is the one auto-eq
    uncertainty a param-side kind check can discharge)."""
    if isinstance(e, Cmp):
        if e.dtype == "auto":
            # _cell_eq's `maybe` needs BOTH sides composite of the same
            # kind: a side that can never be composite makes eq exact;
            # a PVal side becomes a runtime param-kind condition
            if _never_composite(e.lhs) or _never_composite(e.rhs):
                return set()
            for side in (e.lhs, e.rhs):
                if isinstance(side, PVal):
                    return {("pval_scalar", side.slot)}
            return None
        lx = _num_exactness(e.lhs)
        rx = _num_exactness(e.rhs)
        if lx is None or rx is None:
            return None
        lp, lnf, lc = lx
        rp, rnf, rc = rx
        if not lnf and not rnf:
            return None  # f32 tie between two canonical ids possible
        if not (lp and rp):
            return None  # interval operand: hi over-approximates
        return (lc or set()) | (rc or set())
    if isinstance(e, (MatchLookup, Truthy, Exists, KindIs, Const)):
        return set()
    if isinstance(e, (And, Or)):
        out: set = set()
        for x in e.items:
            c = _bool_exactness(x)
            if c is None:
                return None
            out |= c
        return out
    if isinstance(e, Not):
        return _bool_exactness(e.e)
    if isinstance(e, OrReduce):
        return _bool_exactness(e.e)
    if isinstance(e, SumReduce):
        return _bool_exactness(e.e)
    return None


def program_exactness(program: Program) -> Optional[set]:
    """Conditions under which the compiled filter is EXACT (fires ==
    interpreter truth), or None when it may over-fire. The vectorized
    message path requires exactness: it renders a message for every
    firing pair without re-running the evaluator."""
    out: set = set()
    for clause in program.clauses:
        for g in clause.guards:
            c = _bool_exactness(g.expr)
            if c is None:
                return None
            out |= c
    return out


# ------------------------------------------------------------ planning


@dataclass(frozen=True)
class Witness:
    """One fmt placeholder filler.

    kind: "const" (pre-rendered), "param" (path into spec.parameters,
    rendered per constraint), "row" (scalar path into the review dict),
    "rowset" ({v | v = path} set comprehension over the review).
    segs for row/rowset: tuple of ("f", name) | ("iter",).
    """

    kind: str
    spec: str = ""   # "%"-spec + verb, e.g. "v" or "04d"
    text: str = ""   # pre-rendered (const)
    segs: tuple = ()


@dataclass(frozen=True)
class MsgPlan:
    segments: tuple          # len(witnesses) + 1 constant fmt pieces
    witnesses: tuple         # of Witness
    details: Any             # plain constant (shared across results)
    conditions: frozenset    # program_exactness output


def _parse_fmt(fmt: str):
    """Split a sprintf fmt into (segments, [(spec, verb)]) with the
    exact scan bi_sprintf uses (%% folds into the literal segment)."""
    segs = []
    verbs = []
    cur = []
    i, n = 0, len(fmt)
    while i < n:
        c = fmt[i]
        if c != "%":
            cur.append(c)
            i += 1
            continue
        if i + 1 < n and fmt[i + 1] == "%":
            cur.append("%")
            i += 2
            continue
        j = i + 1
        while j < n and fmt[j] in "+-# 0123456789.":
            j += 1
        if j >= n:
            return None  # trailing %: let the exact path raise
        segs.append("".join(cur))
        cur = []
        verbs.append((fmt[i + 1: j], fmt[j]))
        i = j + 1
    segs.append("".join(cur))
    return segs, verbs


def _const_term_value(t):
    """Plain Python value of a constant AST literal, or _REJECT."""
    if isinstance(t, A.Scalar):
        return t.value
    if isinstance(t, A.ArrayLit):
        out = []
        for x in t.items:
            v = _const_term_value(x)
            if v is _REJECT:
                return _REJECT
            out.append(v)
        return out
    if isinstance(t, A.ObjectLit):
        out = {}
        for k, v in t.items:
            kk = _const_term_value(k)
            vv = _const_term_value(v)
            if kk is _REJECT or vv is _REJECT or not isinstance(kk, str):
                return _REJECT
            out[kk] = vv
        return out
    if isinstance(t, A.SetLit):
        return _REJECT  # sets as details never appear; keep exact
    return _REJECT


_REJECT = object()


def _input_path(t, binds, depth=0):
    """Resolve a term to ("review"|"params", segs-of-field-names) by
    following static refs and var bindings; None when not a plain
    scalar input path."""
    if depth > 16:
        return None
    if isinstance(t, A.Var):
        rhs = binds.get(t.name)
        if rhs is None:
            return None
        return _input_path(rhs, binds, depth + 1)
    if not isinstance(t, A.Ref):
        return None
    segs: list = []
    base = t.base
    for a in t.args:
        if not (isinstance(a, A.Scalar) and isinstance(a.value, str)):
            return None
        segs.append(a.value)
    if isinstance(base, A.Var):
        if base.name == "input":
            if not segs:
                return None
            if segs[0] == "review":
                return ("review", tuple(segs[1:]))
            if segs[0] == "parameters":
                return ("params", tuple(segs[1:]))
            return None
        head = _input_path(base, binds, depth + 1)
        if head is None:
            return None
        return (head[0], head[1] + tuple(segs))
    if isinstance(base, A.Ref):
        head = _input_path(base, binds, depth + 1)
        if head is None:
            return None
        return (head[0], head[1] + tuple(segs))
    return None


def _iter_path(t, binds, taken_vars, depth=0):
    """Resolve a set-comprehension element ref to ("review", segs) where
    segs mixes ("f", name) fields and ("iter",) iteration points. The
    iteration vars must be wildcards or vars unused anywhere else
    (uncorrelated — `taken_vars` holds every var the rest of the rule
    mentions)."""
    if depth > 16:
        return None
    if isinstance(t, A.Var):
        rhs = binds.get(t.name)
        if rhs is None:
            return None
        return _iter_path(rhs, binds, taken_vars, depth + 1)
    if not isinstance(t, A.Ref):
        return None
    if isinstance(t.base, A.Var) and t.base.name == "input":
        head: tuple = ()
        args = list(t.args)
        if not args or not (isinstance(args[0], A.Scalar)
                            and args[0].value == "review"):
            return None
        args = args[1:]
    else:
        base = _iter_path(t.base, binds, taken_vars, depth + 1)
        if base is None or base[0] != "review":
            return None
        head = base[1]
        args = list(t.args)
    segs: list = list(head)
    seen_iter_vars: set = set()
    for a in args:
        if isinstance(a, A.Scalar) and isinstance(a.value, str):
            segs.append(("f", a.value))
        elif isinstance(a, A.Var):
            nm = a.name
            if not nm.startswith("$wc"):
                if nm in taken_vars or nm in seen_iter_vars:
                    return None  # correlated/bound var: keep exact
                seen_iter_vars.add(nm)
            segs.append(("iter",))
        else:
            return None
    return ("review", tuple(segs))


def _total_const(t) -> bool:
    """Can this binding rhs never be undefined? (const literals only —
    anything else keeps the template on the exact path)"""
    return _const_term_value(t) is not _REJECT


def _needed_vars(rule):
    from .compile import _needed_vars as nv

    return nv(rule)


def _rule_plan(rule: A.Rule, conditions: set):
    """MsgPlan for one violation rule, or None."""
    head = rule.key
    if not isinstance(head, A.ObjectLit):
        return None
    msg_term = None
    details = {}
    for k, v in head.items:
        if not (isinstance(k, A.Scalar) and isinstance(k.value, str)):
            return None
        if k.value == "msg":
            msg_term = v
        elif k.value == "details":
            details = _const_term_value(v)
            if details is _REJECT:
                return None
        else:
            return None
    if msg_term is None:
        return None
    binds: dict[str, Any] = {}
    for lit in rule.body:
        e = lit.expr
        if lit.negated or lit.withs:
            continue
        if isinstance(e, (A.Assign, A.Unify)) and isinstance(e.lhs, A.Var):
            if e.lhs.name in binds:
                return None  # double binding: unification, keep exact
            binds[e.lhs.name] = e.rhs
    # resolve msg through bindings to a sprintf call / plain string
    msg_chain: set = set()
    t = msg_term
    for _ in range(16):
        if isinstance(t, A.Var):
            if t.name not in binds:
                return None
            msg_chain.add(t.name)
            t = binds[t.name]
            continue
        break
    if isinstance(t, A.Scalar) and isinstance(t.value, str):
        segments: tuple = (t.value,)
        verbs: list = []
        args: list = []
    elif isinstance(t, A.Call) and tuple(t.fn) == ("sprintf",) and \
            len(t.args) == 2 and isinstance(t.args[0], A.Scalar) and \
            isinstance(t.args[0].value, str) and \
            isinstance(t.args[1], A.ArrayLit):
        parsed = _parse_fmt(t.args[0].value)
        if parsed is None:
            return None
        seg_list, verbs = parsed
        args = list(t.args[1].items)
        if len(verbs) != len(args):
            return None
        segments = tuple(seg_list)
    else:
        return None
    # vars the rule mentions OUTSIDE comprehension bodies (for the
    # comprehension-correlation check): an iteration var of a witness
    # set comprehension must not be captured from the enclosing clause
    # — comprehension-LOCAL vars are locally scoped and safe
    taken: set = set()
    for lit in rule.body:
        e = lit.expr
        if isinstance(e, (A.Assign, A.Unify)) and \
                isinstance(e.lhs, A.Var) and e.lhs.name in msg_chain:
            continue
        _collect_outer_vars(e, taken)
    witnesses: list = []
    for (spec, verb), arg in zip(verbs, args):
        w = _witness_for(arg, binds, taken, msg_chain, spec, verb)
        if w is None:
            return None
        witnesses.append(w)
    # totality: every skipped (neither guard-needed nor msg-chain)
    # binding must be provably defined — an undefined head-only binding
    # fails the clause in the interpreter while the device still fires
    needed = _needed_vars(rule)
    for name, rhs in binds.items():
        if name in needed or name in msg_chain or name.startswith("$wc"):
            continue
        if not _total_const(rhs):
            return None
    return MsgPlan(segments=segments, witnesses=tuple(witnesses),
                   details=details, conditions=frozenset(conditions))


def _collect_outer_vars(t, out: set) -> None:
    """_collect_vars, but comprehensions are opaque: their heads and
    bodies bind locally and never capture an iteration var INTO the
    enclosing clause."""
    if isinstance(t, (A.ArrayCompr, A.SetCompr, A.ObjectCompr)):
        return
    if isinstance(t, A.Var):
        out.add(t.name)
    elif isinstance(t, A.Ref):
        _collect_outer_vars(t.base, out)
        for a in t.args:
            _collect_outer_vars(a, out)
    elif isinstance(t, A.Call):
        for a in t.args:
            _collect_outer_vars(a, out)
    elif isinstance(t, A.BinOp):
        _collect_outer_vars(t.lhs, out)
        _collect_outer_vars(t.rhs, out)
    elif isinstance(t, A.UnaryMinus):
        _collect_outer_vars(t.term, out)
    elif isinstance(t, (A.ArrayLit, A.SetLit)):
        for x in t.items:
            _collect_outer_vars(x, out)
    elif isinstance(t, A.ObjectLit):
        for k, v in t.items:
            _collect_outer_vars(k, out)
            _collect_outer_vars(v, out)
    elif isinstance(t, (A.Assign, A.Unify)):
        _collect_outer_vars(t.lhs, out)
        _collect_outer_vars(t.rhs, out)


def _witness_for(arg, binds, taken, msg_chain, spec, verb):
    # resolve var indirection (collect into msg_chain so totality
    # checking knows these bindings are definedness-handled here)
    t = arg
    for _ in range(16):
        if isinstance(t, A.Var) and t.name in binds:
            msg_chain.add(t.name)
            t = binds[t.name]
            continue
        break
    v = _const_term_value(t)
    if v is not _REJECT:
        try:
            return Witness(kind="const",
                           text=bi_sprintf("%" + spec + verb,
                                           (freeze(v),)))
        except BuiltinError:
            return None
    if isinstance(t, A.SetCompr):
        if verb not in ("v", "s") or spec:
            return None
        if not isinstance(t.head, A.Var):
            return None
        hv = t.head.name
        body = [lit for lit in t.body
                if not isinstance(lit.expr, A.SomeDecl)]
        if len(body) != 1 or body[0].negated or body[0].withs:
            return None
        e = body[0].expr
        if not isinstance(e, (A.Assign, A.Unify)):
            return None
        if isinstance(e.lhs, A.Var) and e.lhs.name == hv:
            ref = e.rhs
        elif isinstance(e.rhs, A.Var) and e.rhs.name == hv:
            ref = e.lhs
        else:
            return None
        p = _iter_path(ref, binds, taken)
        if p is None:
            return None
        return Witness(kind="rowset", spec=spec + verb, segs=p[1])
    p = _input_path(t, binds)
    if p is None:
        return None
    if p[0] == "params":
        return Witness(kind="param", spec=spec + verb, segs=p[1])
    if verb not in ("v", "s") or spec:
        # numeric verbs need a number witness; veto-by-kind can't
        # distinguish "%d of a string" errors — keep the exact path
        return None
    return Witness(kind="row", spec=spec + verb, segs=p[1])


def plan_messages(module: A.Module, program: Program) -> Optional[MsgPlan]:
    """The template's message plan, or None when any violation rule's
    head falls outside the vectorizable subset (per-axis witnesses,
    helper-function msgs, non-const details, inexact device filter)."""
    conditions = program_exactness(program)
    if conditions is None:
        return None
    plans = []
    for rule in module.rules:
        if rule.name != "violation":
            continue
        p = _rule_plan(rule, conditions)
        if p is None:
            return None
        plans.append(p)
    if not plans:
        return None
    # multiple clauses must share ONE plan: the device verdict is their
    # OR, so distinct messages per clause are not attributable
    first = plans[0]
    for p in plans[1:]:
        if p != first:
            return None
    return first


# ----------------------------------------------------------- witnesses


def _descend(node, segs):
    for s in segs:
        if not isinstance(node, dict):
            return _REJECT
        node = node.get(s, _REJECT)
        if node is _REJECT:
            return _REJECT
    return node


def _collect_set(node, segs, i, out: list) -> None:
    while i < len(segs) and segs[i][0] == "f":
        if not isinstance(node, dict):
            return
        node = node.get(segs[i][1], _REJECT)
        if node is _REJECT:
            return
        i += 1
    if i == len(segs):
        out.append(node)
        return
    # segs[i] is ("iter",)
    if isinstance(node, dict):
        kids = node.values()
    elif isinstance(node, (list, tuple)):
        kids = node
    else:
        return
    for v in kids:
        _collect_set(v, segs, i + 1, out)


def build_row_witness(reviews: list, w: Witness):
    """-> (U-array of rendered strings, veto bool array) for one row
    witness over the review list. Built once per (witness, data
    revision) and fancy-indexed per firing pair thereafter."""
    n = len(reviews)
    strs: list = [""] * n
    veto = np.zeros(n, dtype=bool)
    if w.kind == "row":
        segs = w.segs
        for i, review in enumerate(reviews):
            v = _descend(review, segs)
            if isinstance(v, str) and len(v) <= MAX_WITNESS_STRLEN:
                strs[i] = v
            else:
                veto[i] = True
    else:  # rowset
        fmt = "%" + w.spec
        for i, review in enumerate(reviews):
            vals: list = []
            _collect_set(review, w.segs, 0, vals)
            try:
                s = bi_sprintf(fmt, (frozenset(freeze(v) for v in vals),))
            except (BuiltinError, TypeError):
                veto[i] = True
                continue
            if len(s) <= MAX_WITNESS_STRLEN:
                strs[i] = s
            else:
                veto[i] = True
    if n:
        arr = np.array(strs, dtype=str)
    else:
        arr = np.zeros(0, dtype="U1")
    return arr, veto


def render_param_witness(w: Witness, frozen_params) -> Optional[str]:
    """Per-constraint witness string, or None when the path is
    undefined (the msg binding then fails: the constraint column emits
    no violations at all)."""
    from ..utils.values import FrozenDict

    v = frozen_params
    for s in w.segs:
        if not isinstance(v, FrozenDict):
            return None
        if s not in v:
            return None
        v = v[s]
    try:
        return bi_sprintf("%" + w.spec, (v,))
    except BuiltinError:
        return None


def check_conditions(program: Program, conditions, cons: list) -> bool:
    """Evaluate the plan's runtime exactness conditions against the
    actual constraint set."""
    if not conditions:
        return True
    by_slot = {s.slot: s for s in program.param_slots}
    for kind, slot in conditions:
        if kind != "pval_scalar":
            return False
        spec = by_slot.get(slot)
        if spec is None:
            return False
        for c in cons:
            cspec = c.get("spec")
            cspec = cspec if isinstance(cspec, dict) else {}
            params = cspec.get("parameters") or {}
            nodes = [params]
            for seg in spec.segs:
                nxt = []
                for nd in nodes:
                    if seg.kind == "field":
                        if isinstance(nd, dict) and seg.name in nd:
                            nxt.append(nd[seg.name])
                    else:
                        if isinstance(nd, (list, tuple)):
                            nxt.extend(nd)
                        elif isinstance(nd, dict):
                            nxt.extend(nd.values())
                nodes = nxt
            if any(isinstance(v, (dict, list, tuple)) for v in nodes):
                return False
    return True
