"""AOT-serialized device programs: kill the XLA cold start.

The worst number in the repo is time-to-first-audit: every restart used
to re-pay the XLA compilation of each template's sweep programs (~10-120s
at audit scale) that the reference OPA interpreter line never pays. The
persistent XLA compilation cache (ir/driver.enable_compile_cache) already
removes the *compiler* time on a warm machine, but still re-traces, re-
lowers, and round-trips every program through the cache on each boot.

This module closes the rest of the gap:

  * ``AotStore`` — an on-disk store of *serialized compiled executables*
    (jax.experimental.serialize_executable), keyed by (program
    fingerprint, jit tag + static config, argument shape signature,
    backend/topology, jax version). A warm boot deserializes the exact
    device program in ~0.1s instead of recompiling it. The store also
    persists the driver's *warm sweep signatures* per program
    fingerprint, so a restarted process knows — before the first sweep —
    which shapes are deserialize-and-go and dispatches them on the
    device immediately.
  * ``AotJit`` — a drop-in wrapper for ``jax.jit`` used by
    CompiledTemplate/JoinCompiled: per argument-shape signature it first
    tries the store (source="aot"), then lowers+compiles, classifying
    the compile as a persistent-XLA-cache hit (source="cache") or a
    cold compile (source="fresh") via jax's cache-hit monitoring events.
    Compiles are timed into the shared PhaseTimers ("compile" phase, so
    audit traces gain a compile stage) and reported through
    ``gatekeeper_tpu_compile_{seconds,total}{source,outcome}``.

Everything here is best-effort: a store that cannot serialize (backend
without executable serialization support, unwritable volume, version
skew) degrades to plain ``jax.jit`` + the persistent XLA cache — never
an error on the serving path. Entries are only trusted when the RESOLVED
program fingerprint matches (interned string ids are embedded in the
program constants, so a vocab mismatch changes the fingerprint and
safely misses).
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import pickle
import threading
import time
from collections import deque
from typing import Any, Optional

log = logging.getLogger("gatekeeper_tpu.ir.aot")


class WouldCompile(Exception):
    """Raised by AotJit instead of compiling while a no_inline_compile()
    scope is active: the caller promised this dispatch would be
    deserialize-and-go (a warm-boot-adopted sweep signature), so a store
    miss must bounce back to the host-fallback/background-warm path
    rather than stall the serving thread on XLA."""


_guard = threading.local()


class no_inline_compile:
    """Context manager: within the scope, an AotJit that cannot answer
    from its in-memory/on-disk executables raises WouldCompile instead
    of lowering+compiling inline. Thread-local (background warm threads
    keep compiling freely)."""

    def __enter__(self):
        self._prev = getattr(_guard, "active", False)
        _guard.active = True
        return self

    def __exit__(self, *exc):
        _guard.active = self._prev
        return False

# global fresh/cache/aot counters, readable by tests and bench runs that
# span several drivers in one process (the per-store stats reset with
# the store object)
COMPILE_COUNTS = {"aot": 0, "cache": 0, "fresh": 0, "error": 0}
_counts_lock = threading.Lock()

_cache_events = {"hits": 0, "misses": 0}
_monitor_registered = False


def _register_monitor() -> None:
    """Count jax persistent-compilation-cache hits via the monitoring
    events jax emits around every backend compile; AotJit diffs the hit
    counter to label a compile "cache" vs "fresh"."""
    global _monitor_registered
    if _monitor_registered:
        return
    _monitor_registered = True
    try:
        from jax._src import monitoring

        def cb(event, **kw):
            if event.endswith("/cache_hits"):
                _cache_events["hits"] += 1
            elif event.endswith("/cache_misses"):
                _cache_events["misses"] += 1

        monitoring.register_event_listener(cb)
    except Exception:  # pragma: no cover - older jax without monitoring
        pass


def xla_cache_hits() -> int:
    return _cache_events["hits"]


def _report_compile(source: str, outcome: str, seconds: float) -> None:
    with _counts_lock:
        COMPILE_COUNTS[source if outcome == "ok" else "error"] = \
            COMPILE_COUNTS.get(source if outcome == "ok" else "error",
                               0) + 1
    try:
        from ..control.metrics import report_compile

        report_compile(source, outcome, seconds)
    except Exception:  # metrics backend optional in embedders
        pass


def arg_sig(args: tuple) -> tuple:
    """Canonical, hashable, cross-process-stable shape signature of a
    jit call's arguments: the flattened leaves' (shape, dtype) plus the
    treedef structure. Two processes computing the same signature get
    byte-identical keys (dict orders are insertion-deterministic in the
    extraction/encoding pipelines)."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(args)
    sig = tuple(
        (tuple(int(d) for d in getattr(a, "shape", ())),
         str(getattr(a, "dtype", type(a).__name__)))
        for a in leaves)
    return (sig, str(treedef))


def _jsonable(x):
    if isinstance(x, tuple):
        return [_jsonable(v) for v in x]
    return x


def _detuple(x):
    if isinstance(x, list):
        return tuple(_detuple(v) for v in x)
    return x


def program_fingerprint(program: Any, kind: str = "") -> str:
    """Fingerprint of a RESOLVED Program (resolve_consts already ran):
    interned string/row/number ids are embedded in the constants, so two
    processes only share a fingerprint when their vocab assignments for
    the program's constants match — exactly the condition under which a
    serialized executable is reusable."""
    body = repr(program).encode()
    return hashlib.sha256(kind.encode() + b"\x00" + body).hexdigest()


class AotStore:
    """Disk store of serialized executables + warm sweep signatures.

    Layout (under ``set_dir``'s path, itself normally
    ``<state-dir>/aot``):

        <dir>/<platform>-d<ndev>-jax<version>/
            manifest.jsonl          append-only: program entries + sigs
            <key>.aotx              pickled (payload, in_tree, out_tree)

    The platform subdir keys the whole store by backend + device count +
    jax version: executables never deserialize across any of those."""

    MANIFEST = "manifest.jsonl"
    # per-fingerprint warm-sig cap: sigs are tiny, but a churn-heavy
    # deployment must not grow them forever (oldest dropped first)
    MAX_SIGS_PER_FP = 256

    def __init__(self, path: Optional[str] = None):
        import os as _os

        self.dir: Optional[str] = None
        self._lock = threading.Lock()
        # fingerprint -> insertion-ordered {sig: None} (dict-as-set)
        self._sigs: dict[str, dict] = {}
        # fingerprint -> list of {"tag","static","asig","file"}
        self._entries: dict[str, list] = {}
        self._known_files: set = set()
        # global FIFO of (fingerprint, file) for bounded eviction:
        # template edits change the fingerprint, so without a cap stale
        # programs' .aotx blobs would accumulate on the state volume
        # forever; oldest-first eviction retires them
        self._order: list = []
        self.max_programs = int(_os.environ.get(
            "GATEKEEPER_TPU_AOT_MAX_PROGRAMS", "512"))
        self.stats = {"aot": 0, "cache": 0, "fresh": 0, "error": 0,
                      "aot_seconds": 0.0, "compile_seconds": 0.0}
        # per-kind recent compile events for /debug/templates
        self._events: dict[str, deque] = {}
        # tags whose executables this backend cannot serialize (e.g.
        # SPMD mesh programs on some runtimes): per-tag, so one broken
        # program class never disables the store for the rest
        self._serialize_broken: set = set()
        # prepack mode (the warm-cache CLI): when a compile answered by
        # the persistent XLA cache yields an unserializable executable,
        # recompile with the cache disabled to mint a durable entry —
        # worth full compile time offline, never on the serving path
        self.force_durable = False
        if path:
            self.set_dir(path)

    # ------------------------------------------------------------ config

    @property
    def enabled(self) -> bool:
        return self.dir is not None

    def _platform_key(self) -> str:
        import jax

        return (f"{jax.default_backend()}-d{len(jax.devices())}"
                f"-jax{jax.__version__}")

    def set_dir(self, path: str) -> bool:
        """Point the store at a directory (idempotent); loads the
        manifest. Returns False (store stays disabled) when the
        directory is unusable — a read-only volume must degrade to the
        plain jit path, not break serving."""
        _register_monitor()
        try:
            full = os.path.join(path, self._platform_key())
            os.makedirs(full, exist_ok=True)
            # probe writability once: os.makedirs succeeds on an
            # existing dir of a read-only volume
            probe = os.path.join(full, f".probe.{os.getpid()}")
            with open(probe, "w") as f:
                f.write("")
            os.unlink(probe)
        except OSError as e:
            log.warning("AOT program cache disabled (dir unusable): "
                        "%s: %s", path, e)
            return False
        with self._lock:
            self.dir = full
            self._load_manifest()
        log.info("AOT program cache at %s: %d serialized programs, "
                 "%d warm sweep signatures",
                 full, sum(len(v) for v in self._entries.values()),
                 sum(len(v) for v in self._sigs.values()))
        try:
            from ..control.metrics import report_aot_store

            report_aot_store(True, self.programs_count())
        except Exception:  # metrics backend optional in embedders
            pass
        return True

    def programs_count(self) -> int:
        with self._lock:
            return sum(len(v) for v in self._entries.values())

    # ---------------------------------------------------------- manifest

    def _load_manifest(self) -> None:
        self._sigs.clear()
        self._entries.clear()
        self._known_files.clear()
        self._order.clear()
        path = os.path.join(self.dir, self.MANIFEST)
        try:
            with open(path) as f:
                lines = f.readlines()
        except OSError:
            return
        dropped = 0
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
                fp = rec["fp"]
                if rec.get("t") == "sig":
                    self._sigs.setdefault(
                        fp, {})[_detuple(rec["sig"])] = None
                elif rec.get("t") == "entry":
                    fn = rec["file"]
                    if fn in self._known_files:
                        continue
                    # an entry whose blob vanished (evicted by another
                    # process, manual cleanup) is dead weight
                    if not os.path.exists(os.path.join(self.dir, fn)):
                        dropped += 1
                        continue
                    self._known_files.add(fn)
                    self._entries.setdefault(fp, []).append({
                        "tag": rec["tag"],
                        "static": _detuple(rec["static"]),
                        "asig": _detuple(rec["asig"]),
                        "file": fn,
                    })
                    self._order.append((fp, fn))
            except Exception:
                continue  # torn tail line of a crashed writer
        self._evict_over_cap()
        live = len(self._order) + sum(len(v) for v in self._sigs.values())
        # the manifest is append-only between boots: compact it when
        # dead lines (duplicate sigs, evicted/vanished entries) dominate
        if dropped or len(lines) > 2 * live + 64:
            self._compact()

    def _evict_over_cap(self) -> None:
        """Retire oldest serialized programs beyond max_programs (FIFO:
        stale fingerprints from template edits age out first). Caller
        holds the lock (or is single-threaded in set_dir)."""
        evicted = False
        while self.max_programs > 0 and len(self._order) > \
                self.max_programs:
            fp, fn = self._order.pop(0)
            self._known_files.discard(fn)
            ents = self._entries.get(fp, [])
            self._entries[fp] = [e for e in ents if e["file"] != fn]
            if not self._entries[fp]:
                self._entries.pop(fp, None)
                self._sigs.pop(fp, None)
            try:
                os.unlink(os.path.join(self.dir, fn))
            except OSError:
                pass
            evicted = True
        if evicted:
            self._compact()

    def _compact(self) -> None:
        """Rewrite the manifest from in-memory state (atomic): drops
        evicted/vanished entries and duplicate sig lines so the
        append-only file can't grow without bound across boots."""
        path = os.path.join(self.dir, self.MANIFEST)
        tmp = path + f".tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                for fp, sigs in self._sigs.items():
                    for sig in sigs:
                        f.write(json.dumps(
                            {"t": "sig", "fp": fp,
                             "sig": _jsonable(sig)},
                            separators=(",", ":")) + "\n")
                for fp, fn in self._order:
                    ent = next((e for e in self._entries.get(fp, ())
                                if e["file"] == fn), None)
                    if ent is None:
                        continue
                    f.write(json.dumps(
                        {"t": "entry", "fp": fp, "tag": ent["tag"],
                         "static": _jsonable(ent["static"]),
                         "asig": _jsonable(ent["asig"]),
                         "file": fn}, separators=(",", ":")) + "\n")
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except OSError as e:
            log.warning("AOT manifest compaction failed: %s", e)
            try:
                os.unlink(tmp)
            except OSError:
                pass

    def _append_manifest(self, rec: dict) -> None:
        try:
            with open(os.path.join(self.dir, self.MANIFEST), "a") as f:
                f.write(json.dumps(rec, separators=(",", ":")) + "\n")
                f.flush()
        except OSError as e:
            log.warning("AOT manifest append failed: %s", e)

    # ------------------------------------------------------- sweep sigs

    def record_sig(self, fingerprint: str, sig: tuple) -> None:
        """Persist one warm driver sweep signature: a later boot marks
        this (fingerprint, shape) warm before its first sweep and
        dispatches on the device (deserialize-and-go) immediately."""
        if not self.enabled:
            return
        with self._lock:
            have = self._sigs.setdefault(fingerprint, {})
            if sig in have:
                return
            have[sig] = None
            while len(have) > self.MAX_SIGS_PER_FP:
                have.pop(next(iter(have)))
            self._append_manifest(
                {"t": "sig", "fp": fingerprint, "sig": _jsonable(sig)})

    def sigs_for(self, fingerprint: str) -> set:
        with self._lock:
            return set(self._sigs.get(fingerprint, ()))

    def entries_for(self, fingerprint: str) -> list:
        with self._lock:
            return list(self._entries.get(fingerprint, ()))

    # ------------------------------------------------------ executables

    def entry_key(self, fingerprint: str, tag: str, static: tuple,
                  asig: tuple) -> str:
        h = hashlib.sha256(repr((fingerprint, tag, static,
                                 asig)).encode()).hexdigest()
        return h[:40]

    def load(self, key: str):
        """Deserialize one stored executable, or None. Any failure
        (missing, corrupt, version-skewed pickle) is a miss."""
        if not self.enabled:
            return None
        path = os.path.join(self.dir, key + ".aotx")
        try:
            with open(path, "rb") as f:
                payload, in_tree, out_tree = pickle.loads(f.read())
        except FileNotFoundError:
            return None
        except Exception as e:
            log.warning("AOT entry %s unreadable (recompiling): %s: %s",
                        key, type(e).__name__, e)
            return None
        try:
            from jax.experimental import serialize_executable as se

            return se.deserialize_and_load(payload, in_tree, out_tree)
        except Exception as e:
            log.warning("AOT entry %s failed to deserialize "
                        "(recompiling): %s: %s", key,
                        type(e).__name__, e)
            return None

    def save(self, key: str, compiled, fingerprint: str, tag: str,
             static: tuple, asig: tuple) -> bool:
        """Serialize + persist one compiled executable (atomic write).
        A program class (tag) that cannot serialize on this backend is
        marked broken after the first failure and skipped from then on
        (the persistent XLA cache remains the fallback for it)."""
        if not self.enabled or tag in self._serialize_broken:
            return False
        try:
            from jax.experimental import serialize_executable as se

            payload = se.serialize(compiled)
        except Exception as e:
            self._serialize_broken.add(tag)
            log.warning("executable serialization unsupported for %r "
                        "programs here (falling back to the persistent "
                        "XLA cache for them): %s: %s", tag,
                        type(e).__name__, e)
            return False
        try:
            # round-trip probe BEFORE persisting: an executable that XLA
            # itself loaded from its persistent compilation cache can
            # serialize to a payload missing its kernel symbols (observed
            # on the CPU thunk runtime: deserialize dies with "Symbols
            # not found"). A corrupt entry would poison every warm boot,
            # so only entries proven to deserialize are stored; the
            # persistent XLA cache remains the fallback for the rest.
            se.deserialize_and_load(*payload)
        except Exception as e:
            log.debug("AOT entry for %s/%s not persisted (payload fails "
                      "round-trip; the persistent XLA cache still covers "
                      "this program): %s: %s", fingerprint[:12],
                      tag, type(e).__name__, e)
            return False
        blob = pickle.dumps(payload, protocol=pickle.HIGHEST_PROTOCOL)
        path = os.path.join(self.dir, key + ".aotx")
        tmp = path + f".tmp.{os.getpid()}"
        try:
            with open(tmp, "wb") as f:
                f.write(blob)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except OSError as e:
            log.warning("AOT entry write failed: %s", e)
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return False
        with self._lock:
            if key + ".aotx" not in self._known_files:
                self._known_files.add(key + ".aotx")
                self._entries.setdefault(fingerprint, []).append({
                    "tag": tag, "static": static, "asig": asig,
                    "file": key + ".aotx"})
                self._order.append((fingerprint, key + ".aotx"))
                self._append_manifest({
                    "t": "entry", "fp": fingerprint, "tag": tag,
                    "static": _jsonable(static),
                    "asig": _jsonable(asig), "file": key + ".aotx"})
                self._evict_over_cap()
        return True

    # ---------------------------------------------------- observability

    def note(self, source: str, seconds: float, kind: str = "",
             tag: str = "", key: tuple = (),
             outcome: str = "ok") -> None:
        with self._lock:
            if outcome == "ok":
                self.stats[source] = self.stats.get(source, 0) + 1
                sec_key = ("aot_seconds" if source == "aot"
                           else "compile_seconds")
                self.stats[sec_key] = self.stats.get(sec_key, 0.0) \
                    + seconds
            else:
                self.stats["error"] = self.stats.get("error", 0) + 1
            ev = self._events.setdefault(kind or "?", deque(maxlen=8))
            ev.append({"tag": tag, "source": source,
                       "seconds": round(seconds, 3),
                       "outcome": outcome,
                       "bucket_key": repr(key)})
        _report_compile(source, outcome, seconds)

    def events_for(self, kind: str) -> list:
        with self._lock:
            return list(self._events.get(kind, ()))

    def stats_snapshot(self) -> dict:
        with self._lock:
            out = dict(self.stats)
        out["enabled"] = self.enabled
        out["dir"] = self.dir
        return out


class AotJit:
    """``jax.jit`` with a persistent executable cache behind it.

    Call semantics are identical to the wrapped jit. Per argument-shape
    signature, the call resolves (once) to a compiled executable:
    store hit -> deserialize ("aot"); miss -> lower+compile ("cache"
    when the persistent XLA cache answered, else "fresh") and persist.
    Executable-vs-argument mismatches (layout/committed-device skew)
    fall back to the plain jit permanently for that signature — the
    wrapper must never fail a call the jit would have served."""

    def __init__(self, fn, store: Optional[AotStore] = None,
                 fingerprint: str = "", tag: str = "",
                 static: tuple = (), kind: str = ""):
        import jax

        self._jit = jax.jit(fn)
        self._store = store
        self._fingerprint = fingerprint
        self._tag = tag
        self._static = tuple(static)
        self._kind = kind
        self._compiled: dict = {}
        self._lock = threading.Lock()

    # jax.jit API surface used elsewhere (profiling.compiled_hlo)
    def lower(self, *args, **kw):
        return self._jit.lower(*args, **kw)

    def ready(self, asig: tuple) -> bool:
        return asig in self._compiled

    def preload(self, asig: tuple, key: str) -> bool:
        """Deserialize a manifest entry into the in-memory cache without
        needing live arguments (ingest-time background prewarm)."""
        store = self._store
        if store is None or not store.enabled:
            return False
        with self._lock:
            if asig in self._compiled:
                return True
        t0 = time.monotonic()
        comp = store.load(key)
        if comp is None:
            return False
        with self._lock:
            self._compiled.setdefault(asig, comp)
        store.note("aot", time.monotonic() - t0, kind=self._kind,
                   tag=self._tag, key=self._static + (asig,))
        return True

    def __call__(self, *args):
        store = self._store
        if store is None or not store.enabled:
            # no store -> no warm-boot adoption is possible, so a
            # no_inline_compile scope can't be violated here
            return self._jit(*args)
        asig = arg_sig(args)
        ent = self._compiled.get(asig)
        if ent is None:
            ent = self._acquire(asig, args)
        if ent is self._jit:
            return ent(*args)
        try:
            return ent(*args)
        except Exception as e:
            # layout/type skew between the stored executable and the
            # live arguments: serve from the jit and stop consulting
            # the entry for this signature
            log.warning("AOT executable rejected live args for %s/%s "
                        "(falling back to jit): %s: %s", self._kind,
                        self._tag, type(e).__name__, e)
            with self._lock:
                self._compiled[asig] = self._jit
            return self._jit(*args)

    def _acquire(self, asig: tuple, args: tuple):
        from ..utils import profiling

        store = self._store
        key = store.entry_key(self._fingerprint, self._tag,
                              self._static, asig)
        t0 = time.monotonic()
        comp = store.load(key)
        if comp is not None:
            store.note("aot", time.monotonic() - t0, kind=self._kind,
                       tag=self._tag, key=self._static + (asig,))
            with self._lock:
                self._compiled.setdefault(asig, comp)
            return self._compiled[asig]
        if getattr(_guard, "active", False):
            # a no_inline_compile scope promised deserialize-and-go
            # (warm-boot-adopted signature) but the store can't answer:
            # bounce to the caller's host-fallback path, never stall
            # the serving thread on XLA
            raise WouldCompile(self._kind, self._tag)
        hits0 = xla_cache_hits()
        t0 = time.monotonic()
        try:
            with profiling.timers().phase("compile"):
                comp = self._jit.lower(*args).compile()
        except Exception as e:
            store.note("fresh", time.monotonic() - t0, kind=self._kind,
                       tag=self._tag, key=self._static + (asig,),
                       outcome="error")
            raise e
        dt = time.monotonic() - t0
        source = "cache" if xla_cache_hits() > hits0 else "fresh"
        store.note(source, dt, kind=self._kind, tag=self._tag,
                   key=self._static + (asig,))
        saved = store.save(key, comp, self._fingerprint, self._tag,
                           self._static, asig)
        if not saved and store.force_durable and source == "cache" \
                and self._tag not in store._serialize_broken:
            comp = self._mint_durable(store, key, asig, args) or comp
        with self._lock:
            self._compiled.setdefault(asig, comp)
        return self._compiled[asig]

    def _mint_durable(self, store: AotStore, key: str, asig: tuple,
                      args: tuple):
        """Prepack-only (store.force_durable): a compile the persistent
        XLA cache answered can serialize to a corrupt payload (save's
        round-trip probe refused it), so recompile with the cache
        disabled — a genuinely fresh executable round-trips — and
        persist that. Full compile time, paid offline by the warm-cache
        CLI so serving boots never have to."""
        import jax

        t0 = time.monotonic()
        try:
            # two process-wide caches would silently hand the same
            # unserializable executable back: jax memoizes (a) its
            # is-the-cache-usable decision the first time any compile
            # runs (so flipping the config alone is a no-op) and (b)
            # the compiled executable itself per (module, options) in
            # pxla's compilation LRU. Reset both around the flip —
            # offline-only cost, this path never runs while serving.
            from jax._src import compilation_cache as _cc
            from jax._src.interpreters import pxla as _pxla

            prev = jax.config.jax_enable_compilation_cache
            jax.config.update("jax_enable_compilation_cache", False)
            _cc.reset_cache()
            _pxla._cached_compilation.cache_clear()
            try:
                comp = self._jit.lower(*args).compile()
            finally:
                jax.config.update("jax_enable_compilation_cache", prev)
                _cc.reset_cache()
        except Exception as e:
            log.warning("durable recompile for %s/%s failed: %s: %s",
                        self._kind, self._tag, type(e).__name__, e)
            return None
        store.note("fresh", time.monotonic() - t0, kind=self._kind,
                   tag=self._tag, key=self._static + (asig,))
        store.save(key, comp, self._fingerprint, self._tag,
                   self._static, asig)
        return comp
