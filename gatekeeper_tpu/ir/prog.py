"""Vectorized policy IR.

A compiled template is a `Program`: extraction slots describing what to
pull out of each review object, parameter slots describing what to encode
per constraint, and clauses — tri-state boolean expressions over the
implicit axes N (objects) × C (constraints) plus small iteration axes
(container lists, label maps, parameter lists).

The device program answers ONE question per (object, constraint) pair:
"does at least one violation clause fire?" — the 99.99%-reject filter of
the audit/admission cross-product. Messages and details for firing pairs
are materialized host-side by the reference interpreter, which guarantees
exact parity with the reference's topdown semantics (regolib/src.go hook
join) while keeping strings off the device entirely.

Correctness invariant (enforced by differential tests): the compiled
filter must never UNDER-fire relative to the interpreter. Templates whose
rego falls outside the compilable subset fall back per-template to the
interpreter driver.

Value model on device (see ops/strtab.py): strings are interned int32 ids;
string predicates are [pattern, vocab] table lookups; numbers are f32;
value kinds are int8 codes so undefined-vs-false tri-state survives
vectorization (SURVEY.md §7 hard part 1).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Union

# value kind codes stored per extracted cell
K_ABSENT = 0
K_NULL = 1
K_FALSE = 2
K_TRUE = 3
K_NUM = 4
K_STR = 5
K_ARR = 6
K_OBJ = 7


# ------------------------------------------------------------------- slots


@dataclass(frozen=True)
class Seg:
    """One path segment: a fixed field or an iteration axis (iteration
    covers both arrays — integer keys — and objects — string keys)."""

    kind: str  # "field" | "iter"
    name: str = ""  # field name for "field"
    axis: str = ""  # axis id for "iter"


@dataclass(frozen=True)
class ObjSlotSpec:
    """What to extract from each review. root: "object" | "oldObject" |
    "review" (the review dict itself, for kind.kind etc.).

    mode:
      "scalar"  — value at path (last seg may be an axis -> [N,K] values)
      "entries" — map at path iterated: key ids + value cells [N,K]
      "count"   — number of children of the collection at path [N]
    """

    slot: int
    root: str
    segs: tuple  # of Seg
    mode: str = "scalar"


@dataclass(frozen=True)
class ParamSlotSpec:
    """What to encode per constraint from spec.parameters.

    segs address into the parameters document; a "list" seg iterates a
    parameter array (the P dim). mode "scalar" (P=1) or "list" [C,P] or
    "count".

    pattern_ops: string-match ops this slot's values are used as patterns
    for — the encoder interns a match-table row per (op, value) and stores
    row indices alongside ids (MatchLookup gathers them on device).
    """

    slot: int
    segs: tuple  # of Seg
    mode: str = "scalar"
    pattern_ops: tuple = ()


# ------------------------------------------------------------------ exprs


@dataclass(frozen=True)
class Expr:
    pass


@dataclass(frozen=True)
class OVal(Expr):
    """Object slot leaf. field: "id" | "num" | "kind" | "key" | "count".
    axis None -> scalar slot (K broadcast)."""

    slot: int
    f: str = "id"
    axis: Optional[str] = None


@dataclass(frozen=True)
class PVal(Expr):
    """Param slot leaf. field: "id" | "num" | "kind" | "count" | "row:<op>"."""

    slot: int
    f: str = "id"
    axis: Optional[str] = None


@dataclass(frozen=True)
class Const(Expr):
    kind: str  # "id" | "num" | "bool"
    value: Union[int, float, bool]


@dataclass(frozen=True)
class Cmp(Expr):
    """Comparison. dtype "id" (string equality) or "num". Defined iff both
    sides are defined with the right kind."""

    op: str  # eq ne lt le gt ge
    lhs: Expr
    rhs: Expr
    dtype: str = "num"


@dataclass(frozen=True)
class MatchLookup(Expr):
    """match_table[row, id] — string predicate against a pattern row."""

    row: Expr  # row index (PVal row:<op> or Const row)
    sid: Expr  # string id expr


@dataclass(frozen=True)
class DerivedVal(Expr):
    """derived_columns[col] gathered at the base cell's intern id (sid for
    strings, nid for numbers) — the device image of a pure unary function
    (canonify_cpu/canonify_mem, split parts, prefix strips) precomputed
    host-side over the vocab (ops/derived.py). Kind K_ABSENT where the
    function is undefined for that input."""

    col: int  # index into Program.derived
    base: Expr


@dataclass(frozen=True)
class KindIs(Expr):
    """cell.kind ∈ kinds, as a boolean (always defined)."""

    e: Expr
    kinds: tuple  # of int kind codes


@dataclass(frozen=True)
class Arith(Expr):
    """Numeric arithmetic over value intervals. Results are widened by an
    f32-rounding epsilon so threshold comparisons over-fire instead of
    under-firing (host re-check is exact)."""

    op: str  # "add" | "sub" | "mul"
    lhs: Expr
    rhs: Expr


@dataclass(frozen=True)
class Truthy(Expr):
    """Rego body-literal success of a value: defined and not false."""

    e: Expr


@dataclass(frozen=True)
class Exists(Expr):
    """Definedness of a value as a boolean (always defined itself)."""

    e: Expr


@dataclass(frozen=True)
class And(Expr):
    items: tuple


@dataclass(frozen=True)
class Or(Expr):
    items: tuple


@dataclass(frozen=True)
class Not(Expr):
    """Rego negation: succeeds when e is undefined or false. Axes listed in
    `local_axes` are existentially reduced inside the negation (wildcards
    first bound under `not`)."""

    e: Expr
    local_axes: tuple = ()


@dataclass(frozen=True)
class OrReduce(Expr):
    """∃ axis: presence(axis) ∧ e. Always defined (empty -> false)."""

    axis: str
    e: Expr


@dataclass(frozen=True)
class SumReduce(Expr):
    """Σ over axis of (presence ∧ e) as a number. Always defined."""

    axis: str
    e: Expr


# ------------------------------------------------------------------ clauses


@dataclass(frozen=True)
class Axis:
    """Iteration axis. kind "obj" (bound to an object slot's K dim) or
    "param" (a parameter list's P dim). presence comes from the owning
    slot's kind/cell masks."""

    name: str
    kind: str  # "obj" | "param"
    slot: int  # owning ObjSlotSpec.slot / ParamSlotSpec.slot


@dataclass(frozen=True)
class Guard:
    expr: Expr
    negated: bool = False


@dataclass(frozen=True)
class Clause:
    axes: tuple  # of Axis — positively-bound; reduced jointly at clause level
    guards: tuple  # of Guard


@dataclass(frozen=True)
class DerivedSpec:
    """One derived column the program needs. kind:
      "fn"           — arg = module function name, host-evaluated by the
                       interpreter per vocab entry
      "split"        — arg = "<sep>|<i>|<k>": part i of split(s, sep),
                       defined only when the split has exactly k parts
      "strip_prefix" — arg = prefix; s minus prefix, undefined otherwise
    """

    col: int
    kind: str
    arg: str


@dataclass(frozen=True)
class Program:
    """One compiled template."""

    kind: str  # template Kind (constraint kind)
    obj_slots: tuple  # of ObjSlotSpec
    param_slots: tuple  # of ParamSlotSpec
    clauses: tuple  # of Clause
    # every axis in the program (clause-level AND reduce-internal), by name
    axes: tuple = ()  # of Axis
    derived: tuple = ()  # of DerivedSpec
    # interpreted binary predicates: (match op name, module function name);
    # the driver registers each op with MatchTables before evaluation
    pred_ops: tuple = ()

    def axis_table(self) -> dict[str, Axis]:
        return {a.name: a for a in self.axes}
