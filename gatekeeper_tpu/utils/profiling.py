"""Profiling + compiler introspection.

The reference exposes pprof endpoints through controller-runtime; the
TPU-native analogs are (a) the XLA program itself — dump the HLO of any
compiled template to see exactly what the device executes — and (b)
jax.profiler traces viewable in TensorBoard/Perfetto for device
timelines. Host-side audit phases get a lightweight timer registry that
feeds the metrics exposition.
"""

from __future__ import annotations

import contextlib
import time
from typing import Optional


def compiled_hlo(ct, feats, params, table, derived=None,
                 stage: str = "hlo") -> str:
    """The compiled device program for one template's dense sweep.
    stage: "jaxpr" | "hlo" (StableHLO text) | "optimized" (post-XLA)."""
    import jax

    args = (feats, params, table, derived or {})
    if stage == "jaxpr":
        return str(jax.make_jaxpr(ct._eval)(*args))
    # lower through the template's own jit wrapper when it has one (an
    # AotJit — ir/aot.py — exposes .lower), so the rendered program is
    # the exact one the AOT store persists/serves; plain jax.jit is the
    # fallback for bare evaluators
    fn = getattr(ct, "_fn", None)
    lowered = (fn.lower(*args) if fn is not None and hasattr(fn, "lower")
               else jax.jit(ct._eval).lower(*args))
    if stage == "optimized":
        return lowered.compile().as_text()
    return lowered.as_text()


@contextlib.contextmanager
def device_trace(log_dir: str):
    """jax.profiler trace (TensorBoard/Perfetto) around a block:

        with device_trace("/tmp/gk-trace"):
            client.audit()
    """
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class PhaseTimers:
    """Named wall-clock phase accumulators (audit: encode/device_sweep/
    materialize/...), exposed via control.metrics + the trace layer.

    The driver's audit internals add() into the process-global timers()
    instance; the audit manager snapshots before/after a sweep and
    diffs, turning the per-sweep phase durations into trace spans and
    per-stage histograms — the attribution PAPER.md's per-package stats
    reporters provide in the reference line."""

    def __init__(self):
        import threading
        self._lock = threading.Lock()
        self.totals: dict[str, float] = {}
        self.counts: dict[str, int] = {}

    @contextlib.contextmanager
    def phase(self, name: str):
        t0 = time.monotonic()
        try:
            yield
        finally:
            self.add(name, time.monotonic() - t0)

    def add(self, name: str, seconds: float, n: int = 1) -> None:
        """Accumulate an externally-timed interval (slab pipelines time
        device-wait and materialize with two stopwatches inside one
        loop — a context manager per slab would mis-nest)."""
        with self._lock:
            self.totals[name] = self.totals.get(name, 0.0) + seconds
            self.counts[name] = self.counts.get(name, 0) + n

    def snapshot(self) -> dict[str, tuple[float, int]]:
        with self._lock:
            return {k: (self.totals[k], self.counts[k])
                    for k in self.totals}

    @staticmethod
    def diff(before: dict, after: dict) -> dict[str, float]:
        """Per-phase seconds accumulated between two snapshots."""
        out = {}
        for name, (total, _n) in after.items():
            delta = total - before.get(name, (0.0, 0))[0]
            if delta > 1e-9:
                out[name] = delta
        return out


_timers: Optional[PhaseTimers] = None


def timers() -> PhaseTimers:
    global _timers
    if _timers is None:
        _timers = PhaseTimers()
    return _timers
