"""Profiling + compiler introspection.

The reference exposes pprof endpoints through controller-runtime; the
TPU-native analogs are (a) the XLA program itself — dump the HLO of any
compiled template to see exactly what the device executes — and (b)
jax.profiler traces viewable in TensorBoard/Perfetto for device
timelines. Host-side audit phases get a lightweight timer registry that
feeds the metrics exposition.
"""

from __future__ import annotations

import contextlib
import time
from typing import Optional


def compiled_hlo(ct, feats, params, table, derived=None,
                 stage: str = "hlo") -> str:
    """The compiled device program for one template's dense sweep.
    stage: "jaxpr" | "hlo" (StableHLO text) | "optimized" (post-XLA)."""
    import jax

    args = (feats, params, table, derived or {})
    if stage == "jaxpr":
        return str(jax.make_jaxpr(ct._eval)(*args))
    lowered = jax.jit(ct._eval).lower(*args)
    if stage == "optimized":
        return lowered.compile().as_text()
    return lowered.as_text()


@contextlib.contextmanager
def device_trace(log_dir: str):
    """jax.profiler trace (TensorBoard/Perfetto) around a block:

        with device_trace("/tmp/gk-trace"):
            client.audit()
    """
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class PhaseTimers:
    """Named wall-clock phase accumulators (audit: match/sweep/
    materialize), exposed via control.metrics when wired."""

    def __init__(self):
        self.totals: dict[str, float] = {}
        self.counts: dict[str, int] = {}

    @contextlib.contextmanager
    def phase(self, name: str):
        t0 = time.time()
        try:
            yield
        finally:
            self.totals[name] = self.totals.get(name, 0.0) + \
                (time.time() - t0)
            self.counts[name] = self.counts.get(name, 0) + 1

    def snapshot(self) -> dict[str, tuple[float, int]]:
        return {k: (self.totals[k], self.counts[k]) for k in self.totals}


_timers: Optional[PhaseTimers] = None


def timers() -> PhaseTimers:
    global _timers
    if _timers is None:
        _timers = PhaseTimers()
    return _timers
