"""Lockset tracer: lock-order inversions, cycles, and held-across-
blocking events, recorded at runtime.

The static no-block checker (tools/gklint) proves blocking operations
aren't *reachable* from the no-block zones; this module watches what
threads actually *do* under the chaos and concurrency suites — the
runtime companion to the deadlock checker.

Armed via ``GATEKEEPER_TPU_LOCKTRACE=1`` (tests/conftest.py installs
it before any serving code constructs a lock): ``threading.Lock`` /
``threading.RLock`` are replaced with tracing wrappers that record,
per thread, the lock-acquisition-order graph keyed by each lock's
ALLOCATION SITE (file:line — all instances born on one line are one
node, so per-connection locks aggregate). On every acquisition taken
while other locks are held, the tracer adds held-site -> new-site
edges; an edge whose reverse path already exists is a lock-order
INVERSION (two threads can deadlock given the right interleaving —
the classic lockdep check). ``report()`` additionally runs a cycle
search over the whole graph, catching A->B->C->A orders no single
inversion edge shows. ``time.sleep`` is wrapped so a sleep while
holding any traced lock records a held-across-blocking event.

Findings append to ``GATEKEEPER_TPU_LOCKTRACE_OUT`` as JSONL the
moment they are recorded (inversions are detected at acquire time,
BEFORE any deadlock can wedge the process — so a SIGKILLed run still
leaves its evidence on disk; concurrent test processes share one
file), with a final flush at exit for report-time cycle findings.
``python -m tools.gklint --locktrace-report FILE`` turns the dump
into a CI verdict: cycles/inversions fail, held-across-blocking is
reported but advisory (bounded sleeps under a lock are a code smell,
not a deadlock).
"""

from __future__ import annotations

import atexit
import json
import os
import sys
import threading
import time
from typing import Optional

ENV = "GATEKEEPER_TPU_LOCKTRACE"
OUT_ENV = "GATEKEEPER_TPU_LOCKTRACE_OUT"

_ORIG_LOCK = threading.Lock
_ORIG_RLOCK = threading.RLock
_ORIG_SLEEP = time.sleep

_THIS_FILE = os.path.abspath(__file__)
_THREADING_FILE = os.path.abspath(threading.__file__)


def _alloc_site() -> str:
    """file:line of the frame that constructed the lock (first frame
    outside this module and threading.py)."""
    f = sys._getframe(2)
    while f is not None:
        fn = f.f_code.co_filename
        if os.path.abspath(fn) not in (_THIS_FILE, _THREADING_FILE):
            return f"{fn}:{f.f_lineno}"
        f = f.f_back
    return "<unknown>"


class LockTracer:
    """One tracing domain: the per-thread lockset, the site-order
    graph, and the findings list. Tests construct their own; the
    process-global one is installed by install()."""

    def __init__(self, out_path: Optional[str] = None):
        self._lock = _ORIG_LOCK()  # real lock: guards graph + findings
        self._tls = threading.local()
        # site -> set of sites acquired while it was held
        self.edges: dict[str, set] = {}
        self.findings: list[dict] = []
        self._seen: set = set()
        # incremental JSONL emission: findings append to out_path the
        # moment they are recorded, NOT only at exit — a deadlock that
        # WEDGES the process (SIGKILLed by the CI timeout, atexit
        # never runs) still leaves its inversion evidence on disk
        self.out_path = out_path
        self._emitted = 0

    def _flush_locked(self, path: Optional[str]) -> None:
        """Append findings not yet written (caller holds self._lock)."""
        if not path or self._emitted >= len(self.findings):
            return
        fresh = self.findings[self._emitted:]
        self._emitted = len(self.findings)
        try:
            with open(path, "a", encoding="utf-8") as f:
                for ent in fresh:
                    f.write(json.dumps(ent) + "\n")
        except OSError:
            pass  # tracing must never take the process down

    # ------------------------------------------------------- factories

    def lock(self):
        return _TracedLock(self, _ORIG_LOCK(), _alloc_site())

    def rlock(self):
        return _TracedRLock(self, _ORIG_RLOCK(), _alloc_site())

    # ------------------------------------------------------- recording

    def _held(self) -> list:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    def _note_acquired(self, site: str) -> None:
        held = self._held()
        new_edges = [(h, site) for h in held
                     if h != site]  # same-site nesting is one node
        held.append(site)
        if not new_edges:
            return
        with self._lock:
            for a, b in new_edges:
                peers = self.edges.setdefault(a, set())
                if b in peers:
                    continue
                peers.add(b)
                # reverse REACHABILITY at edge-add time: b ->* a means
                # two threads can now interleave into a deadlock
                if self._reachable(b, a):
                    key = ("inversion",) + tuple(sorted((a, b)))
                    if key not in self._seen:
                        self._seen.add(key)
                        self.findings.append({
                            "kind": "inversion",
                            "detail": f"lock order inverted: {a} -> "
                                      f"{b} observed while a {b} -> "
                                      f"{a} path already exists "
                                      f"(thread "
                                      f"{threading.current_thread().name})",
                            "sites": sorted((a, b)),
                        })
                        self._flush_locked(self.out_path)

    def _reachable(self, src: str, dst: str) -> bool:
        seen = set()
        stack = [src]
        while stack:
            cur = stack.pop()
            if cur == dst:
                return True
            if cur in seen:
                continue
            seen.add(cur)
            stack.extend(self.edges.get(cur, ()))
        return False

    def _note_released(self, site: str) -> None:
        held = self._held()
        # release order may not be LIFO: remove the newest matching
        for i in range(len(held) - 1, -1, -1):
            if held[i] == site:
                del held[i]
                return

    def note_blocking(self, what: str, where: str = "") -> None:
        """A blocking call is happening on this thread NOW; records a
        held-across-blocking event when any traced lock is held."""
        held = list(self._held())
        if not held:
            return
        key = ("held", what, tuple(held), where)
        with self._lock:
            if key in self._seen:
                return
            self._seen.add(key)
            self.findings.append({
                "kind": "held_across_blocking",
                "detail": f"{what} called at {where or '<unknown>'} "
                          f"while holding {held} (thread "
                          f"{threading.current_thread().name})",
                "sites": held,
            })
            self._flush_locked(self.out_path)

    # --------------------------------------------------------- results

    def report(self) -> list[dict]:
        """All findings, plus a fresh cycle search over the full order
        graph (catches A->B->C->A that no single edge-add flagged as a
        2-party inversion)."""
        with self._lock:
            out = list(self.findings)
            cycles = self._find_cycles()
            for cyc in cycles:
                key = ("cycle", tuple(cyc))
                if key not in self._seen:
                    self._seen.add(key)
                    ent = {"kind": "cycle",
                           "detail": "lock-order cycle: "
                                     + " -> ".join(cyc + [cyc[0]]),
                           "sites": cyc}
                    self.findings.append(ent)
                    out.append(ent)
            return out

    def _find_cycles(self) -> list[list[str]]:
        cycles: list[list[str]] = []
        seen_cycles: set = set()
        WHITE, GRAY, BLACK = 0, 1, 2
        color = {n: WHITE for n in self.edges}

        def dfs(node: str, path: list[str]) -> None:
            color[node] = GRAY
            path.append(node)
            for nxt in sorted(self.edges.get(node, ())):
                if color.get(nxt, WHITE) == GRAY:
                    i = path.index(nxt)
                    cyc = path[i:]
                    # canonical rotation so one cycle reports once
                    k = cyc.index(min(cyc))
                    canon = tuple(cyc[k:] + cyc[:k])
                    if canon not in seen_cycles:
                        seen_cycles.add(canon)
                        cycles.append(list(canon))
                elif color.get(nxt, WHITE) == WHITE:
                    dfs(nxt, path)
            path.pop()
            color[node] = BLACK

        for node in sorted(self.edges):
            if color.get(node, WHITE) == WHITE:
                dfs(node, [])
        return cycles

    def dump(self, path: Optional[str] = None) -> None:
        findings = self.report()
        if not findings:
            return
        path = path or self.out_path
        if path:
            with self._lock:
                self._flush_locked(path)  # whatever is not yet on disk
        else:
            sys.stderr.write("=== gatekeeper_tpu locktrace findings "
                             "===\n" + "".join(
                                 json.dumps(f) + "\n"
                                 for f in findings))


class _TracedLock:
    """threading.Lock wrapper recording acquisition order by the
    lock's allocation site."""

    __slots__ = ("_tracer", "_real", "site")

    def __init__(self, tracer: LockTracer, real, site: str):
        self._tracer = tracer
        self._real = real
        self.site = site

    def acquire(self, blocking: bool = True, timeout: float = -1):
        got = self._real.acquire(blocking, timeout)
        if got:
            self._tracer._note_acquired(self.site)
        return got

    def release(self):
        self._real.release()
        self._tracer._note_released(self.site)

    def locked(self):
        return self._real.locked()

    def __getattr__(self, name):
        # pass-through for private protocol attrs the stdlib pokes at
        # (e.g. concurrent.futures registers _at_fork_reinit with
        # os.register_at_fork). Attrs the real lock lacks raise
        # naturally, so Condition's Lock-vs-RLock feature probing
        # still distinguishes the two.
        if name == "_real":  # slot unset mid-construction: no recursion
            raise AttributeError(name)
        return getattr(self._real, name)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False


class _TracedRLock(_TracedLock):
    """RLock wrapper. Also implements the private Condition protocol
    (_release_save / _acquire_restore / _is_owned) so Condition.wait's
    full-release keeps the per-thread lockset honest."""

    __slots__ = ()

    def _release_save(self):
        state = self._real._release_save()
        self._tracer._note_released(self.site)
        return state

    def _acquire_restore(self, state):
        self._real._acquire_restore(state)
        self._tracer._note_acquired(self.site)

    def _is_owned(self):
        return self._real._is_owned()


# ------------------------------------------------------ global install

_TRACER: Optional[LockTracer] = None
_installed = False
_ATEXIT_REGISTERED = False


def tracer() -> Optional[LockTracer]:
    return _TRACER


def armed() -> bool:
    return os.environ.get(ENV, "") not in ("", "0", "false")


def note_blocking(what: str) -> None:
    """Hook for blocking-call wrappers (the patched time.sleep)."""
    t = _TRACER
    if t is not None:
        f = sys._getframe(2)
        t.note_blocking(what, f"{f.f_code.co_filename}:{f.f_lineno}"
                        if f else "")


def install(force: bool = False) -> Optional[LockTracer]:
    """Patch threading.Lock/RLock (and time.sleep) with tracing
    wrappers. No-op unless GATEKEEPER_TPU_LOCKTRACE=1 (or force).
    Locks created BEFORE install stay untraced — call early."""
    global _TRACER, _installed
    if _installed:
        return _TRACER
    if not (force or armed()):
        return None
    t = LockTracer(out_path=os.environ.get(OUT_ENV) or None)
    _TRACER = t
    _installed = True

    def make_lock():
        return _TracedLock(t, _ORIG_LOCK(), _alloc_site())

    def make_rlock():
        return _TracedRLock(t, _ORIG_RLOCK(), _alloc_site())

    threading.Lock = make_lock
    threading.RLock = make_rlock

    def traced_sleep(secs):
        note_blocking("time.sleep")
        return _ORIG_SLEEP(secs)

    time.sleep = traced_sleep
    global _ATEXIT_REGISTERED
    if not _ATEXIT_REGISTERED:
        # once per process: the hook resolves the CURRENT tracer at
        # exit, so an uninstall/re-install cycle (tests) neither
        # stacks duplicate hooks nor dumps through a dead tracer
        _ATEXIT_REGISTERED = True
        atexit.register(
            lambda: _TRACER.dump() if _TRACER is not None else None)
    return t


def uninstall() -> None:
    """Restore the patched factories (tests)."""
    global _TRACER, _installed
    threading.Lock = _ORIG_LOCK
    threading.RLock = _ORIG_RLOCK
    time.sleep = _ORIG_SLEEP
    _TRACER = None
    _installed = False
