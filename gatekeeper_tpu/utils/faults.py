"""Fault injection for chaos testing.

Named injection points are compiled into the serving paths (kube writes,
watch subscriptions, device evaluation, the micro-batch flusher) and are
ZERO-COST when no fault is armed: `fire()` returns on a plain dict lookup.
Arming happens programmatically (the chaos suite), via the
GATEKEEPER_TPU_FAULTS environment variable, or the --fault-injection
flag — the production entrypoint accepts storms so operators can game-day
a staging cluster with the exact binary they deploy.

Spec syntax (env/flag), comma-separated:

    point:mode[:param][@rate][#count]

    kube.write:error:503            every guarded kube write fails 503
    kube.write:error:503@0.5#20     ... with probability 0.5, 20 times
    kube.watch:error                watch subscriptions fail (poll path)
    eval.device:raise               device eval raises (quarantine path)
    webhook.flush:sleep:2           each micro-batch flush stalls 2s
    state.snapshot:corrupt          snapshot files corrupt on disk
    state.snapshot:truncate#1       one snapshot file torn mid-write
    kube.lease:steal                leader lease stolen by a rival
    kube.lease:expire               leader misses renews; lease lapses
    backplane.engine:error          frontends cannot reach the engine
                                    (answer per the failure stance)

Injection points in the tree (grep for faults.fire / faults.consume):
    kube.write     control/resilience.py  GuardedKube mutating verbs
    kube.watch     control/resilience.py  GuardedKube.watch subscribe
    eval.device    ir/driver.py           compiled-template device eval
    webhook.flush  control/webhook.py     MicroBatcher._flush entry
    state.snapshot control/statestore.py  snapshot save/load (modes:
                   io-error -> the I/O call raises; truncate/corrupt ->
                   the on-disk file is torn / bit-flipped so the next
                   restore must fall back to the cold path)
    kube.lease     control/kube.py        LeaseElector tick (modes:
                   steal -> a rival identity takes the lease; expire ->
                   our renews stop landing and the lease lapses;
                   error -> the renew API call fails)
    backplane.engine control/backplane.py BackplaneClient.call — the
                   frontend->engine forward path (raise/error -> the
                   engine is unreachable and the frontend answers per
                   the fail-open/closed stance; sleep -> a slow
                   backplane)
    backplane.wire control/backplane.py    _send_frame — the wire
                   itself (modes: reset -> the socket closes mid-frame;
                   truncate -> a partial frame is written then the
                   socket closes; slow -> the frame drips out in small
                   chunks with delays, holding the peer's read loop)
    state.disk     control/statestore.py   _write_atomic (modes:
                   enospc/eio -> the write raises OSError as if the
                   state dir ran out of space / the device errored)
    kube.list      control/kube.py         FakeKube.list — apiserver
                   flap (error param carries the HTTP code: 410 forces
                   relist storms, 429 rate-limit storms)
"""

from __future__ import annotations

import os
import random
import threading
import time
from typing import Any, Callable, Optional


class FaultError(Exception):
    """Default exception raised at an armed point (sites that need a
    typed error — e.g. KubeError with an HTTP code — translate it)."""

    def __init__(self, point: str, param: Optional[str] = None):
        super().__init__(f"injected fault at {point}"
                         + (f" ({param})" if param else ""))
        self.point = point
        self.param = param

    def code(self, default: int = 503) -> int:
        try:
            return int(self.param)
        except (TypeError, ValueError):
            return default


class _Spec:
    __slots__ = ("point", "mode", "param", "rate", "count", "sleep_s",
                 "exc", "match")

    def __init__(self, point: str, mode: str, param: Optional[str],
                 rate: float, count: Optional[int], sleep_s: float,
                 exc: Optional[Callable[[], BaseException]],
                 match: Optional[dict]):
        self.point = point
        self.mode = mode          # "raise" | "error" | "sleep"
        self.param = param
        self.rate = rate
        self.count = count        # remaining fires; None = unlimited
        self.sleep_s = sleep_s
        self.exc = exc            # factory overriding the default
        self.match = match        # ctx subset that must equal fire()'s


class FaultInjector:
    """Thread-safe registry of armed faults + per-point fire counters."""

    def __init__(self):
        self._lock = threading.Lock()
        self._specs: dict[str, _Spec] = {}
        self._fired: dict[str, int] = {}

    # ------------------------------------------------------------- arming

    def inject(self, point: str, mode: str = "raise",
               param: Optional[str] = None, rate: float = 1.0,
               count: Optional[int] = None, sleep_s: float = 0.0,
               exc: Optional[Callable[[], BaseException]] = None,
               match: Optional[dict] = None) -> None:
        with self._lock:
            self._specs[point] = _Spec(point, mode, param, rate, count,
                                       sleep_s, exc, match)

    def clear(self, point: Optional[str] = None) -> None:
        with self._lock:
            if point is None:
                self._specs.clear()
            else:
                self._specs.pop(point, None)

    def reset(self) -> None:
        """Disarm everything and zero the counters (test isolation)."""
        with self._lock:
            self._specs.clear()
            self._fired.clear()

    def configure(self, spec_text: str) -> None:
        """Arm faults from the flag/env spec syntax (module docstring)."""
        for part in (spec_text or "").split(","):
            part = part.strip()
            if not part:
                continue
            count = None
            if "#" in part:
                part, _, c = part.rpartition("#")
                count = int(c)
            rate = 1.0
            if "@" in part:
                part, _, r = part.rpartition("@")
                rate = float(r)
            fields = part.split(":")
            point = fields[0]
            mode = fields[1] if len(fields) > 1 else "raise"
            param = fields[2] if len(fields) > 2 else None
            sleep_s = float(param) if mode == "sleep" and param else 1.0
            self.inject(point, mode=mode, param=param, rate=rate,
                        count=count, sleep_s=sleep_s)

    # ---------------------------------------------------------- reporting

    def fired(self, point: str) -> int:
        with self._lock:
            return self._fired.get(point, 0)

    def armed(self) -> list[str]:
        with self._lock:
            return sorted(self._specs)

    def armed_snapshot(self) -> dict[str, dict]:
        """Full armed-state snapshot for /debug/chaos: point -> the
        spec's observable fields (mode, param, rate, remaining count).
        An aborted schedule reports which faults were still pending."""
        with self._lock:
            return {
                point: {
                    "mode": spec.mode,
                    "param": spec.param,
                    "rate": spec.rate,
                    "count": spec.count,
                }
                for point, spec in sorted(self._specs.items())
            }

    def fired_snapshot(self) -> dict[str, int]:
        """All per-point fire counters (points that fired at least
        once), for the /debug/chaos ledger."""
        with self._lock:
            return dict(sorted(self._fired.items()))

    # ------------------------------------------------------------- firing

    def fire(self, point: str, **ctx: Any) -> None:
        """Called at an injection point; no-op unless armed. An armed
        "sleep" fault stalls the caller; "raise"/"error" raise the
        injected exception (FaultError carrying the param when no
        factory was given)."""
        if not self._specs:  # hot path: nothing armed anywhere
            return
        with self._lock:
            spec = self._specs.get(point)
            if spec is None:
                return
            if spec.match and any(ctx.get(k) != v
                                  for k, v in spec.match.items()):
                return
            if spec.rate < 1.0 and random.random() >= spec.rate:
                return
            if spec.count is not None:
                if spec.count <= 0:
                    return
                spec.count -= 1
                if spec.count == 0:
                    self._specs.pop(point, None)
            self._fired[point] = self._fired.get(point, 0) + 1
            sleep_s = spec.sleep_s if spec.mode == "sleep" else 0.0
            exc = None
            if spec.mode in ("raise", "error"):
                exc = spec.exc() if spec.exc is not None else \
                    FaultError(point, spec.param)
        if sleep_s:
            time.sleep(sleep_s)
        if exc is not None:
            raise exc

    def consume(self, point: str, **ctx: Any) -> Optional[tuple]:
        """Site-interpreted firing: instead of raising, return the armed
        `(mode, param)` for the caller to act on (file corruption, lease
        theft — behaviors only the call site can simulate), or None when
        nothing is armed. Respects rate/count/match and increments the
        fire counter exactly like fire()."""
        if not self._specs:
            return None
        with self._lock:
            spec = self._specs.get(point)
            if spec is None:
                return None
            if spec.match and any(ctx.get(k) != v
                                  for k, v in spec.match.items()):
                return None
            if spec.rate < 1.0 and random.random() >= spec.rate:
                return None
            if spec.count is not None:
                if spec.count <= 0:
                    return None
                spec.count -= 1
                if spec.count == 0:
                    self._specs.pop(point, None)
            self._fired[point] = self._fired.get(point, 0) + 1
            return (spec.mode, spec.param)


FAULTS = FaultInjector()

_env_spec = os.environ.get("GATEKEEPER_TPU_FAULTS")
if _env_spec:
    FAULTS.configure(_env_spec)


def fire(point: str, **ctx: Any) -> None:
    FAULTS.fire(point, **ctx)


def consume(point: str, **ctx: Any) -> Optional[tuple]:
    return FAULTS.consume(point, **ctx)
