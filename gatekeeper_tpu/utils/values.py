"""Immutable Rego value model.

Rego documents are JSON values plus sets. The interpreter (rego/interp.py)
and the vectorizing compiler (ir/) both operate on *frozen* values so they
can be hashed into sets, used as object keys, and interned into device
vocabularies (ops/strtab.py).

Representation:
  null    -> None
  bool    -> bool
  number  -> int | float  (ints kept exact, matching OPA's arbitrary precision
             for the magnitudes k8s policies use, e.g. mem_multiple("Ei"))
  string  -> str
  array   -> tuple
  object  -> FrozenDict
  set     -> frozenset

Reference semantics being mirrored: the OPA value model in
vendor/github.com/open-policy-agent/opa/ast/term.go (types Null, Boolean,
Number, String, Array, Object, Set) and its canonical sort ordering used by
sprintf("%v") output of sets.
"""

from __future__ import annotations

from typing import Any


class FrozenDict(dict):
    """Hashable, immutable dict used for Rego objects."""

    __slots__ = ("_hash",)

    def __hash__(self):  # type: ignore[override]
        h = getattr(self, "_hash", None)
        if h is None:
            h = hash(frozenset(self.items()))
            object.__setattr__(self, "_hash", h)
        return h

    def _immutable(self, *a, **k):
        raise TypeError("FrozenDict is immutable")

    __setitem__ = _immutable
    __delitem__ = _immutable
    clear = _immutable
    pop = _immutable
    popitem = _immutable
    setdefault = _immutable
    update = _immutable

    def __reduce__(self):
        # dict subclasses normally pickle by reconstruct-then-setitem,
        # which the immutability guard rejects; rebuild through the
        # constructor instead (the state-snapshot blob path pickles
        # whole frozen inventory trees)
        return (FrozenDict, (dict(self),))


def freeze(v: Any) -> Any:
    """Deep-freeze a JSON-ish Python value into the Rego value model."""
    if isinstance(v, dict):
        return FrozenDict((freeze(k), freeze(x)) for k, x in v.items())
    if isinstance(v, (list, tuple)):
        return tuple(freeze(x) for x in v)
    if isinstance(v, (set, frozenset)):
        return frozenset(freeze(x) for x in v)
    if isinstance(v, float) and v.is_integer() and abs(v) < 2**53:
        # json numbers like 2.0 canonicalize to ints, as OPA's ast.Number does
        return int(v)
    return v


def thaw(v: Any) -> Any:
    """Convert a frozen value back to plain JSON-able Python (sets -> sorted lists)."""
    if isinstance(v, FrozenDict):
        return {thaw(k): thaw(x) for k, x in v.items()}
    if isinstance(v, tuple):
        return [thaw(x) for x in v]
    if isinstance(v, frozenset):
        return [thaw(x) for x in sorted(v, key=sort_key)]
    return v


# OPA canonical type order: null < bool < number < string < var < ref < array
# < object < set (ast/compare.go). We only need the value types.
_TYPE_RANK = {
    type(None): 0,
    bool: 1,
    int: 2,
    float: 2,
    str: 3,
    tuple: 4,
    FrozenDict: 5,
    frozenset: 6,
}


def type_name(v: Any) -> str:
    if v is None:
        return "null"
    if isinstance(v, bool):
        return "boolean"
    if isinstance(v, (int, float)):
        return "number"
    if isinstance(v, str):
        return "string"
    if isinstance(v, tuple):
        return "array"
    if isinstance(v, FrozenDict):
        return "object"
    if isinstance(v, frozenset):
        return "set"
    raise TypeError(f"not a rego value: {type(v)!r}")


def sort_key(v: Any):
    """Total-order sort key across heterogeneous Rego values."""
    r = _TYPE_RANK[type(v)]
    if r == 0:
        return (0, 0)
    if r == 1:
        return (1, int(v))
    if r == 2:
        return (2, float(v))
    if r == 3:
        return (3, v)
    if r == 4:
        return (4, tuple(sort_key(x) for x in v))
    if r == 5:
        return (5, tuple(sorted((sort_key(k), sort_key(x)) for k, x in v.items())))
    return (6, tuple(sorted(sort_key(x) for x in v)))


def rego_eq(a: Any, b: Any) -> bool:
    """Type-aware equality: booleans never equal numbers (unlike Python)."""
    if isinstance(a, bool) != isinstance(b, bool):
        return False
    return a == b


def format_value(v: Any, top: bool = True) -> str:
    """Go fmt `%v`-style rendering as OPA's sprintf produces it.

    Top-level strings print bare; nested strings are quoted; sets print as
    {elem, ...} in canonical order; objects as {"k": v, ...}. Mirrors message
    output of e.g. `sprintf("you must provide labels: %v", [missing])` in
    library/general/requiredlabels/src.rego.
    """
    if v is None:
        return "null"
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, (int, float)):
        if isinstance(v, float):
            if v.is_integer():
                return str(int(v))
            return repr(v)
        return str(v)
    if isinstance(v, str):
        if top:
            return v
        return '"' + v.replace("\\", "\\\\").replace('"', '\\"') + '"'
    if isinstance(v, tuple):
        return "[" + ", ".join(format_value(x, top=False) for x in v) + "]"
    if isinstance(v, frozenset):
        items = sorted(v, key=sort_key)
        return "{" + ", ".join(format_value(x, top=False) for x in items) + "}"
    if isinstance(v, FrozenDict):
        items = sorted(v.items(), key=lambda kv: sort_key(kv[0]))
        return (
            "{"
            + ", ".join(
                f"{format_value(k, top=False)}: {format_value(x, top=False)}"
                for k, x in items
            )
            + "}"
        )
    raise TypeError(f"not a rego value: {type(v)!r}")
