from .client import Backend, Client
from .drivers import Driver, RegoDriver
from .templates import CONSTRAINT_GROUP, ConstraintTemplate, load_template
from .types import (
    ClientError,
    MissingTemplateError,
    Response,
    Responses,
    Result,
    UnrecognizedConstraintError,
)

__all__ = [
    "Backend",
    "Client",
    "CONSTRAINT_GROUP",
    "ConstraintTemplate",
    "ClientError",
    "Driver",
    "load_template",
    "MissingTemplateError",
    "RegoDriver",
    "Response",
    "Responses",
    "Result",
    "UnrecognizedConstraintError",
]
