"""Validation result types.

Shape parity with the reference constraint framework's
types package (vendor/.../constraint/pkg/types/validation.go:11-99):
Result carries msg/metadata/constraint/review/resource/enforcement action,
Response groups results per target with optional trace/input dumps, and
Responses aggregates per-target responses for a Review/Audit call.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional


@dataclass(slots=True)
class Result:
    msg: str = ""
    metadata: dict = field(default_factory=dict)
    # The constraint (unstructured dict) that was violated.
    constraint: Optional[dict] = None
    # The review object (gkReview-shaped dict) that produced the violation.
    review: Any = None
    # The violating resource, re-extracted from the review by the target
    # handler (reference pkg/target/target.go:193-244 HandleViolation).
    resource: Optional[dict] = None
    enforcement_action: str = "deny"

    def to_dict(self) -> dict:
        return {
            "msg": self.msg,
            "metadata": self.metadata,
            "constraint": self.constraint,
            "enforcementAction": self.enforcement_action,
        }


@dataclass
class Response:
    trace: Optional[str] = None
    input: Optional[str] = None
    target: str = ""
    results: list[Result] = field(default_factory=list)

    def trace_dump(self) -> str:
        parts = []
        if self.trace is not None:
            parts.append(f"Trace:\n{self.trace}")
        if self.input is not None:
            parts.append(f"Input:\n{self.input}")
        parts.append(f"Target: {self.target}")
        for r in self.results:
            parts.append(f"Result:\n{r.to_dict()}")
        return "\n\n".join(parts)


@dataclass
class Responses:
    by_target: dict[str, Response] = field(default_factory=dict)
    handled: dict[str, bool] = field(default_factory=dict)

    def results(self) -> list[Result]:
        out: list[Result] = []
        for _, resp in sorted(self.by_target.items()):
            out.extend(resp.results)
        return out

    def trace_dump(self) -> str:
        return "\n\n".join(
            resp.trace_dump() for _, resp in sorted(self.by_target.items())
        )


class ErrorMap(dict):
    """target name -> error; raised/returned alongside partial Responses."""

    def __str__(self) -> str:
        return "\n".join(f"{k}: {v}" for k, v in sorted(self.items()))


class ClientError(Exception):
    pass


class MissingTemplateError(ClientError):
    pass


class UnrecognizedConstraintError(ClientError):
    def __init__(self, kind: str):
        super().__init__(f"Constraint kind {kind} is not recognized")
        self.kind = kind
