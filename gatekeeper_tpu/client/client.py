"""The constraint-framework Client: orchestration core of the framework.

Behavior parity with the reference Client
(vendor/.../constraint/pkg/client/client.go): template/constraint CRUD with
semantic-equal dedupe, data CRUD routed through target handlers,
Review/Audit queries through the Driver seam, CRD generation/validation,
and Reset. Targets and templates are cached so constraints can be validated
without the driver.

Differences by design (TPU-first):
  * modules are parsed+rewritten ASTs, not source strings — template
    ingestion does not recompile unrelated modules (the reference's
    local driver recompiles the world per change, local.go:168-207);
  * driver data paths are tuples, so no URL escaping / path joining.
"""

from __future__ import annotations

import copy
import json
import threading
from typing import Any, Iterable, Optional, Union

from .crd import CRDError, create_crd, create_schema, validate_cr, validate_crd
from .drivers import Driver, hook_audit_path, hook_violation_path
from .rewriter import RewriteError, rewrite_template_modules
from .templates import (
    CONSTRAINT_GROUP,
    ConstraintTemplate,
    TemplateError,
    load_template,
)
from .types import (
    ClientError,
    ErrorMap,
    MissingTemplateError,
    Response,
    Responses,
    UnrecognizedConstraintError,
)


class Backend:
    """Driver holder + client factory (reference backend.go:28-49: one
    client per backend)."""

    def __init__(self, driver: Driver):
        self.driver = driver
        self._has_client = False

    def new_client(self, targets: Iterable[Any],
                   allowed_data_fields: tuple = ("inventory",)) -> "Client":
        if self._has_client:
            raise ClientError("backend already has a client")
        self._has_client = True
        client = Client(self.driver, targets, allowed_data_fields)
        client.init()
        return client


class _TemplateEntry:
    def __init__(self, template: ConstraintTemplate, crd: dict, targets: list[str]):
        self.template = template
        self.crd = crd
        self.targets = targets
        self.constraints: dict[str, dict] = {}  # name -> unstructured


class Client:
    def __init__(self, driver: Driver, targets: Iterable[Any],
                 allowed_data_fields: tuple = ("inventory",)):
        self.driver = driver
        self.targets = {t.get_name(): t for t in targets}
        if not self.targets:
            raise ClientError("client must have at least one target")
        self.allowed_data_fields = allowed_data_fields
        self._lock = threading.RLock()
        self._templates: dict[str, _TemplateEntry] = {}  # by Kind
        # library generation: bumped whenever anything a review's verdict
        # can depend on changes (templates, constraints, synced data).
        # The admission decision cache keys on it, so a template or
        # constraint update invalidates every cached decision at once
        # without an explicit flush. Semantic-equal dedupes do NOT bump —
        # a level-triggered controller replaying identical CRs must not
        # cold the cache.
        self._generation = 0
        # library-change observer (the N-engine admission plane's
        # replication hook): called AFTER a mutation applied and bumped
        # this client's generation, with (op, plain object) — ops:
        # add_template / remove_template / add_constraint /
        # remove_constraint / add_data / remove_data. Semantic-equal
        # dedupes do not notify (nothing changed, nothing to fan out).
        # Each replica client bumps ITS OWN generation when the op
        # lands there, so every engine's decision-cache keys stay
        # coherent with that engine's library.
        self.on_change: Optional[Any] = None

    def _notify(self, op: str, obj) -> None:
        """Run the observer OUTSIDE the client lock (it does I/O to the
        engine processes); a replication failure is the supervisor's to
        heal (resync), never an ingestion error."""
        cb = self.on_change
        if cb is None or obj is None:
            return
        try:
            cb(op, obj)
        except Exception:
            import logging

            logging.getLogger("gatekeeper_tpu.client").warning(
                "library change notification failed", exc_info=True)

    @property
    def generation(self) -> int:
        with self._lock:
            return self._generation

    def _bump_generation(self) -> None:
        with self._lock:
            self._generation += 1

    def init(self) -> None:
        self.driver.init()

    # ------------------------------------------------------------ templates

    def _load(self, templ: Union[dict, ConstraintTemplate]) -> ConstraintTemplate:
        if isinstance(templ, ConstraintTemplate):
            return templ
        try:
            return load_template(templ)
        except TemplateError as e:
            raise ClientError(str(e)) from None

    def _artifacts(self, ct: ConstraintTemplate):
        if len(ct.targets) != 1:
            raise ClientError(
                f"expected exactly 1 item in targets, got {len(ct.targets)}"
            )
        tspec = ct.targets[0]
        handler = self.targets.get(tspec.target)
        if handler is None:
            raise ClientError(f"target {tspec.target} is not recognized")
        schema = create_schema(ct, handler.match_schema())
        crd = create_crd(ct, schema)
        try:
            validate_crd(crd)
        except CRDError as e:
            raise ClientError(f"invalid CRD for template {ct.name}: {e}") from None
        try:
            modules = rewrite_template_modules(
                tspec.target, ct.kind, tspec.rego, tspec.libs,
                allowed_externs=self.allowed_data_fields,
                source_name=f"template:{ct.name}",
            )
        except RewriteError as e:
            raise ClientError(str(e)) from None
        return handler, crd, modules

    def create_crd(self, templ: Union[dict, ConstraintTemplate]) -> dict:
        ct = self._load(templ)
        _, crd, _ = self._artifacts(ct)
        return crd

    def add_template(self, templ: Union[dict, ConstraintTemplate]) -> Responses:
        ct = self._load(templ)
        resp = Responses()
        with self._lock:
            cached = self._templates.get(ct.kind)
            if cached is not None and cached.template.semantic_equal(ct):
                for t in cached.targets:
                    resp.handled[t] = True
                return resp
            handler, crd, modules = self._artifacts(ct)
            if cached is not None:
                # a template may switch targets; scrub the old target's
                # modules and constraint data so it stops enforcing
                for old_target in cached.targets:
                    if old_target != handler.get_name():
                        self.driver.delete_modules(
                            self._module_prefix(old_target, ct.kind))
                        self.driver.delete_data(
                            ("constraints", old_target, "cluster",
                             CONSTRAINT_GROUP, ct.kind))
            prefix = self._module_prefix(handler.get_name(), ct.kind)
            self.driver.put_modules(prefix, modules)
            entry = _TemplateEntry(ct, crd, [handler.get_name()])
            if cached is not None:
                entry.constraints = cached.constraints
            self._templates[ct.kind] = entry
            resp.handled[handler.get_name()] = True
            self._generation += 1
        self._notify("add_template",
                     templ if isinstance(templ, dict) else ct.raw)
        return resp

    def remove_template(self, templ: Union[dict, ConstraintTemplate]) -> Responses:
        ct = self._load(templ)
        resp = Responses()
        with self._lock:
            entry = self._templates.pop(ct.kind, None)
            if entry is None:
                return resp
            for target in entry.targets:
                self.driver.delete_modules(self._module_prefix(target, ct.kind))
                # drop the template's constraint instances from the store
                self.driver.delete_data(
                    ("constraints", target, "cluster", CONSTRAINT_GROUP, ct.kind)
                )
                resp.handled[target] = True
            self._generation += 1
        self._notify("remove_template",
                     templ if isinstance(templ, dict) else ct.raw)
        return resp

    def get_template(self, kind_or_templ: Union[str, dict, ConstraintTemplate]
                     ) -> ConstraintTemplate:
        kind = kind_or_templ if isinstance(kind_or_templ, str) else \
            self._load(kind_or_templ).kind
        with self._lock:
            entry = self._templates.get(kind)
            if entry is None:
                raise MissingTemplateError(f"template for kind {kind} not found")
            return copy.deepcopy(entry.template)

    def _module_prefix(self, target: str, kind: str) -> str:
        return f'templates["{target}"]["{kind}"]'

    # ----------------------------------------------------------- constraints

    def _entry_for_constraint(self, constraint: dict) -> _TemplateEntry:
        kind = constraint.get("kind") or ""
        if not kind:
            raise ClientError(
                f"Constraint {(constraint.get('metadata') or {}).get('name')} "
                "has no kind"
            )
        group = (constraint.get("apiVersion") or "").partition("/")[0]
        if group != CONSTRAINT_GROUP:
            raise ClientError(
                f"Constraint {(constraint.get('metadata') or {}).get('name')} "
                "has the wrong group"
            )
        entry = self._templates.get(kind)
        if entry is None:
            raise UnrecognizedConstraintError(kind)
        return entry

    def _constraint_path(self, target: str, constraint: dict) -> tuple:
        name = (constraint.get("metadata") or {}).get("name") or ""
        if not name:
            raise ClientError("constraint has no name")
        return ("constraints", target, "cluster", CONSTRAINT_GROUP,
                constraint["kind"], name)

    def add_constraint(self, constraint: dict) -> Responses:
        resp = Responses()
        errs = ErrorMap()
        with self._lock:
            entry = self._entry_for_constraint(constraint)
            name = (constraint.get("metadata") or {}).get("name") or ""
            cached = entry.constraints.get(name)
            if cached is not None and _constraint_semantic_equal(cached, constraint):
                for t in entry.targets:
                    resp.handled[t] = True
                return resp
            self._validate_constraint_locked(constraint, entry)
            for target in entry.targets:
                try:
                    self.driver.put_data(
                        self._constraint_path(target, constraint), constraint
                    )
                    resp.handled[target] = True
                except Exception as e:  # driver errors surface per target
                    errs[target] = e
            if not errs:
                entry.constraints[name] = copy.deepcopy(constraint)
                self._generation += 1
        if errs:
            raise ClientError(str(errs))
        self._notify("add_constraint", constraint)
        return resp

    def remove_constraint(self, constraint: dict) -> Responses:
        resp = Responses()
        with self._lock:
            entry = self._entry_for_constraint(constraint)
            name = (constraint.get("metadata") or {}).get("name") or ""
            for target in entry.targets:
                self.driver.delete_data(self._constraint_path(target, constraint))
                resp.handled[target] = True
            entry.constraints.pop(name, None)
            self._generation += 1
        self._notify("remove_constraint", constraint)
        return resp

    def get_constraint(self, kind: str, name: str) -> dict:
        with self._lock:
            entry = self._templates.get(kind)
            if entry is None:
                raise UnrecognizedConstraintError(kind)
            c = entry.constraints.get(name)
            if c is None:
                raise ClientError(f"constraint {kind}/{name} not found")
            return copy.deepcopy(c)

    def validate_constraint(self, constraint: dict) -> None:
        """Validate without mutating state (webhook path, client.go:655-659)."""
        with self._lock:
            entry = self._entry_for_constraint(constraint)
            self._validate_constraint_locked(constraint, entry)

    def _validate_constraint_locked(self, constraint: dict,
                                    entry: _TemplateEntry) -> None:
        try:
            validate_cr(constraint, entry.crd)
        except CRDError as e:
            raise ClientError(str(e)) from None
        for target in entry.targets:
            self.targets[target].validate_constraint(constraint)

    # ----------------------------------------------------------------- data

    def add_data(self, obj: Any) -> Responses:
        resp = Responses()
        errs = ErrorMap()
        for name, handler in self.targets.items():
            try:
                handled, path, data = handler.process_data(obj)
            except Exception as e:
                errs[name] = e
                continue
            if not handled:
                continue
            try:
                self.driver.put_data(("external", name) + tuple(path), data)
                resp.handled[name] = True
            except Exception as e:
                errs[name] = e
        if resp.handled:
            # synced inventory feeds referential policies: a data change
            # can flip a cached verdict, so it invalidates like a
            # constraint change (clusters without sync never pay this)
            self._bump_generation()
            self._notify("add_data", obj)
        if errs:
            raise ClientError(str(errs))
        return resp

    def remove_data(self, obj: Any) -> Responses:
        resp = Responses()
        errs = ErrorMap()
        for name, handler in self.targets.items():
            try:
                handled, path, _ = handler.process_data(obj)
            except Exception as e:
                errs[name] = e
                continue
            if not handled:
                continue
            try:
                self.driver.delete_data(("external", name) + tuple(path))
                resp.handled[name] = True
            except Exception as e:
                errs[name] = e
        if resp.handled:
            self._bump_generation()
            self._notify("remove_data", obj)
        if errs:
            raise ClientError(str(errs))
        return resp

    # -------------------------------------------------------------- queries

    def review(self, obj: Any, tracing: bool = False) -> Responses:
        with self._lock:
            return self._review_locked(obj, tracing)

    def _review_locked(self, obj: Any, tracing: bool) -> Responses:
        responses = Responses()
        errs = ErrorMap()
        for name, handler in self.targets.items():
            try:
                handled, review = handler.handle_review(obj)
            except Exception as e:
                errs[name] = e
                continue
            if not handled:
                continue
            try:
                resp = self.driver.query(
                    hook_violation_path(name), {"review": review},
                    tracing=tracing,
                )
                memo: dict = {}
                for r in resp.results:
                    handler.handle_violation(r, memo)
            except Exception as e:
                errs[name] = e
                continue
            resp.target = name
            responses.by_target[name] = resp
        if errs:
            raise ClientError(str(errs))
        return responses

    def review_batch(self, objs: list, tracing: bool = False
                     ) -> list[Responses]:
        """Batched Review: per-object semantics identical to review(),
        with the driver's vectorized review_batch amortizing evaluation
        across the whole batch when available (the gRPC ReviewBatch RPC
        and any bulk caller land here). Tracing and drivers without a
        batch entry point fall back to per-object review."""
        driver_batch = getattr(self.driver, "review_batch", None)
        with self._lock:
            if tracing or driver_batch is None:
                return [self._review_locked(o, tracing) for o in objs]
            out = [Responses() for _ in objs]
            for name, handler in self.targets.items():
                reviews: list = []
                idxs: list[int] = []
                errs = ErrorMap()
                for i, obj in enumerate(objs):
                    try:
                        handled, review = handler.handle_review(obj)
                    except Exception as e:
                        # keyed per batch index: several bad objects in
                        # one batch must all be reported, with positions
                        errs[f"{name}[{i}]"] = e
                        continue
                    if handled:
                        reviews.append(review)
                        idxs.append(i)
                if errs:
                    # same contract as review(): an unhandleable object
                    # fails the call (the wire envelope carries it)
                    raise ClientError(str(errs))
                try:
                    batches = driver_batch(name, reviews)
                    for i, results in zip(idxs, batches):
                        memo: dict = {}
                        for r in results:
                            handler.handle_violation(r, memo)
                        resp = Response(results=results)
                        resp.target = name
                        out[i].by_target[name] = resp
                except Exception as e:
                    # same envelope as review(): evaluation AND
                    # violation-handling failures surface as ClientError
                    raise ClientError(str(ErrorMap({name: e}))) from e
            return out

    def audit(self, tracing: bool = False) -> Responses:
        with self._lock:
            return self._audit_locked(tracing)

    def _audit_locked(self, tracing: bool) -> Responses:
        responses = Responses()
        errs = ErrorMap()
        for name, handler in self.targets.items():
            try:
                resp = self.driver.query(hook_audit_path(name), None,
                                         tracing=tracing)
                memo: dict = {}
                for r in resp.results:
                    handler.handle_violation(r, memo)
            except Exception as e:
                errs[name] = e
                continue
            resp.target = name
            responses.by_target[name] = resp
        if errs:
            raise ClientError(str(errs))
        return responses

    # ----------------------------------------------------------------- misc

    def reset(self) -> None:
        """Wipe all state (reference client.go:726-747)."""
        with self._lock:
            for name in self.targets:
                self.driver.delete_data(("external", name))
                self.driver.delete_data(("constraints", name))
            for kind, entry in self._templates.items():
                for target in entry.targets:
                    self.driver.delete_modules(self._module_prefix(target, kind))
            self._templates = {}
            self._generation += 1

    def snapshot_library(self) -> dict:
        """Raw SOURCES of every ingested template and constraint, for
        the warm-restart state snapshot (control/statestore.py). Restore
        replays them through add_template/add_constraint — the normal
        ingestion path, so compile metadata and validation run exactly
        as they would from a watch delivery — before the controllers'
        level-triggered replay arrives and dedupes via semantic-equal."""
        with self._lock:
            templates = []
            constraints = []
            for kind in sorted(self._templates):
                entry = self._templates[kind]
                if entry.template.raw is not None:
                    templates.append(copy.deepcopy(entry.template.raw))
                for name in sorted(entry.constraints):
                    constraints.append(
                        copy.deepcopy(entry.constraints[name]))
        return {"templates": templates, "constraints": constraints}

    def restore_library(self, snap: dict) -> dict:
        """Re-ingest a snapshot_library() payload. Per-item failures are
        collected, not raised: one stale template must not abort the
        whole warm boot (its live CR re-ingests via the watch replay)."""
        ok = errors = 0
        for t in snap.get("templates") or []:
            try:
                self.add_template(t)
                ok += 1
            except ClientError:
                errors += 1
        for c in snap.get("constraints") or []:
            try:
                self.add_constraint(c)
                ok += 1
            except ClientError:
                errors += 1
        return {"restored": ok, "errors": errors}

    def dump(self) -> str:
        return self.driver.dump()

    def knows_kind(self, kind: str) -> bool:
        with self._lock:
            return kind in self._templates

    def library_index(self) -> dict:
        """{kind: [constraint names]} of the ingested library — the
        N-engine sync diff uses it to drop templates/constraints a
        restarted primary no longer carries."""
        with self._lock:
            return {k: sorted(e.constraints)
                    for k, e in self._templates.items()}

    def template_kinds(self) -> list[str]:
        with self._lock:
            return sorted(self._templates)


def _constraint_semantic_equal(a: dict, b: dict) -> bool:
    """Spec+meta equality ignoring status (reference
    util/constraint SemanticEqual used at client.go:556)."""
    def key(c: dict):
        meta = c.get("metadata") or {}
        return json.dumps(
            {
                "apiVersion": c.get("apiVersion"),
                "kind": c.get("kind"),
                "name": meta.get("name"),
                "labels": meta.get("labels"),
                "annotations": meta.get("annotations"),
                "spec": c.get("spec"),
            },
            sort_keys=True,
        )
    return key(a) == key(b)
