"""Driver seam + the interpreter-backed reference driver.

The Driver protocol is the framework's replaceable evaluation backend —
shape parity with the reference interface
(vendor/.../constraint/pkg/client/drivers/interface.go:21-39): module CRUD,
data CRUD at tree paths, Query, Dump. Two implementations exist:

  * RegoDriver (here): modules run in the tree-walking interpreter; the
    hook join (matching constraints ⋈ template violation rules,
    reference regolib/src.go:23-62) and the match predicate
    (pkg/target/regolib/src.rego) are evaluated natively rather than as
    installed Rego — same results, no meta-interpretation.
  * TpuDriver (ir/driver.py): compiles templates to vectorized JAX programs
    and evaluates reviews in batches; falls back to this driver for
    templates outside the vectorizable subset.

Paths are tuples of segments (not strings), so no URL escaping is needed
anywhere. Well-known roots (reference client.go:79-86, 493-511):
  ("constraints", <target>, "cluster", <group>, <Kind>, <name>)
  ("external", <target>, ...)       synced inventory
"""

from __future__ import annotations

import json
import logging
from typing import Any, Iterable, Optional, Protocol

from ..rego import ast as A
from ..rego.interp import UNDEF, Interpreter, RegoError
from ..target.matcher import constraint_matches, needs_autoreject
from ..utils.values import FrozenDict, freeze, sort_key, thaw
from .templates import CONSTRAINT_GROUP
from .types import Response, Result

log = logging.getLogger("gatekeeper_tpu.client.drivers")


class DriverError(Exception):
    pass


class Driver(Protocol):
    def init(self) -> None: ...

    def put_module(self, name: str, module: A.Module) -> None: ...

    def put_modules(self, prefix: str, modules: Iterable[A.Module]) -> None: ...

    def delete_module(self, name: str) -> bool: ...

    def delete_modules(self, prefix: str) -> int: ...

    def put_data(self, path: tuple, data: Any) -> None: ...

    def delete_data(self, path: tuple) -> bool: ...

    def query(self, path: tuple, input_value: Any = None,
              tracing: bool = False) -> Response: ...

    def dump(self) -> str: ...


def hook_violation_path(target: str) -> tuple:
    return ("hooks", target, "violation")


def hook_audit_path(target: str) -> tuple:
    return ("hooks", target, "audit")


def split_group_version(gv: str) -> tuple[str, str]:
    group, _, version = gv.rpartition("/")
    return group, version


class RegoDriver:
    """Interpreter-backed driver with native hook/matcher evaluation."""

    def __init__(self):
        self._interp = Interpreter()
        self._module_names: set[str] = set()
        self._trace_sink: Optional[list] = None
        # per-template codegen'd materializers (rego/codegen.py): None =
        # outside the compilable subset, fall through to the interpreter
        self._codegen: dict[tuple, Any] = {}
        # kind -> (review, dict): per-review memo for review-pure
        # comprehensions in the codegen'd evaluator
        self._rmemo: dict[str, tuple] = {}
        # kind -> (frozen inventory, dict): arg-pure function memo
        self._fmemo: dict[str, tuple] = {}
        # kind -> {id(constraint): (constraint, dict)}: params-pure
        # comprehension memo, one dict per constraint (valid for its
        # lifetime; identity-checked so a replaced constraint re-derives)
        self._pmemo: dict[str, dict] = {}
        # kind -> dict: head-witness memo — (slot, *witness values) ->
        # materialized head tuple. Values-keyed over pure computation, so
        # never invalidated by data churn; cleared on module changes and
        # capped for boundedness
        self._hmemo: dict[str, dict] = {}
        # identity-keyed freeze caches for the audit materialization loop
        # (consecutive firing pairs share the review; constraints repeat)
        self._frz_review: dict[int, tuple] = {}
        self._frz_params: dict[int, tuple] = {}
        self._frz_inv: tuple = (None, None)
        self._plain_constraint: dict[int, tuple] = {}
        # steady-state audit caches: flattening 100k inventory objects into
        # review dicts (and computing their match signatures) each sweep
        # costs seconds; both are stable until the data tree changes
        self._data_rev = 0
        self._inv_reviews_cache: dict[str, tuple] = {}  # target -> (rev, l)
        self._inv_key_cache: dict[str, tuple] = {}  # target -> (rev, keys)
        self._sig_cache: dict[str, tuple] = {}  # target -> (rev, dict)
        self._inv_tree_cache: dict[str, tuple] = {}  # target -> (rev, tree)
        # audit-scoped freeze cache: id(review) -> (review, frozen),
        # valid for one data generation (inventory reviews are stable
        # then), journal-patched on single-object replacements. Sized by
        # the inventory, unlike the small capped _frz_review the
        # webhook's transient reviews go through.
        self._audit_frz: tuple = (None, {})
        # incremental-mutation journal: ("patch", rev, target, index,
        # old_review, new_review) for single-object in-place replacements
        # that PATCHED the warm caches, ("break", rev) for anything else.
        # Consumers (mask/feature caches in ir/driver.py) replay the
        # range since their snapshot instead of rebuilding from scratch.
        self._patch_notes: list = []
        self._con_rev = 0  # constraint-store revision (ns-selector cache)
        self._ns_sel_cache: tuple = (None, False)
        # per-constraint violation cap applied BEFORE message
        # materialization (control/audit.py arms it with its status
        # violations limit): pairs beyond the cap for their constraint
        # still count toward totals but skip message assembly — capped
        # constraints stop paying for messages that are never published
        self.audit_violations_cap: Optional[int] = None
        # audit ownership predicate pred(gv, kind, namespace) -> bool,
        # installed by the sharded audit plane (control/shardmap.py) so
        # this driver flattens reviews only for its inventory slice.
        # None = unsharded. Applies ONLY to review building — the
        # inventory data tree stays whole so joins and interpreter
        # data.inventory reads keep seeing broadcast objects.
        self.audit_review_filter = None

    # ------------------------------------------------------------- modules

    def init(self) -> None:  # hooks are native; nothing to install
        return None

    def put_module(self, name: str, module: A.Module) -> None:
        self._interp.put_module(name, module)
        self._module_names.add(name)
        self._codegen.clear()
        self._rmemo.clear()
        self._fmemo.clear()
        self._pmemo.clear()
        self._hmemo.clear()

    def put_modules(self, prefix: str, modules: Iterable[A.Module]) -> None:
        # mirror of PutModules upsert semantics (local.go:124-148): existing
        # modules under the prefix not in the new set are removed
        new_names = []
        mods = list(modules)
        for i, m in enumerate(mods):
            new_names.append(f"{prefix}#{i}")
        for old in sorted(self._module_names):
            if old.startswith(prefix + "#") and old not in new_names:
                self._interp.delete_module(old)
                self._module_names.discard(old)
        for name, m in zip(new_names, mods):
            self._interp.put_module(name, m)
            self._module_names.add(name)
        self._codegen.clear()
        self._rmemo.clear()
        self._fmemo.clear()
        self._pmemo.clear()
        self._hmemo.clear()

    def delete_module(self, name: str) -> bool:
        if name not in self._module_names:
            return False
        self._interp.delete_module(name)
        self._module_names.discard(name)
        self._codegen.clear()
        self._rmemo.clear()
        self._fmemo.clear()
        self._pmemo.clear()
        self._hmemo.clear()
        return True

    def delete_modules(self, prefix: str) -> int:
        doomed = [n for n in self._module_names if n.startswith(prefix + "#")]
        for n in doomed:
            self._interp.delete_module(n)
            self._module_names.discard(n)
        self._codegen.clear()
        self._rmemo.clear()
        self._fmemo.clear()
        self._pmemo.clear()
        self._hmemo.clear()
        return len(doomed)

    # ---------------------------------------------------------------- data

    def put_data(self, path: tuple, data: Any) -> None:
        if not path:
            raise DriverError("cannot put data at the root")
        self._interp.put_data(tuple(path), data)
        self._frz_params.clear()
        self._plain_constraint.clear()
        self._frz_inv = (None, None)
        if path[0] != "constraints":
            # constraint churn leaves the inventory-review/signature/tree
            # caches valid — only actual inventory writes invalidate them
            self._data_rev += 1
            self._note_inventory_write(tuple(path), deleted=False)
        else:
            # bound growth: dead constraint objects would pin stale
            # per-constraint memo dicts (identity checks keep them safe,
            # clearing keeps them small)
            self._pmemo.clear()
            self._con_rev += 1

    def delete_data(self, path: tuple) -> bool:
        if not path:
            raise DriverError("cannot delete the data root")
        out = self._interp.delete_data(tuple(path))
        self._frz_params.clear()
        self._plain_constraint.clear()
        self._frz_inv = (None, None)
        if path[0] != "constraints":
            self._data_rev += 1
            self._note_inventory_write(tuple(path), deleted=True)
        else:
            self._pmemo.clear()
            self._con_rev += 1
        return out

    # ------------------------------------------------ incremental writes

    def drop_inventory_caches(self) -> None:
        """Full re-encode backstop: forget every derived inventory cache
        so the next audit rebuilds from the raw data tree. The
        incremental audit's --audit-full-resync-every routes here — a
        reachable from-scratch path self-heals any cache-patching bug."""
        self._data_rev += 1
        self._patch_notes.append(("break", self._data_rev))
        self._inv_reviews_cache.clear()
        self._inv_key_cache.clear()
        self._sig_cache.clear()
        self._inv_tree_cache.clear()
        self._audit_frz = (None, {})
        self._frz_inv = (None, None)

    def set_audit_review_filter(self, pred) -> None:
        """Install (or clear, pred=None) the audit ownership predicate.
        Tears down every derived inventory cache: the flattened review
        list changes shape under a new filter, and any cache keyed off
        it (signatures, encoded rows downstream) must rebuild from the
        filtered view. Installed once at shard start, so the full
        rebuild is a non-event."""
        if pred is self.audit_review_filter:
            return
        self.audit_review_filter = pred
        self.drop_inventory_caches()

    # spine depth below each scope node at which object leaves sit:
    # cluster/<gv>/<kind>/<name>, namespace/<ns>/<gv>/<kind>/<name> —
    # the same layout knowledge _try_patch_reviews encodes below
    _INV_SCOPE_DEPTH = {"cluster": 3, "namespace": 4}

    def inventory_snapshot(self) -> Optional[dict]:
        """PLAIN copy of the synced-inventory subtree ("external") for
        the warm-restart blob snapshot. Plain on purpose: unpickling
        plain dicts is C-native, while reconstructing FrozenDict leaves
        costs a Python call per node — and every consumer of the tree
        (review building, the frozen _inventory_tree cache, the
        interpreter's _freeze_review memo) freezes on demand anyway,
        exactly as it does for never-frozen admission reviews. The one
        deep pass here runs on the snapshot thread, off the serving
        path; a concurrent mutation mid-copy fails the save (caught by
        the manager — previous snapshot kept), never corrupts it. None
        when empty."""
        tree = self._interp.get_data(("external",))
        if tree is UNDEF or not isinstance(tree, dict):
            return None
        return _deep_plain(tree) or None

    def inventory_restore(self, tree: dict) -> int:
        """Attach a snapshotted synced-inventory subtree, bypassing the
        per-object add_data path (target-handler processing, freezing,
        journal notes, and cache invalidation per object) that makes a
        cold boot O(cluster) — the warm-restart fast path. Leaves stay
        plain; eval paths freeze them on demand (see
        inventory_snapshot), and any later per-object put_data
        re-freezes its own leaf. Returns the number of objects
        installed; unknown scopes are skipped (the tracker's resync
        cold-path heals them)."""
        if not isinstance(tree, dict):
            raise DriverError("inventory snapshot must be a mapping")
        n = 0
        root = self._interp.data
        ext = root.get("external")
        if not isinstance(ext, dict):
            ext = {}
            root["external"] = ext

        def count(node, left: int) -> int:
            if left == 0:
                return 1
            if not isinstance(node, dict):
                return 0
            return sum(count(v, left - 1) for v in node.values())

        for target, scopes in tree.items():
            if not isinstance(scopes, dict):
                continue
            tnode = ext.get(target)
            if not isinstance(tnode, dict):
                tnode = {}
                ext[target] = tnode
            for scope, sub in scopes.items():
                depth = self._INV_SCOPE_DEPTH.get(scope)
                if depth is None or not isinstance(sub, dict):
                    continue
                tnode[scope] = dict(sub)
                n += count(sub, depth)
        # one journal break + cache drop for the whole install: the next
        # audit rebuilds reviews from the restored tree exactly as it
        # would after a full resync
        self.drop_inventory_caches()
        self._frz_params.clear()
        self._plain_constraint.clear()
        return n

    def _note_inventory_write(self, path: tuple, deleted: bool) -> None:
        notes = self._patch_notes
        if len(notes) >= 1024:
            # journal cap: older ranges fall out of coverage and replay
            # degrades to a rebuild (checked via note count == rev delta)
            del notes[: len(notes) // 2]
        patched = None if deleted else self._try_patch_reviews(path)
        if patched is None:
            notes.append(("break", self._data_rev))
        else:
            notes.append(("patch", self._data_rev) + patched)

    def _any_namespace_selector(self) -> bool:
        """True when any stored constraint matches via namespaceSelector
        (cached per constraint revision)."""
        ent = self._ns_sel_cache
        if ent[0] == self._con_rev:
            return ent[1]
        found = False
        root = self._interp.get_data(("constraints",))
        stack = [root] if isinstance(root, dict) else []
        while stack and not found:
            node = stack.pop()
            for v in node.values():
                if not isinstance(v, dict):
                    continue
                if v.get("kind") and isinstance(v.get("spec"), dict):
                    match = v["spec"].get("match")
                    if isinstance(match, dict) and \
                            "namespaceSelector" in match:
                        found = True
                        break
                else:
                    stack.append(v)
        self._ns_sel_cache = (self._con_rev, found)
        return found

    def _notes_between(self, rev_a: int, rev_b: int):
        """Patch notes for writes in (rev_a, rev_b], or None when the
        range is uncovered or contains a non-patchable write."""
        if rev_b <= rev_a:
            return []
        sel = [n for n in self._patch_notes if rev_a < n[1] <= rev_b]
        if len(sel) != rev_b - rev_a or any(n[0] == "break" for n in sel):
            return None
        return sel

    def _try_patch_reviews(self, path: tuple):
        """In-place REPLACEMENT of a single existing inventory object
        patches the warm steady-state caches (review list, signature
        cache, frozen inventory tree) instead of invalidating them — the
        churning-cluster case where one object mutates between audits.
        Inserts, deletes, and non-object writes return None (rebuild).
        Returns (target, index, old_review, new_review) on success."""
        import bisect

        if len(path) < 2 or path[0] != "external":
            return None
        target = path[1]
        rest = path[2:]
        if len(rest) == 4 and rest[0] == "cluster":
            gv, kind, name = rest[1], rest[2], rest[3]
            sort_key_t = (0, "", gv, kind, name)
            ns = None
        elif len(rest) == 5 and rest[0] == "namespace":
            ns, gv, kind, name = rest[1], rest[2], rest[3], rest[4]
            sort_key_t = (1, ns, gv, kind, name)
        else:
            return None
        if kind == "Namespace" and self._any_namespace_selector():
            # a Namespace's labels feed OTHER reviews' match verdicts
            # through namespaceSelector; patching only its own mask row
            # would leave every other review in that namespace stale
            return None
        prev = self._data_rev - 1
        cached = self._inv_reviews_cache.get(target)
        keys = self._inv_key_cache.get(target)
        if cached is None or cached[0] != prev or keys is None or \
                keys[0] != prev:
            return None
        reviews, keylist = cached[1], keys[1]
        i = bisect.bisect_left(keylist, sort_key_t)
        if not (i < len(keylist) and keylist[i] == sort_key_t):
            return None  # insertion would shift every later index
        node = self._interp.get_data(tuple(path))
        if node is UNDEF or not isinstance(node, dict):
            return None
        group, version = split_group_version(gv)
        new_review = {"kind": {"group": group, "version": version,
                               "kind": kind},
                      "name": name, "object": node}
        if ns is not None:
            new_review["namespace"] = ns
        old = reviews[i]
        reviews[i] = new_review
        self._inv_reviews_cache[target] = (self._data_rev, reviews)
        self._inv_key_cache[target] = (self._data_rev, keylist)
        sig = self._sig_cache.get(target)
        if sig is not None and sig[0] == prev:
            sig[1].pop(id(old), None)
            self._sig_cache[target] = (self._data_rev, sig[1])
        tre = self._inv_tree_cache.get(target)
        if tre is not None and tre[0] == prev:
            self._inv_tree_cache[target] = (
                self._data_rev,
                _tree_with(tre[1], rest, freeze(_deep_plain(node))))
        return (target, i, old, new_review)

    def get_data(self, path: tuple) -> Any:
        v = self._interp.get_data(tuple(path))
        return None if v is UNDEF else v

    # --------------------------------------------------------------- query

    def query(self, path: tuple, input_value: Any = None,
              tracing: bool = False) -> Response:
        path = tuple(path)
        trace: Optional[list] = [] if tracing else None
        if len(path) == 3 and path[0] == "hooks" and path[2] == "violation":
            results = self._eval_violation(path[1], input_value or {}, trace)
        elif len(path) == 3 and path[0] == "hooks" and path[2] == "audit":
            results = self._eval_audit(path[1], trace)
        else:
            results = self._eval_data_path(path, input_value)
        resp = Response(results=results)
        if tracing:
            resp.trace = "\n".join(trace or [])
            resp.input = json.dumps(thaw(freeze(input_value)), sort_keys=True)
        return resp

    # hooks["<target>"].violation — the admission path (regolib/src.go:7-41)
    def _eval_violation(self, target: str, input_value: dict,
                        trace: Optional[list]) -> list[Result]:
        review = input_value.get("review") or {}
        results: list[Result] = []
        lookup_ns = self._namespace_lookup(target)
        inventory = self._inventory_tree(target)
        for constraint in self._constraints(target):
            spec = constraint.get("spec")
            spec = spec if isinstance(spec, dict) else {}
            match = spec.get("match")
            match = match if isinstance(match, dict) else {}
            enforcement = spec.get("enforcementAction") or "deny"
            if needs_autoreject(match, review, lookup_ns):
                if trace is not None:
                    trace.append(
                        f"autoreject {constraint.get('kind')}/"
                        f"{(constraint.get('metadata') or {}).get('name')}"
                    )
                results.append(Result(
                    msg="Namespace is not cached in OPA.",
                    metadata={"details": {}},
                    constraint=thaw(freeze(constraint)),
                    review=review,
                    enforcement_action=enforcement,
                ))
                # no `continue`: the reference hook UNIONS autoreject with
                # matching_constraints results (regolib/src.go rules 1+2) —
                # a Namespace-kind review can still match via its own labels
            if not constraint_matches(constraint, review, lookup_ns):
                continue
            results.extend(
                self._eval_template_violations(
                    target, constraint, review, enforcement, inventory, trace
                )
            )
        return results

    # hooks["<target>"].audit — cached-state sweep (regolib/src.go:45-62)
    def _eval_audit(self, target: str, trace: Optional[list]) -> list[Result]:
        results: list[Result] = []
        lookup_ns = self._namespace_lookup(target)
        constraints = self._constraints(target)
        inventory = self._inventory_tree(target)
        for review in self._inventory_reviews(target):
            for constraint in constraints:
                if not constraint_matches(constraint, review, lookup_ns):
                    continue
                spec = constraint.get("spec")
                spec = spec if isinstance(spec, dict) else {}
                enforcement = spec.get("enforcementAction") or "deny"
                results.extend(
                    self._eval_template_violations(
                        target, constraint, review, enforcement, inventory,
                        trace
                    )
                )
        return results

    def _codegen_for(self, target: str, kind: str):
        """Per-template codegen'd materializer, or None (interpreter
        path). Built lazily from the same rewritten modules the
        interpreter holds, merged into one compile unit."""
        key = (target, kind)
        if key in self._codegen:
            return self._codegen[key]
        fn = None
        prefix = f'templates["{target}"]["{kind}"]#'
        names = sorted(n for n in self._module_names
                       if n.startswith(prefix))
        if names:
            from ..rego.codegen import Unsupported, compile_module
            # lazy: ir imports this module at load; no cycle at call time
            from ..ir.driver import merge_template_modules
            mods = [self._interp.modules[n] for n in names]
            try:
                merged = (mods[0] if len(mods) == 1
                          else merge_template_modules(mods))
                if merged is not None:
                    fn = compile_module(merged, entry="violation")
            except Unsupported as e:
                log.debug("codegen unsupported for %s: %s", kind, e)
                fn = None
        self._codegen[key] = fn
        return fn

    def _freeze_review_audit(self, review: dict):
        ent = self._audit_frz
        if ent[0] != self._data_rev:
            notes = self._notes_between(ent[0], self._data_rev) \
                if ent[0] is not None else None
            if notes is None:
                ent = (self._data_rev, {})
            else:
                for n in notes:
                    ent[1].pop(id(n[4]), None)  # replaced review object
                ent = (self._data_rev, ent[1])
            self._audit_frz = ent
        m = ent[1]
        c = m.get(id(review))
        if c is not None and c[0] is review:
            return c[1]
        f = freeze(review)
        m[id(review)] = (review, f)
        return f

    def _freeze_review(self, review: dict):
        # id-keyed with identity check: a micro-batch sweeps the same
        # reviews once per KIND, and a single-entry cache would re-freeze
        # the whole batch for every kind after the first
        c = self._frz_review.get(id(review))
        if c is not None and c[0] is review:
            return c[1]
        if len(self._frz_review) > 32768:
            # bound retention: webhook reviews are transient (never
            # reused), so the cache exists for audits re-sweeping the
            # stable inventory — ~32k distinct materialized objects
            self._frz_review.clear()
        f = freeze(review)
        self._frz_review[id(review)] = (review, f)
        return f

    def _freeze_params(self, constraint: dict, parameters):
        c = self._frz_params.get(id(constraint))
        if c is not None and c[0] is constraint:
            return c[1]
        f = freeze(parameters)
        self._frz_params[id(constraint)] = (constraint, f)
        return f

    def _freeze_inv(self, inventory):
        if isinstance(inventory, FrozenDict):
            return inventory  # _inventory_tree output is deep-frozen
        c = self._frz_inv
        if c[0] is inventory:
            return c[1]
        f = freeze(inventory)
        self._frz_inv = (inventory, f)
        return f

    def _constraint_plain(self, constraint: dict) -> dict:
        """Result.constraint deep-copy, cached per constraint object (one
        audit materializes the same constraint thousands of times)."""
        c = self._plain_constraint.get(id(constraint))
        if c is not None and c[0] is constraint:
            return c[1]
        p = thaw(freeze(constraint))
        self._plain_constraint[id(constraint)] = (constraint, p)
        return p

    def _eval_template_violations(self, target: str, constraint: dict,
                                  review: dict, enforcement: str,
                                  inventory: Any,
                                  trace: Optional[list]) -> list[Result]:
        kind = constraint.get("kind")
        pkg = ("templates", target, kind)
        if pkg not in self._interp.packages:
            return []
        spec = constraint.get("spec")
        spec = spec if isinstance(spec, dict) else {}
        parameters = spec.get("parameters")
        if parameters is None:
            parameters = {}
        out = _MISSING_OUT = object()
        fn = self._codegen_for(target, kind) if trace is None else None
        if fn is not None:
            frz_review = self._freeze_review(review)
            frz_params = self._freeze_params(constraint, parameters)
            # review-pure comprehension memo: audit materialization is
            # row-major, so consecutive calls share the review — reuse its
            # review-only subresults across the constraints it fired
            ent = self._rmemo.get(kind)
            if ent is None or ent[0] is not review:
                ent = (review, {})
                self._rmemo[kind] = ent
            # arg-pure function memo: scoped to the frozen inventory tree,
            # so inventory-join projections (selector flattening etc.)
            # evaluate once per inventory object, not once per (review ×
            # object) pair
            frozen_inv = self._freeze_inv(inventory)
            fent = self._fmemo.get(kind)
            if fent is None or fent[0] is not frozen_inv:
                fent = (frozen_inv, {})
                self._fmemo[kind] = fent
            # params-pure memo: one dict per constraint object
            pmap = self._pmemo.setdefault(kind, {})
            pent = pmap.get(id(constraint))
            if pent is None or pent[0] is not constraint:
                pent = (constraint, {})
                pmap[id(constraint)] = pent
            # head-witness memo: cross-review AND cross-constraint
            hm = self._hmemo.get(kind)
            if hm is None:
                hm = self._hmemo[kind] = {}
            elif len(hm) > 500_000:
                hm.clear()
            try:
                if fn.__sections__:
                    out = fn(frz_review, frz_params, frozen_inv, ent[1],
                             fent[1], pent[1], hm)
                else:
                    finp = FrozenDict((("review", frz_review),
                                       ("parameters", frz_params)))
                    out = fn(finp, frozen_inv, ent[1], fent[1], pent[1], hm)
            except RegoError as e:
                raise DriverError(
                    f"evaluating {kind} violation: {e}"
                ) from e
            except Exception as e:
                # a codegen bug must be visible, never silent, and must
                # not take the request down: log + permanent fallback
                log.warning("codegen evaluator for %s failed (%s: %s); "
                            "falling back to the interpreter",
                            kind, type(e).__name__, e)
                self._codegen[(target, kind)] = None
                out = _MISSING_OUT
        if out is _MISSING_OUT:
            inp = {"review": review, "parameters": parameters}
            try:
                out = self._interp.eval_rule(
                    pkg, "violation", inp,
                    overrides={("inventory",): inventory}
                )
            except RegoError as e:
                raise DriverError(
                    f"evaluating {kind} violation: {e}"
                ) from e
        results = []
        if out is UNDEF:
            return results
        constraint_plain = self._constraint_plain(constraint)
        ordered = out if len(out) <= 1 else sorted(out, key=sort_key)
        for r in ordered:
            if not isinstance(r, FrozenDict) or "msg" not in r:
                raise DriverError(
                    f"template {kind}: violation output must be an object "
                    f"with msg, got {thaw(r)!r}"
                )
            msg = r["msg"]
            if not isinstance(msg, str):
                raise DriverError(f"template {kind}: msg must be a string")
            details = thaw(r["details"]) if "details" in r else {}
            if trace is not None:
                trace.append(f"violation {kind}: {msg}")
            results.append(Result(
                msg=msg,
                metadata={"details": details},
                constraint=constraint_plain,
                review=review,
                enforcement_action=enforcement,
            ))
        return results

    def _vec_msgs(self, target: str, kind: str, cons: list,
                  pair_reviews: list, rows, cols, cand):
        """Vectorized per-pair message assembly hook. The base driver
        has no encoded columns: always the exact path. TpuDriver
        overrides with the ir/vecmat.py plan evaluator, returning
        (status[P] int8, msgs[P], details) — status 1 = message ready,
        0 = veto (exact evaluator), 2 = provably no violation."""
        return None

    def materialize_pairs(self, target: str, cons: list, pair_reviews: list,
                          rows, cols, inventory: Any,
                          cand=None) -> list[Result]:
        """Batched exact materialization of firing (review, constraint)
        pairs, row-major. Semantically identical to calling
        _eval_template_violations per pair (the audit differential tests
        assert that), but:

          * kinds with a message plan (ir/vecmat.py) render their
            messages VECTORIZED — one numpy assembly pass over the
            already-built witness columns instead of one evaluator call
            per pair — with per-pair fallback to the exact evaluator
            for witnesses outside the plan's subset (the differential
            suite asserts bit-equal messages either way);
          * the exact path hoists per-constraint context (frozen
            params, enforcement, plain copy, params-memo) and
            per-review context (frozen review, review-memo) out of the
            pair loop, and caches thawed msg/details per distinct
            violation object;
          * with audit_violations_cap armed, vectorized pairs past the
            cap for their constraint emit count-only results (empty
            msg) — the status writer never publishes past its limit,
            so the messages were pure waste.

        `cand`, when given, maps pair rows to global inventory-review
        indices (rows index pair_reviews == [reviews[i] for i in
        cand]), letting witness columns cache across sweeps on the
        stable full review list. Results share constraint/details
        structures (callers treat results as read-only, as they
        already must for .constraint)."""
        if not len(rows):
            return []
        kind = cons[0].get("kind")
        vec = self._vec_msgs(target, kind, cons, pair_reviews, rows, cols,
                             cand)
        fn = self._codegen_for(target, kind)
        if fn is None and vec is None:
            out: list[Result] = []
            for ri, ci in zip(rows, cols):
                c = cons[int(ci)]
                spec = c.get("spec")
                spec = spec if isinstance(spec, dict) else {}
                out.extend(self._eval_template_violations(
                    target, c, pair_reviews[int(ri)],
                    spec.get("enforcementAction") or "deny", inventory,
                    None))
            return out
        # per-constraint context, built once
        n_c = len(cons)
        frz_params: list = [None] * n_c
        enforce: list = [None] * n_c
        plain: list = [None] * n_c
        pmemos: list = [None] * n_c
        pmap = self._pmemo.setdefault(kind, {})
        for ci in range(n_c):
            c = cons[ci]
            spec = c.get("spec")
            spec = spec if isinstance(spec, dict) else {}
            p = spec.get("parameters")
            frz_params[ci] = self._freeze_params(c, p if p is not None
                                                 else {})
            enforce[ci] = spec.get("enforcementAction") or "deny"
            plain[ci] = self._constraint_plain(c)
            pe = pmap.get(id(c))
            if pe is None or pe[0] is not c:
                pe = (c, {})
                pmap[id(c)] = pe
            pmemos[ci] = pe[1]
        frozen_inv = self._freeze_inv(inventory)
        fent = self._fmemo.get(kind)
        if fent is None or fent[0] is not frozen_inv:
            fent = (frozen_inv, {})
            self._fmemo[kind] = fent
        fmemo = fent[1]
        hm = self._hmemo.get(kind)
        if hm is None:
            hm = self._hmemo[kind] = {}
        elif len(hm) > 500_000:
            hm.clear()
        sections = fn.__sections__ if fn is not None else None
        vcache: dict[int, tuple] = {}  # id(violation) -> (msg, details)
        out = []
        append = out.append
        cur_ri = -1
        frz_review = None
        review = None
        rmemo: dict = {}
        # plain-int lists: iterating numpy scalars costs ~100ns per
        # element extraction and they are slow dict keys
        rows = rows.tolist() if hasattr(rows, "tolist") else rows
        cols = cols.tolist() if hasattr(cols, "tolist") else cols
        vec_status = vec_msgs = vec_details = None
        if vec is not None:
            vec_status, vec_msgs, vec_details = vec
        # the cap applies only inside a full audit sweep (the flag is
        # set by the sweep entry point): what-if previews and direct
        # pair materialization stay uncapped
        cap = (self.audit_violations_cap
               if getattr(self, "_in_audit_sweep", False) else None)
        # per-call cap counters: blocks of one sweep each materialize at
        # most `cap` messages per constraint, so the sweep's global
        # first `cap` per constraint are always fully materialized even
        # when mesh blocks reassemble out of materialization order
        cap_counts: dict[int, int] = {}
        n_vec = n_capped = 0
        for j, (ri, ci) in enumerate(zip(rows, cols)):
            if vec_status is not None:
                st = vec_status[j]
                if st == 2:  # msg witness undefined for this constraint:
                    continue  # the head binding fails — no violation
                if st == 1:
                    n_vec += 1
                    if cap is not None:
                        seen = cap_counts.get(ci, 0)
                        cap_counts[ci] = seen + 1
                        if seen >= cap:
                            n_capped += 1
                            append(Result(
                                msg="",
                                metadata={"details": {}},
                                constraint=plain[ci],
                                review=pair_reviews[ri],
                                enforcement_action=enforce[ci],
                            ))
                            continue
                    append(Result(
                        msg=vec_msgs[j],
                        metadata={"details": vec_details},
                        constraint=plain[ci],
                        review=pair_reviews[ri],
                        enforcement_action=enforce[ci],
                    ))
                    continue
            if ri != cur_ri:
                cur_ri = ri
                review = pair_reviews[ri]
                frz_review = self._freeze_review_audit(review)
                ent = self._rmemo.get(kind)
                if ent is None or ent[0] is not review:
                    ent = (review, {})
                    self._rmemo[kind] = ent
                rmemo = ent[1]
            if fn is None:  # demoted mid-batch / no codegen: exact path
                out.extend(self._eval_template_violations(
                    target, cons[ci], review, enforce[ci], inventory,
                    None))
                continue
            try:
                if sections:
                    res = fn(frz_review, frz_params[ci], frozen_inv, rmemo,
                             fmemo, pmemos[ci], hm)
                else:
                    finp = FrozenDict((("review", frz_review),
                                       ("parameters", frz_params[ci])))
                    res = fn(finp, frozen_inv, rmemo, fmemo, pmemos[ci], hm)
            except RegoError as e:
                raise DriverError(
                    f"evaluating {kind} violation: {e}") from e
            except Exception as e:
                log.warning("codegen evaluator for %s failed (%s: %s); "
                            "falling back to the interpreter",
                            kind, type(e).__name__, e)
                self._codegen[(target, kind)] = None
                fn = None
                out.extend(self._eval_template_violations(
                    target, cons[ci], review, enforce[ci], inventory,
                    None))
                continue
            if res is UNDEF or not res:
                continue
            ordered = (tuple(res) if len(res) == 1
                       else sorted(res, key=sort_key))
            for r in ordered:
                ent2 = vcache.get(id(r))
                if ent2 is None or ent2[0] is not r:
                    if not isinstance(r, FrozenDict) or "msg" not in r:
                        raise DriverError(
                            f"template {kind}: violation output must be "
                            f"an object with msg, got {thaw(r)!r}")
                    msg = r["msg"]
                    if not isinstance(msg, str):
                        raise DriverError(
                            f"template {kind}: msg must be a string")
                    details = thaw(r["details"]) if "details" in r else {}
                    ent2 = (r, msg, details)
                    vcache[id(r)] = ent2
                append(Result(
                    msg=ent2[1],
                    metadata={"details": ent2[2]},
                    constraint=plain[ci],
                    review=review,
                    enforcement_action=enforce[ci],
                ))
        try:
            from ..control.metrics import report_materialize_pairs

            n_skip = (int((vec_status == 2).sum())
                      if vec_status is not None else 0)
            report_materialize_pairs("vectorized", n_vec - n_capped)
            report_materialize_pairs("capped", n_capped)
            report_materialize_pairs("exact",
                                     len(rows) - n_vec - n_skip)
        except Exception:  # metrics backend optional in embedders
            pass
        return out

    # ---------------------------------------------------------- store views

    def _constraints(self, target: str) -> list[dict]:
        root = self._interp.get_data(("constraints", target, "cluster",
                                      CONSTRAINT_GROUP))
        if root is UNDEF or not isinstance(root, dict):
            return []
        out = []
        for kind in sorted(root):
            by_name = root[kind]
            if isinstance(by_name, dict):
                for name in sorted(by_name):
                    if isinstance(by_name[name], dict):
                        out.append(by_name[name])
        return out

    def _namespace_lookup(self, target: str):
        def lookup(name: str):
            v = self._interp.get_data(
                ("external", target, "cluster", "v1", "Namespace", name)
            )
            return None if v is UNDEF or not isinstance(v, dict) else v
        return lookup

    def _inventory_tree(self, target: str) -> Any:
        cached = self._inv_tree_cache.get(target)
        if cached is not None and cached[0] == self._data_rev:
            return cached[1]
        v = self._interp.get_data(("external", target))
        tree = {} if v is UNDEF else freeze(_deep_plain(v))
        self._inv_tree_cache[target] = (self._data_rev, tree)
        return tree

    def _inventory_reviews(self, target: str) -> list[dict]:
        """Flatten the inventory into make_review-shaped dicts
        (reference regolib src.rego:40-61). Cached until the data tree
        changes — the recurring audit sweep's steady state."""
        cached = self._inv_reviews_cache.get(target)
        if cached is not None and cached[0] == self._data_rev:
            return cached[1]
        reviews, keys = self._build_inventory_reviews(target)
        self._inv_reviews_cache[target] = (self._data_rev, reviews)
        self._inv_key_cache[target] = (self._data_rev, keys)
        return reviews

    def _audit_sig_cache(self, target: str) -> dict:
        """Match-signature cache (id(review) -> signature) valid for the
        cached review list of the current data revision."""
        cached = self._sig_cache.get(target)
        if cached is not None and cached[0] == self._data_rev:
            return cached[1]
        sigs: dict = {}
        self._sig_cache[target] = (self._data_rev, sigs)
        return sigs

    def _build_inventory_reviews(self, target: str) -> tuple:
        """-> (reviews, sort keys) aligned; the key list lets single-
        object writes bisect to their review index for in-place cache
        patching (_try_patch_reviews)."""
        reviews: list[dict] = []
        keys: list[tuple] = []
        root = self._interp.get_data(("external", target))
        if root is UNDEF or not isinstance(root, dict):
            return reviews, keys
        flt = self.audit_review_filter
        cluster = root.get("cluster")
        if isinstance(cluster, dict):
            for gv in sorted(cluster):
                by_kind = cluster[gv]
                if not isinstance(by_kind, dict):
                    continue
                group, version = split_group_version(gv)
                for kind in sorted(by_kind):
                    by_name = by_kind[kind]
                    if not isinstance(by_name, dict):
                        continue
                    if flt is not None and not flt(gv, kind, ""):
                        continue
                    for name in sorted(by_name):
                        reviews.append({
                            "kind": {"group": group, "version": version,
                                     "kind": kind},
                            "name": name,
                            "object": by_name[name],
                        })
                        keys.append((0, "", gv, kind, name))
        namespaced = root.get("namespace")
        if isinstance(namespaced, dict):
            for ns in sorted(namespaced):
                by_gv = namespaced[ns]
                if not isinstance(by_gv, dict):
                    continue
                for gv in sorted(by_gv):
                    by_kind = by_gv[gv]
                    if not isinstance(by_kind, dict):
                        continue
                    group, version = split_group_version(gv)
                    for kind in sorted(by_kind):
                        by_name = by_kind[kind]
                        if not isinstance(by_name, dict):
                            continue
                        if flt is not None and not flt(gv, kind, ns):
                            continue
                        for name in sorted(by_name):
                            reviews.append({
                                "kind": {"group": group, "version": version,
                                         "kind": kind},
                                "name": name,
                                "namespace": ns,
                                "object": by_name[name],
                            })
                            keys.append((1, ns, gv, kind, name))
        return reviews, keys

    def _eval_data_path(self, path: tuple, input_value: Any) -> list[Result]:
        """Generic data query: wrap each value at `path` as a bare Result
        (used by tests and Dump; the reference local driver's
        data.<path>[result] shape, local.go:302-324)."""
        if len(path) >= 2:
            pkg, name = tuple(path[:-1]), path[-1]
            if pkg in self._interp.packages and name in self._interp.packages[pkg]:
                v = self._interp.eval_rule(pkg, name, input_value)
                if v is UNDEF:
                    return []
                return [Result(msg="", metadata={"value": thaw(v)})]
        v = self._interp.get_data(path)
        if v is UNDEF:
            return []
        return [Result(msg="", metadata={"value": thaw(freeze(_deep_plain(v)))})]

    # ---------------------------------------------------------------- dump

    def dump(self) -> str:
        data = thaw(freeze(_deep_plain(self._interp.data)))
        return json.dumps({
            "modules": sorted(self._module_names),
            "data": data,
        }, indent=2, sort_keys=True)


def _tree_with(tree: Any, segs: tuple, frozen_value: Any) -> Any:
    """Frozen inventory tree with tree[segs...] replaced, rebuilding
    only the spine (O(path depth x siblings), not O(inventory))."""
    if not segs:
        return frozen_value
    base = tree if isinstance(tree, dict) else {}
    d = dict(base)
    d[segs[0]] = _tree_with(base.get(segs[0]), segs[1:], frozen_value)
    return FrozenDict(d)


def _deep_plain(v: Any) -> Any:
    """Make a store subtree JSON-able (mutable dict shells + frozen leaves)."""
    if isinstance(v, dict):
        return {k: _deep_plain(x) for k, x in v.items()}
    return thaw(v)
