"""Template-module rewriting: package namespacing + extern safety.

The reference's regorewriter (vendor/.../constraint/pkg/client/regorewriter/
regorewriter.go) rewrites a template's entry module into the
`templates["<target>"]["<Kind>"]` package and its libs under
`libs.<target>.<Kind>`, requires libs to live under `package lib...`,
and rejects references to any `data.*` root other than the lib prefix and
the allowed externs (`data.inventory`). It also enforces that the entry
module defines `violation` as a partial-set rule (client.go:312-316).

This implementation works on parsed AST modules directly (no source
re-emission — the driver stores ASTs), which also gives the recompile-free
template swap the reference lacks (local.go:168-207 recompiles everything).
"""

from __future__ import annotations

from dataclasses import replace
from typing import Callable, Iterable

from ..rego import ast as A
from ..rego.parser import ParseError, parse_module


class RewriteError(Exception):
    pass


def template_package(target: str, kind: str) -> tuple:
    return ("templates", target, kind)


def lib_package_prefix(target: str, kind: str) -> tuple:
    return ("libs", target, kind)


def rewrite_template_modules(
    target: str,
    kind: str,
    rego_src: str,
    libs: Iterable[str] = (),
    allowed_externs: tuple = ("inventory",),
    source_name: str = "<template>",
) -> list[A.Module]:
    """Parse + namespace a template's entry module and libs.

    Returns modules whose packages are `templates.<target>.<Kind>` (entry)
    and `libs.<target>.<Kind>.lib...` (libs); every `data.lib...` reference
    is redirected into the namespaced lib location.
    """
    try:
        entry = parse_module(rego_src, source_name)
    except ParseError as e:
        raise RewriteError(f"could not parse template rego: {e}") from None
    lib_mods = []
    for i, src in enumerate(libs):
        try:
            m = parse_module(src, f"{source_name}/lib_{i}")
        except ParseError as e:
            raise RewriteError(f"could not parse lib {i}: {e}") from None
        if not m.package or m.package[0] != "lib":
            raise RewriteError(
                f"lib {i}: package must begin with `lib`, got {'.'.join(m.package)}"
            )
        lib_mods.append(m)

    _require_violation_rule(entry)

    lib_prefix = lib_package_prefix(target, kind)

    def redirect(path: tuple) -> tuple:
        """Map a data-root path onto its namespaced location."""
        if path and path[0] == "lib":
            return lib_prefix + path
        return path

    out = []
    entry2 = replace(
        entry,
        package=template_package(target, kind),
        rules=tuple(
            _rewrite_rule(r, redirect, allowed_externs, entry.package)
            for r in entry.rules
        ),
    )
    out.append(entry2)
    for m in lib_mods:
        m2 = replace(
            m,
            package=lib_prefix + m.package,
            rules=tuple(
                _rewrite_rule(r, redirect, allowed_externs, m.package)
                for r in m.rules
            ),
        )
        out.append(m2)
    return out


def _require_violation_rule(entry: A.Module) -> None:
    kinds = [r.kind for r in entry.rules if r.name == "violation"]
    if not kinds:
        raise RewriteError("Invalid rego: template must define a violation rule")
    if any(k != "partial_set" for k in kinds):
        raise RewriteError(
            "Invalid rego: violation must be a partial-set rule of arity 1 "
            "(violation[{…}] { … })"
        )


# ------------------------------------------------------------ AST traversal


def _rewrite_rule(rule: A.Rule, redirect: Callable, externs: tuple, pkg: tuple):
    fn = _make_term_rewriter(redirect, externs, pkg)
    return replace(
        rule,
        args=tuple(fn(t) for t in rule.args),
        key=fn(rule.key) if rule.key is not None else None,
        value=fn(rule.value) if rule.value is not None else None,
        body=tuple(_rewrite_literal(l, fn) for l in rule.body),
    )


def _rewrite_literal(lit: A.Literal, fn: Callable) -> A.Literal:
    return replace(
        lit,
        expr=fn(lit.expr),
        withs=tuple(replace(w, value=fn(w.value)) for w in lit.withs),
    )


def _ref_static_path(t: A.Ref) -> tuple | None:
    """The leading all-static segments of a data ref, or None if not data-rooted."""
    if not isinstance(t.base, A.Var) or t.base.name != "data":
        return None
    path = []
    for a in t.args:
        if isinstance(a, A.Scalar) and isinstance(a.value, str):
            path.append(a.value)
        else:
            break
    return tuple(path)


def _make_term_rewriter(redirect: Callable, externs: tuple, pkg: tuple):
    def fn(t):
        if t is None:
            return None
        if isinstance(t, A.Ref):
            base = fn(t.base)
            args = tuple(fn(a) for a in t.args)
            t2 = A.Ref(base=base, args=args)
            static = _ref_static_path(t2)
            if static is not None:
                if not static:
                    raise RewriteError(
                        "template rego may not reference the bare `data` document"
                    )
                root = static[0]
                if root == "lib":
                    new = redirect(static)
                    new_args = tuple(A.Scalar(s) for s in new) + args[len(static):]
                    return A.Ref(base=base, args=new_args)
                if root not in externs:
                    raise RewriteError(
                        f"invalid data reference data.{'.'.join(static)}: only "
                        f"data.lib and data.{{{', '.join(externs)}}} are allowed "
                        "in template rego"
                    )
            return t2
        if isinstance(t, A.Scalar) or isinstance(t, A.Var):
            return t
        if isinstance(t, A.ArrayLit):
            return A.ArrayLit(tuple(fn(x) for x in t.items))
        if isinstance(t, A.SetLit):
            return A.SetLit(tuple(fn(x) for x in t.items))
        if isinstance(t, A.ObjectLit):
            return A.ObjectLit(tuple((fn(k), fn(v)) for k, v in t.items))
        if isinstance(t, A.ArrayCompr):
            return A.ArrayCompr(fn(t.head), tuple(_rewrite_literal(l, fn) for l in t.body))
        if isinstance(t, A.SetCompr):
            return A.SetCompr(fn(t.head), tuple(_rewrite_literal(l, fn) for l in t.body))
        if isinstance(t, A.ObjectCompr):
            return A.ObjectCompr(
                fn(t.key), fn(t.value), tuple(_rewrite_literal(l, fn) for l in t.body)
            )
        if isinstance(t, A.Call):
            # calls into libs: data.lib.x.fn(...) — redirect the name path
            if t.fn and t.fn[0] == "data" and len(t.fn) > 1:
                inner = t.fn[1:]
                if inner[0] == "lib":
                    t = A.Call(("data",) + redirect(inner), t.args)
                elif inner[0] not in externs:
                    raise RewriteError(
                        f"invalid data call data.{'.'.join(inner)}"
                    )
            return A.Call(t.fn, tuple(fn(a) for a in t.args))
        if isinstance(t, A.BinOp):
            return A.BinOp(t.op, fn(t.lhs), fn(t.rhs))
        if isinstance(t, A.UnaryMinus):
            return A.UnaryMinus(fn(t.term))
        if isinstance(t, (A.Assign, A.Unify)):
            return type(t)(fn(t.lhs), fn(t.rhs))
        if isinstance(t, A.SomeDecl):
            return t
        raise RewriteError(f"unhandled AST node {type(t).__name__}")

    return fn
