"""ConstraintTemplate model.

The reference defines versioned CRD Go types (v1alpha1/v1beta1) converted to
an unversioned internal form (vendor/.../constraint/pkg/apis/templates/
core/templates/constrainttemplate_types.go:31-113). Here templates are
ingested from unstructured dicts (as parsed from YAML) in any of those
versions — the conversion is shape-preserving, so a single loader suffices.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

TEMPLATE_GROUP = "templates.gatekeeper.sh"
TEMPLATE_VERSIONS = ("v1beta1", "v1alpha1")
CONSTRAINT_GROUP = "constraints.gatekeeper.sh"


class TemplateError(Exception):
    pass


@dataclass
class TemplateTarget:
    target: str
    rego: str
    libs: list[str] = field(default_factory=list)


@dataclass
class ConstraintTemplate:
    name: str
    kind: str  # CRD names.kind for generated constraints, e.g. K8sRequiredLabels
    targets: list[TemplateTarget]
    # openAPIV3Schema for spec.parameters (plain dict), may be None
    validation_schema: Optional[dict] = None
    api_version: str = f"{TEMPLATE_GROUP}/v1beta1"
    metadata: dict = field(default_factory=dict)
    raw: Optional[dict] = None

    def semantic_equal(self, other: "ConstraintTemplate") -> bool:
        """Spec-level equality used for no-op dedupe on AddTemplate
        (reference client.go:370-373 SemanticEqual)."""
        return (
            self.name == other.name
            and self.kind == other.kind
            and self.validation_schema == other.validation_schema
            and [(t.target, t.rego, t.libs) for t in self.targets]
            == [(t.target, t.rego, t.libs) for t in other.targets]
        )


def load_template(obj: dict) -> ConstraintTemplate:
    """Parse an unstructured ConstraintTemplate (any supported version)."""
    if not isinstance(obj, dict):
        raise TemplateError("template must be an object")
    api_version = obj.get("apiVersion", f"{TEMPLATE_GROUP}/v1beta1")
    group = api_version.split("/")[0] if "/" in api_version else ""
    if group != TEMPLATE_GROUP:
        raise TemplateError(f"unexpected template group {group!r}")
    if obj.get("kind") not in (None, "ConstraintTemplate"):
        raise TemplateError(f"unexpected kind {obj.get('kind')!r}")
    metadata = obj.get("metadata") or {}
    name = metadata.get("name") or ""
    spec = obj.get("spec") or {}

    crd_spec = ((spec.get("crd") or {}).get("spec")) or {}
    names = crd_spec.get("names") or {}
    kind = names.get("kind") or ""
    if not kind:
        raise TemplateError(f"template {name!r}: missing spec.crd.spec.names.kind")
    # The reference requires metadata.name == lowercase(kind)
    # (crd_helpers.go validateTargets path; e2e "Bad Name" case).
    if name != kind.lower():
        raise TemplateError(
            f"template name {name!r} must equal lowercase of kind {kind!r}"
        )

    validation = crd_spec.get("validation") or {}
    schema = validation.get("openAPIV3Schema")

    targets_spec = spec.get("targets")
    if not targets_spec or not isinstance(targets_spec, list):
        raise TemplateError(f"template {name!r}: no targets specified")
    targets = []
    for t in targets_spec:
        tname = t.get("target") or ""
        rego = t.get("rego") or ""
        if not tname:
            raise TemplateError(f"template {name!r}: target missing name")
        if not rego:
            raise TemplateError(f"template {name!r}: target {tname} has no rego")
        targets.append(
            TemplateTarget(target=tname, rego=rego, libs=list(t.get("libs") or []))
        )

    return ConstraintTemplate(
        name=name,
        kind=kind,
        targets=targets,
        validation_schema=schema,
        api_version=api_version,
        metadata=dict(metadata),
        raw=obj,
    )
