"""Constraint-CRD generation and custom-resource validation.

Behavior parity with the reference crd helper
(vendor/.../constraint/pkg/client/crd_helpers.go): the per-template
constraint CRD's schema is `{spec: {match: <target MatchSchema>,
parameters: <template openAPIV3Schema>, enforcementAction: string}}`;
constraints are validated against that schema plus name/kind/group/version
checks. CRDs here are plain dicts (apiextensions v1beta1 shape) — there is
no client-go scheme machinery to mirror.
"""

from __future__ import annotations

import re
from typing import Any

from .templates import CONSTRAINT_GROUP, ConstraintTemplate

SUPPORTED_CONSTRAINT_VERSIONS = ("v1alpha1", "v1beta1")

_DNS1123_RE = re.compile(
    r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?(\.[a-z0-9]([-a-z0-9]*[a-z0-9])?)*$"
)


class CRDError(Exception):
    pass


def create_schema(templ: ConstraintTemplate, match_schema: dict) -> dict:
    props: dict[str, Any] = {
        "match": match_schema,
        "enforcementAction": {"type": "string"},
    }
    if templ.validation_schema is not None:
        props["parameters"] = templ.validation_schema
    return {"properties": {"spec": {"properties": props}}}


def create_crd(templ: ConstraintTemplate, schema: dict) -> dict:
    kind = templ.kind
    plural = kind.lower()
    return {
        "apiVersion": "apiextensions.k8s.io/v1beta1",
        "kind": "CustomResourceDefinition",
        "metadata": {"name": f"{plural}.{CONSTRAINT_GROUP}"},
        "spec": {
            "group": CONSTRAINT_GROUP,
            "names": {
                "kind": kind,
                "listKind": kind + "List",
                "plural": plural,
                "singular": plural,
                "categories": ["constraint"],
            },
            "scope": "Cluster",
            "version": "v1beta1",
            "subresources": {"status": {}},
            "versions": [
                {"name": "v1beta1", "storage": True, "served": True},
                {"name": "v1alpha1", "storage": False, "served": True},
            ],
            "validation": {"openAPIV3Schema": schema},
        },
    }


def validate_crd(crd: dict) -> None:
    """Structural sanity of a generated CRD (stand-in for the apiextensions
    validation pass the reference runs; createTemplateArtifacts path)."""
    spec = crd.get("spec") or {}
    names = spec.get("names") or {}
    for f in ("kind", "plural", "singular"):
        if not names.get(f):
            raise CRDError(f"CRD missing names.{f}")
    if not _DNS1123_RE.match(crd.get("metadata", {}).get("name", "")):
        raise CRDError("CRD name is not a DNS-1123 subdomain")
    if spec.get("group") != CONSTRAINT_GROUP:
        raise CRDError(f"CRD group must be {CONSTRAINT_GROUP}")
    _check_schema(spec.get("validation", {}).get("openAPIV3Schema") or {}, "")


def _check_schema(schema: Any, path: str) -> None:
    if not isinstance(schema, dict):
        raise CRDError(f"schema node at {path or '/'} must be an object")
    ty = schema.get("type")
    if ty is not None and ty not in (
        "object", "array", "string", "integer", "number", "boolean", "null",
    ):
        raise CRDError(f"schema at {path or '/'}: unknown type {ty!r}")
    for key, sub in (schema.get("properties") or {}).items():
        _check_schema(sub, f"{path}.{key}")
    items = schema.get("items")
    if items is not None:
        if isinstance(items, list):
            for i, sub in enumerate(items):
                _check_schema(sub, f"{path}[{i}]")
        else:
            _check_schema(items, f"{path}[]")
    ap = schema.get("additionalProperties")
    if isinstance(ap, dict):
        _check_schema(ap, f"{path}.*")


# ----------------------------------------------------------------- CR checks


def validate_cr(cr: dict, crd: dict) -> None:
    """Validate a constraint instance against its generated CRD
    (reference crd_helpers.go validateCR)."""
    if not isinstance(cr, dict):
        raise CRDError("constraint must be an object")
    name = (cr.get("metadata") or {}).get("name") or ""
    if not name or len(name) > 253 or not _DNS1123_RE.match(name):
        raise CRDError(f"Invalid Name: {name!r} is not a DNS-1123 subdomain")
    spec = crd.get("spec") or {}
    want_kind = (spec.get("names") or {}).get("kind")
    if cr.get("kind") != want_kind:
        raise CRDError(
            f"Wrong kind for constraint {name}. Have {cr.get('kind')}, want {want_kind}"
        )
    api_version = cr.get("apiVersion") or ""
    group, _, version = api_version.partition("/")
    if group != CONSTRAINT_GROUP:
        raise CRDError(
            f"Wrong group for constraint {name}. Have {group}, want {CONSTRAINT_GROUP}"
        )
    if version not in SUPPORTED_CONSTRAINT_VERSIONS:
        raise CRDError(
            f"Wrong version for constraint {name}. Have {version}, "
            f"supported: {SUPPORTED_CONSTRAINT_VERSIONS}"
        )
    schema = (spec.get("validation") or {}).get("openAPIV3Schema")
    if schema:
        errs: list[str] = []
        _validate_value(cr, schema, "", errs)
        if errs:
            raise CRDError("; ".join(errs))


def _type_ok(value: Any, ty: str) -> bool:
    if ty == "object":
        return isinstance(value, dict)
    if ty == "array":
        return isinstance(value, list)
    if ty == "string":
        return isinstance(value, str)
    if ty == "boolean":
        return isinstance(value, bool)
    if ty == "integer":
        return isinstance(value, int) and not isinstance(value, bool)
    if ty == "number":
        return isinstance(value, (int, float)) and not isinstance(value, bool)
    if ty == "null":
        return value is None
    return True


def _validate_value(value: Any, schema: dict, path: str, errs: list[str]) -> None:
    """openAPIV3Schema subset validator: type, properties, required, items,
    enum, additionalProperties, pattern, min/max(+Items/Length)."""
    if value is None:
        return  # null handled as missing, matching k8s structural defaults
    ty = schema.get("type")
    if ty and not _type_ok(value, ty):
        errs.append(f"{path or '/'}: expected {ty}")
        return
    enum = schema.get("enum")
    if enum is not None and value not in enum:
        errs.append(f"{path or '/'}: value {value!r} not in enum {enum!r}")
    if isinstance(value, dict):
        props = schema.get("properties") or {}
        for req in schema.get("required") or []:
            if req not in value:
                errs.append(f"{path or '/'}: missing required field {req!r}")
        ap = schema.get("additionalProperties")
        for k, v in value.items():
            if k in props:
                _validate_value(v, props[k], f"{path}.{k}", errs)
            elif isinstance(ap, dict):
                _validate_value(v, ap, f"{path}.{k}", errs)
            elif ap is False:
                errs.append(f"{path or '/'}: unexpected field {k!r}")
    elif isinstance(value, list):
        items = schema.get("items")
        if isinstance(items, dict):
            for i, v in enumerate(value):
                _validate_value(v, items, f"{path}[{i}]", errs)
        mn, mx = schema.get("minItems"), schema.get("maxItems")
        if mn is not None and len(value) < mn:
            errs.append(f"{path or '/'}: fewer than {mn} items")
        if mx is not None and len(value) > mx:
            errs.append(f"{path or '/'}: more than {mx} items")
    elif isinstance(value, str):
        pat = schema.get("pattern")
        if pat is not None and not re.search(pat, value):
            errs.append(f"{path or '/'}: does not match pattern {pat!r}")
        mn, mx = schema.get("minLength"), schema.get("maxLength")
        if mn is not None and len(value) < mn:
            errs.append(f"{path or '/'}: shorter than {mn}")
        if mx is not None and len(value) > mx:
            errs.append(f"{path or '/'}: longer than {mx}")
    elif isinstance(value, (int, float)) and not isinstance(value, bool):
        mn, mx = schema.get("minimum"), schema.get("maximum")
        if mn is not None and value < mn:
            errs.append(f"{path or '/'}: below minimum {mn}")
        if mx is not None and value > mx:
            errs.append(f"{path or '/'}: above maximum {mx}")
