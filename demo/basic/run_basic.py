#!/usr/bin/env python
"""Scripted basic walkthrough (counterpart of the reference's
demo/basic/demo.sh, which drives kubectl against a kind cluster).

Drives the REAL control plane — Runtime with the in-memory apiserver —
through the same beats: sync config, template ingest (including a broken
template rejected at admission), constraint enforcement at admission,
a cross-object unique-label policy over synced inventory, a dryrun
constraint, and the audit populating status.violations.

Run:  python demo/basic/run_basic.py
"""

from __future__ import annotations

import pathlib
import sys

import yaml

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[2]))

from gatekeeper_tpu.control.main import Runtime, build_parser  # noqa: E402

HERE = pathlib.Path(__file__).resolve().parent
GREEN, RED, DIM, END = "\033[32m", "\033[31m", "\033[2m", "\033[0m"


def say(msg: str) -> None:
    print(f"\n=== {msg}")


def ok(msg: str) -> None:
    print(f"  {GREEN}✓{END} {msg}")


def load(rel: str) -> dict:
    return yaml.safe_load((HERE / rel).read_text())


def review_of(obj: dict, username: str = "dev") -> dict:
    group, _, version = (obj.get("apiVersion") or "").rpartition("/")
    req = {
        "uid": "uid-basic",
        "kind": {"group": group, "version": version, "kind": obj["kind"]},
        "operation": "CREATE",
        "name": obj["metadata"]["name"],
        "userInfo": {"username": username},
        "object": obj,
    }
    ns = obj["metadata"].get("namespace")
    if ns:
        req["namespace"] = ns
    return {"apiVersion": "admission.k8s.io/v1beta1",
            "kind": "AdmissionReview", "request": req}


def main() -> int:
    args = build_parser().parse_args([
        "--fake-kube", "--port", "0", "--prometheus-port", "0",
        "--health-addr", ":0", "--disable-cert-rotation",
        "--log-level", "WARNING",
    ])
    rt = Runtime(args)
    rt.args.metrics_backend = "none"
    rt.start()
    handler = rt.webhook.validation

    def admit(obj):
        return handler.handle(review_of(obj))["response"]

    def expect(obj, allowed: bool, label: str):
        resp = admit(obj)
        if resp["allowed"] is not allowed:
            print(f"  {RED}✗ {label}: expected allowed={allowed}, "
                  f"got {resp}{END}")
            raise SystemExit(1)
        reason = (resp.get("status") or {}).get("reason", "")
        suffix = f" {DIM}{reason.splitlines()[0][:80]}{END}" if reason else ""
        ok(f"{label}{suffix}")

    try:
        rt.kube.create({"apiVersion": "v1", "kind": "Namespace",
                        "metadata": {"name": "gatekeeper-system",
                                     "labels": {"team": "platform"}}})
        say("Sync config: namespaces feed the inventory")
        rt.kube.create(load("sync.yaml"))
        rt.manager.drain()
        ok("Config applied; Namespace kind synced")

        say("Templates are ingested; a broken one is rejected")
        resp = admit(load("bad/broken_template.yaml"))
        assert resp["allowed"] is False, resp
        ok("broken template DENIED at admission "
           f"{DIM}{(resp['status']['reason'] or '').splitlines()[0][:70]}"
           f"{END}")
        rt.kube.create(load("templates/required_labels.yaml"))
        rt.kube.create(load("templates/unique_label.yaml"))
        rt.manager.drain()
        ok("2 templates ingested, constraint CRDs created")

        say("Constraints enforce at admission")
        rt.kube.create(load("constraints/ns_must_have_team.yaml"))
        rt.kube.create(load("constraints/team_label_unique.yaml"))
        rt.kube.create(load("constraints/ns_must_have_team_dryrun.yaml"))
        rt.manager.drain()
        expect(load("bad/unlabeled_ns.yaml"), False,
               "namespace without team label DENIED")
        expect(load("good/labeled_ns.yaml"), True,
               "labeled namespace ALLOWED (dryrun cost-center only warns)")

        say("Cross-object policy over synced inventory")
        rt.kube.create(load("good/labeled_ns.yaml"))
        rt.manager.drain()
        expect(load("bad/duplicate_team_ns.yaml"), False,
               "namespace duplicating team=retail DENIED (inventory join)")
        expect(load("good/unique_ns.yaml"), True,
               "namespace with a fresh team label ALLOWED")

        say("Audit reports dryrun + live violations in status")
        rt.kube.create(load("bad/unlabeled_ns.yaml"))
        rt.manager.drain()
        rt.audit.audit_once()
        stored = rt.kube.get(("constraints.gatekeeper.sh", "v1beta1",
                              "K8sRequiredLabelsList"), "ns-must-have-team")
        viol = stored["status"].get("violations") or []
        assert any(v["name"] == "shadow-it" for v in viol), viol
        ok(f"audit[deny] shadow-it reported "
           f"{DIM}{viol[0]['message'][:60]}{END}")
        dr = rt.kube.get(("constraints.gatekeeper.sh", "v1beta1",
                          "K8sRequiredLabelsList"), "ns-must-have-cost-center")
        dviol = dr["status"].get("violations") or []
        assert dviol and all(v["enforcementAction"] == "dryrun"
                             for v in dviol), dviol
        ok(f"audit[dryrun] {len(dviol)} namespaces missing cost-center")

        print(f"\n{GREEN}basic demo complete — all steps behaved as "
              f"expected{END}")
        return 0
    finally:
        rt.stop()


if __name__ == "__main__":
    sys.exit(main())
