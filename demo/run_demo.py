#!/usr/bin/env python
"""The agilebank walkthrough (reference demo/agilebank/demo.sh analog).

Boots the real control plane (control.main.Runtime) against the
in-memory apiserver, applies the demo manifests, and walks the same
story: templates -> constraints -> denied bad resources -> allowed good
resources -> synced inventory powering the unique-selector join -> the
dryrun unique-ingress-host enforcement (allowed at admission, reported
by audit).

Run:  python demo/run_demo.py
"""

import json
import sys
from pathlib import Path

import yaml

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from gatekeeper_tpu.control.main import Runtime, build_parser  # noqa: E402

DEMO = Path(__file__).resolve().parent / "agilebank"
CONSTRAINT_GROUP = "constraints.gatekeeper.sh"

GREEN, RED, DIM, END = "\033[32m", "\033[31m", "\033[2m", "\033[0m"


def say(msg: str) -> None:
    print(f"\n=== {msg}")


def ok(msg: str) -> None:
    print(f"  {GREEN}✓{END} {msg}")


def load(rel: str) -> dict:
    return yaml.safe_load((DEMO / rel).read_text())


def review_of(obj, operation="CREATE"):
    group, _, version = (obj.get("apiVersion") or "").rpartition("/")
    req = {"uid": "demo", "kind": {"group": group, "version": version,
                                   "kind": obj["kind"]},
           "operation": operation, "name": obj["metadata"]["name"],
           "userInfo": {"username": "demo-user"}, "object": obj}
    if obj["metadata"].get("namespace"):
        req["namespace"] = obj["metadata"]["namespace"]
    return {"apiVersion": "admission.k8s.io/v1beta1",
            "kind": "AdmissionReview", "request": req}


def main() -> int:
    args = build_parser().parse_args([
        "--fake-kube", "--port", "0", "--prometheus-port", "0",
        "--health-addr", ":0", "--disable-cert-rotation",
        "--log-level", "WARNING",
    ])
    rt = Runtime(args)
    rt.args.metrics_backend = "none"
    rt.kube.register_kind(("networking.k8s.io", "v1", "Ingress"),
                          namespaced=True)
    rt.start()
    handler = rt.webhook.validation

    def admit(obj):
        return handler.handle(review_of(obj))["response"]

    def expect(obj, allowed: bool, label: str):
        resp = admit(obj)
        if resp["allowed"] is not allowed:
            print(f"  {RED}✗ {label}: expected allowed={allowed}, "
                  f"got {resp}{END}")
            raise SystemExit(1)
        reason = (resp.get("status") or {}).get("reason", "")
        suffix = f" {DIM}{reason.splitlines()[0][:90]}{END}" if reason else ""
        ok(f"{label}{suffix}")

    try:
        # the namespaces the demo manifests deploy into — a real cluster
        # always has the Namespace object (the audit skips objects whose
        # namespace cannot be fetched, mirroring the reference)
        for ns_name in ("gatekeeper-system", "payments", "production",
                        "staging"):
            rt.kube.create({"apiVersion": "v1", "kind": "Namespace",
                            "metadata": {"name": ns_name,
                                         "labels": {"owner": "agilebank"}}})

        say("AgileBank applies the policy templates")
        for p in sorted((DEMO / "templates").glob("*.yaml")):
            rt.kube.create(yaml.safe_load(p.read_text()))
        rt.manager.drain()
        n_tpl = len(list((DEMO / 'templates').glob('*.yaml')))
        ok(f"{n_tpl} templates ingested, constraint CRDs created")

        say("...and the constraints that use them")
        for p in sorted((DEMO / "constraints").glob("*.yaml")):
            rt.kube.create(yaml.safe_load(p.read_text()))
        rt.kube.create(load("dryrun/unique_ingress_host.yaml"))
        rt.manager.drain()
        ok("constraints enforced (unique-ingress-host in DRYRUN)")

        say("Cluster state is synced for cross-object policies")
        rt.kube.create(load("sync.yaml"))
        rt.kube.create(load("existing_resources/payments_service.yaml"))
        rt.kube.create(load("dryrun/existing_ingress.yaml"))
        rt.manager.drain()
        ok("existing payments Service + checkout Ingress synced")

        say("Bad resources are denied at admission")
        expect(load("bad_resources/namespace.yaml"), False,
               "namespace without owner label DENIED")
        expect(load("bad_resources/opa_no_limits.yaml"), False,
               "pod without limits DENIED")
        expect(load("bad_resources/opa_limits_too_high.yaml"), False,
               "pod with oversized limits DENIED")
        expect(load("bad_resources/opa_wrong_repo.yaml"), False,
               "pod from an unapproved repo DENIED")
        expect(load("bad_resources/duplicate_service.yaml"), False,
               "service duplicating a live selector DENIED (inventory join)")

        say("Good resources sail through")
        expect(load("good_resources/namespace.yaml"), True,
               "labelled namespace ALLOWED")
        expect(load("good_resources/opa.yaml"), True,
               "compliant pod ALLOWED")

        say("Dryrun: conflicting ingress is allowed...")
        conflicting = load("dryrun/conflicting_ingress.yaml")
        expect(conflicting, True,
               "conflicting ingress ALLOWED (enforcementAction: dryrun)")

        say("...but the audit reports it")
        rt.kube.create(conflicting)
        rt.manager.drain()
        rt.audit.audit_once()
        stored = rt.kube.get((CONSTRAINT_GROUP, "v1beta1",
                              "K8sUniqueIngressHost"), "unique-ingress-host")
        viol = stored["status"].get("violations") or []
        assert any(v["enforcementAction"] == "dryrun" for v in viol), viol
        for v in viol:
            ok(f"audit[{v['enforcementAction']}] {v['namespace']}/"
               f"{v['name']}: {v['message'][:70]}")

        print(f"\n{GREEN}demo complete — all steps behaved as "
              f"expected{END}")
        return 0
    finally:
        rt.stop()


if __name__ == "__main__":
    sys.exit(main())
