#!/usr/bin/env python
"""CI audit-bound check: the steady audit must stay SWEEP-bound.

Runs a reduced-scale BENCH config 3 (full pod-security-policy library
over synthetic pods) in-process, measures the non-delta steady sweep's
phase breakdown (bench_configs.audit_phase_breakdown), and asserts

    materialize_s <= 2 * sweep_wall_s  (+ a small absolute floor)

— the ROADMAP item 3 regression gate: host-side violation-message
materialization must not grow back past the device sweep it decorates.
The absolute floor (ABS_FLOOR_S) absorbs timer noise at reduced scale,
where both phases are tens of milliseconds on a CI host.

Prints the full phase breakdown always; exits 1 on a violated bound
(the CI job is non-blocking — the signal is the printed breakdown).

    BENCH_SCALE=0.1 python tools/audit_bound_check.py
"""

from __future__ import annotations

import json
import os
import sys
import time

ABS_FLOOR_S = float(os.environ.get("AUDIT_BOUND_FLOOR_S", "0.3"))


def main() -> int:
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import bench_configs as bc
    from gatekeeper_tpu import policies

    n = int(50_000 * bc.SCALE)
    drv, client = bc.new_client()
    for name in policies.names():
        if name.startswith("pod-security-policy/"):
            client.add_template(policies.load(name))
    for kind, cname, params in bc.PSP_CONSTRAINTS:
        client.add_constraint({
            "apiVersion": "constraints.gatekeeper.sh/v1beta1",
            "kind": kind, "metadata": {"name": cname},
            "spec": ({"parameters": params} if params else {}),
        })
    for o in bc.synth_pods_psp(n):
        client.add_data(o)
    # force the device sweep path: at reduced scale the cost model
    # would (correctly) keep the whole audit on the host, measuring
    # nothing — this check exists to watch the device-sweep/
    # materialize ratio, so pin the dispatch decision
    drv._dev_batch_lat_s = 1e-6
    drv._host_pair_rate = 1.0
    t0 = time.time()
    client.audit()  # cold: compiles + extraction
    while drv.warm_status()["compiling"] and time.time() - t0 < 600:
        time.sleep(0.2)
    phases = bc.audit_phase_breakdown(drv, client, iters=3)
    out = {"check": "audit-bound", "objects": n,
           "constraints": len(bc.PSP_CONSTRAINTS), **phases}
    sweep = phases["sweep_wall_s"]
    mat = phases["materialize_s"]
    bound = 2 * sweep + ABS_FLOOR_S
    out["bound_s"] = round(bound, 4)
    out["ok"] = mat <= bound
    print(json.dumps(out))
    if not out["ok"]:
        print(f"AUDIT-BOUND VIOLATED: materialize_s={mat:.3f}s exceeds "
              f"2x sweep_wall_s + {ABS_FLOOR_S}s = {bound:.3f}s — "
              f"host-side message materialization is dominating the "
              f"device sweep again (phase breakdown above)",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
