"""Seeded chaos run + crash-consistency verification, end to end.

    JAX_PLATFORMS=cpu python -m tools.chaos_verify [--seed N]

Boots the REAL multi-process plane (pre-forked frontends + engine
children + audit shard children + FakeKube), generates a fault schedule
deterministically from one integer seed (printed first — any failure
replays with `--seed N`), executes it under closed-loop admission load,
and then asserts the five crash-consistency invariants:

  1. zero unanswered admissions, every verdict matching the stance
     contract (a stance answer carries allowed == not fail_closed);
  2. the post-convergence audit round is bit-equal to a clean
     single-process oracle over an identical cluster;
  3. at most one lease holder ever writes status (fencing);
  4. no leaked child processes, fds, or /dev/shm segments;
  5. no stale lifecycle gauge series after teardown (the gklint
     gauge-teardown families, checked at runtime).

Three phases, each chaosed from the same seed (+0 / +1 / +2):
  serve — frontends/engines under kill/pause/wire/apiserver faults;
  audit — shard children killed/paused between bit-equal rounds;
  fence — two lease candidates + status writers under steal/expire.

Exit code 0 iff zero invariant violations. `--ledger PATH` writes the
full machine-readable run (schedule, ledger, verifier report) for CI
artifact upload.
"""

from __future__ import annotations

import argparse
import http.client
import json
import os
import sys
import tempfile
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

from gatekeeper_tpu.control import chaos  # noqa: E402
from gatekeeper_tpu.control.chaos import (  # noqa: E402
    ChaosOrchestrator,
    ChaosSchedule,
    LeakBaseline,
    PlaneHandles,
    RecordingKube,
    Verifier,
)
from gatekeeper_tpu.utils.faults import FAULTS  # noqa: E402

TARGET = "admission.k8s.gatekeeper.sh"

# the serve phase's fault surface: everything that can hit the
# admission path. state.* is excluded (this Runtime runs without
# --state-dir); shard.* belongs to the audit phase.
SERVE_SURFACE = (
    "engine.kill", "engine.pause",
    "frontend.kill", "frontend.pause",
    "wire.reset", "wire.truncate", "wire.slow",
    "backplane.error",
    "kube.flap", "kube.stall",
    "shm.corrupt", "shm.unlink",
)
AUDIT_SURFACE = ("shard.kill", "shard.pause")
FENCE_SURFACE = ("lease.steal", "lease.expire")


def _review(uid: str) -> bytes:
    return json.dumps({
        "apiVersion": "admission.k8s.io/v1", "kind": "AdmissionReview",
        "request": {
            "uid": uid, "operation": "CREATE",
            "kind": {"group": "", "version": "v1", "kind": "Pod"},
            "object": {
                "apiVersion": "v1", "kind": "Pod",
                "metadata": {"name": f"p-{uid}", "namespace": "default",
                             "labels": {"owner": "chaos"}}},
        },
    }).encode()


# ------------------------------------------------------------ serve phase


def _load_worker(port: int, ids: list, answered: dict, errors: list,
                 lock: threading.Lock, retries: int = 8) -> None:
    """Closed-loop admission client: each uid is retried across
    reconnects until a 200 envelope lands (the API server re-calls a
    webhook whose connection died), recording the terminal outcome."""
    conn = None
    for uid in ids:
        last_err = "no attempt"
        for attempt in range(retries):
            try:
                if conn is None:
                    conn = http.client.HTTPConnection(
                        "127.0.0.1", port, timeout=10)
                body = _review(uid)
                conn.request("POST", "/v1/admit?timeout=8s", body,
                             {"Content-Type": "application/json"})
                resp = conn.getresponse()
                data = resp.read()
                if resp.status == 200:
                    with lock:
                        answered[uid] = (resp.status, json.loads(data))
                    break
                last_err = f"http {resp.status}: {data[:80]!r}"
            except Exception as e:
                last_err = repr(e)
                try:
                    if conn is not None:
                        conn.close()
                except Exception:
                    pass
                conn = None
            time.sleep(min(0.05 * (attempt + 1), 0.5))
        else:
            with lock:
                errors.append((uid, last_err))
    if conn is not None:
        try:
            conn.close()
        except Exception:
            pass


def phase_serve(verifier: Verifier, seed: int, n_actions: int,
                horizon_s: float, n_requests: int = 160) -> dict:
    from gatekeeper_tpu.control.main import Runtime, build_parser

    args = build_parser().parse_args([
        "--fake-kube", "--port", "0", "--prometheus-port", "0",
        "--disable-cert-rotation", "--health-addr", ":0",
        "--operation", "webhook", "--admission-workers", "2",
        "--admission-engines", "2"])
    rt = Runtime(args)
    rt.args.metrics_backend = "none"

    plane = PlaneHandles(kube=rt.kube)
    baseline = LeakBaseline(plane).capture()
    rt.start()
    plane.frontends = rt.frontends
    plane.engines = rt.engines
    # tight deadlines so a SIGSTOP'd child is detected within the run,
    # not the production 10s
    rt.frontends.heartbeat_deadline_s = 3.0
    if rt.engines is not None:
        rt.engines.heartbeat_deadline_s = 3.0
    try:
        deadline = time.monotonic() + 30
        while rt.backplane.connected < 2 and time.monotonic() < deadline:
            time.sleep(0.05)
        baseline.track_children()

        schedule = ChaosSchedule.generate(
            seed, surface=SERVE_SURFACE, n_actions=n_actions,
            horizon_s=horizon_s)
        orch = ChaosOrchestrator(plane, schedule)

        ids = [f"c{i}" for i in range(n_requests)]
        answered: dict = {}
        errors: list = []
        lock = threading.Lock()
        workers = [threading.Thread(
            target=_load_worker,
            args=(rt.frontends.port, ids[k::4], answered, errors, lock),
            daemon=True) for k in range(4)]
        for w in workers:
            w.start()
        orch.run()

        # convergence: clear remaining armed faults, then wait for the
        # supervisors to detect/kill/respawn/resync everything. A child
        # paused by the schedule's LAST action is only detectable once
        # its heartbeat deadline lapses — wait that out first so the
        # recovery happens under supervision, not in stop()'s sweep.
        FAULTS.reset()
        time.sleep(rt.frontends.heartbeat_deadline_s + 1.0)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if rt.frontends.alive() and rt.backplane.connected >= 2 \
                    and (rt.engines is None
                         or rt.engines.alive_count()
                         == len(rt.engines.engine_ids)):
                break
            time.sleep(0.2)
        for w in workers:
            w.join(timeout=120)
        baseline.track_children()

        verifier.check_admissions(n_requests, answered, errors,
                                  fail_closed=bool(args.fail_closed))
    finally:
        rt.stop()
    verifier.check_leaks(baseline)
    return orch.snapshot()


# ------------------------------------------------------------ audit phase


def _cluster_objects(n_pods: int = 12) -> list:
    objs = [{"apiVersion": "v1", "kind": "Namespace",
             "metadata": {"name": f"ns{i}", "uid": f"u-ns-{i}",
                          "resourceVersion": "1"}} for i in range(4)]
    for i in range(n_pods):
        labels = {"team": "core"} if i % 3 else {"app": "x"}
        objs.append({"apiVersion": "v1", "kind": "Pod",
                     "metadata": {"name": f"p-{i}",
                                  "namespace": f"ns{i % 4}",
                                  "uid": f"u-p-{i}",
                                  "resourceVersion": "1",
                                  "labels": labels}})
    objs += [
        {"apiVersion": "networking.k8s.io/v1", "kind": "Ingress",
         "metadata": {"name": n, "namespace": ns, "uid": f"u-ing-{n}",
                      "resourceVersion": "1"},
         "spec": {"rules": [{"host": h} for h in hosts]}}
        for n, ns, hosts in (("ing-a", "ns0", ["x.com", "y.com"]),
                             ("ing-b", "ns1", ["x.com"]),
                             ("ing-c", "ns2", ["solo.com"]))]
    return objs


def _cluster_kube(objs):
    from gatekeeper_tpu.control.kube import FakeKube

    kube = FakeKube()
    kube.register_kind(("", "v1", "Namespace"), namespaced=False)
    kube.register_kind(("", "v1", "Pod"), namespaced=True)
    kube.register_kind(("networking.k8s.io", "v1", "Ingress"),
                       namespaced=True)
    for o in objs:
        kube.apply(dict(o))
    for c in (
        {"apiVersion": "constraints.gatekeeper.sh/v1beta1",
         "kind": "K8sRequiredLabels",
         "metadata": {"name": "pods-need-team", "uid": "c-team"},
         "spec": {"match": {"kinds": [{"apiGroups": [""],
                                       "kinds": ["Pod"]}]},
                  "parameters": {"labels": [{"key": "team"}]}}},
        {"apiVersion": "constraints.gatekeeper.sh/v1beta1",
         "kind": "K8sUniqueIngressHost",
         "metadata": {"name": "unique-hosts", "uid": "c-hosts"},
         "spec": {}},
    ):
        kube.apply(dict(c))
    return kube


def _library(client):
    from gatekeeper_tpu import policies
    from gatekeeper_tpu.parallel.workload import REQUIRED_LABELS_TEMPLATE

    client.add_template(REQUIRED_LABELS_TEMPLATE)
    client.add_template(policies.load("general/uniqueingresshost"))
    client.add_constraint(
        {"apiVersion": "constraints.gatekeeper.sh/v1beta1",
         "kind": "K8sRequiredLabels",
         "metadata": {"name": "pods-need-team", "uid": "c-team"},
         "spec": {"match": {"kinds": [{"apiGroups": [""],
                                       "kinds": ["Pod"]}]},
                  "parameters": {"labels": [{"key": "team"}]}}})
    client.add_constraint(
        {"apiVersion": "constraints.gatekeeper.sh/v1beta1",
         "kind": "K8sUniqueIngressHost",
         "metadata": {"name": "unique-hosts", "uid": "c-hosts"},
         "spec": {}})


def _result_key(r):
    return (r.msg,
            json.dumps(r.metadata, sort_keys=True, default=str),
            json.dumps(r.constraint, sort_keys=True, default=str),
            json.dumps(r.review, sort_keys=True, default=str),
            json.dumps(r.resource, sort_keys=True, default=str),
            r.enforcement_action)


def phase_audit(verifier: Verifier, seed: int) -> dict:
    from gatekeeper_tpu.client import Backend
    from gatekeeper_tpu.control.audit import (AuditManager,
                                              ShardedAuditPlane)
    from gatekeeper_tpu.control.backplane import AuditShardSupervisor
    from gatekeeper_tpu.ir import TpuDriver
    from gatekeeper_tpu.target import K8sValidationTarget

    objs = _cluster_objects()
    # rv-identical oracle cluster: an unsharded single-process audit is
    # the bit-equality reference for both results and status writes
    okube = _cluster_kube(objs)
    oracle_client = Backend(TpuDriver()).new_client(
        [K8sValidationTarget()])
    _library(oracle_client)
    oracle = AuditManager(okube, oracle_client, interval=3600,
                          incremental=True)
    oracle_results = [_result_key(r) for r in oracle.audit_once()]

    kube = _cluster_kube(objs)
    leader = Backend(TpuDriver()).new_client([K8sValidationTarget()])
    tmp = tempfile.mkdtemp(prefix="chaos-audit-")
    sock = os.path.join(tmp, "audit.sock")
    plane_box: list = []
    sup = AuditShardSupervisor(
        2, socket_for=lambda k: f"{sock}.{k}",
        spawn_args=["--log-level", "WARNING"],
        snapshot_provider=lambda k: plane_box[0].sync_snapshot(k),
        heartbeat_deadline_s=3.0)
    splane = ShardedAuditPlane(kube, leader, sup, 2)
    plane_box.append(splane)
    splane.attach()
    _library(leader)
    mgr = AuditManager(kube, leader, interval=3600, shard_plane=splane)

    handles = PlaneHandles(audit_shards=sup, kube=kube)
    baseline = LeakBaseline(handles).capture()
    sup.start()
    schedule = ChaosSchedule.generate(seed, surface=AUDIT_SURFACE,
                                      n_actions=2, horizon_s=0.5,
                                      max_target=2)
    orch = ChaosOrchestrator(handles, schedule)
    try:
        baseline.track_children()
        round1 = [_result_key(r) for r in mgr.audit_once()]
        r = chaos.CheckResult("audit_round1_clean")
        if round1 != oracle_results:
            r.violations.append(
                "pre-chaos sharded round already differs from oracle")
        verifier.results.append(r)

        orch.run()  # SIGKILL / SIGSTOP the shard children
        # convergence: wedge detection (<= heartbeat deadline) + respawn
        # + slice resync, all supervisor-internal. A paused child still
        # counts alive until the deadline trips, so wait that out first.
        time.sleep(sup.heartbeat_deadline_s + 1.0)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if sup.alive_count() == 2 and \
                    not any(sup._dirty.values()):
                break
            time.sleep(0.2)
        baseline.track_children()
        round2 = [_result_key(r) for r in mgr.audit_once()]
        verifier.check_audit_bitequal(round2, oracle_results)

        # status parity, kind by kind, against the oracle cluster
        r = chaos.CheckResult("audit_status_parity")
        for kind, name in (("K8sRequiredLabels", "pods-need-team"),
                           ("K8sUniqueIngressHost", "unique-hosts")):
            gvk = ("constraints.gatekeeper.sh", "v1beta1", kind)
            want = (okube.get(gvk, name).get("status") or {})
            got = (kube.get(gvk, name).get("status") or {})
            if got.get("totalViolations") != want.get("totalViolations"):
                r.violations.append(
                    f"{kind}/{name}: totalViolations "
                    f"{got.get('totalViolations')} != oracle "
                    f"{want.get('totalViolations')}")
        verifier.results.append(r)
    finally:
        sup.stop()
        splane.stop()
    verifier.check_leaks(baseline)
    return orch.snapshot()


# ------------------------------------------------------------ fence phase


def phase_fence(verifier: Verifier, seed: int,
                run_s: float = 4.0) -> dict:
    """Two lease candidates + per-candidate status writers gated on
    `is_leader` (the GuardedKube fence), under seeded steal/expire
    faults. Every successful status write records the lease holder at
    write time; a write by one candidate while ANOTHER candidate held
    the lease is a fencing violation."""
    import random as _random

    from gatekeeper_tpu.control.kube import (FakeKube, LEASE_GVK,
                                             LeaseElector)
    from gatekeeper_tpu.control.resilience import GuardedKube, NotLeader

    kube = FakeKube()
    kube.register_kind(LEASE_GVK)
    kube.register_kind(("constraints.gatekeeper.sh", "v1beta1",
                        "K8sRequiredLabels"))
    kube.apply({"apiVersion": "constraints.gatekeeper.sh/v1beta1",
                "kind": "K8sRequiredLabels",
                "metadata": {"name": "fence-target", "uid": "c-fence"},
                "spec": {}})

    writes: list = []
    identities = ("pod-a", "pod-b")
    electors = [LeaseElector(kube, identity=i, lease_duration=0.6,
                             namespace="gk") for i in identities]
    stop = threading.Event()

    def writer(elector, identity):
        gvk = ("constraints.gatekeeper.sh", "v1beta1",
               "K8sRequiredLabels")
        rec = RecordingKube(kube, identity, writes,
                            lease_name=elector.lease_name,
                            lease_namespace="gk")
        guard = GuardedKube(rec, write_gate=lambda: elector.is_leader)
        while not stop.is_set():
            try:
                obj = kube.get(gvk, "fence-target")
                obj["status"] = {"by": identity,
                                 "seq": len(writes)}
                guard.update(obj, subresource="status")
            except NotLeader:
                pass
            except Exception:
                pass  # conflicts / injected API errors: retry
            time.sleep(0.02)

    threads = [threading.Thread(target=writer, args=(e, i), daemon=True)
               for e, i in zip(electors, identities)]
    rng = _random.Random(seed)
    schedule = ChaosSchedule.generate(seed, surface=FENCE_SURFACE,
                                      n_actions=3, horizon_s=run_s * 0.7)
    for e in electors:
        e.start()
    for t in threads:
        t.start()
    t0 = time.monotonic()
    ledger = []
    try:
        for action in schedule.actions:
            delay = (t0 + action.t) - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            verb = action.kind.split(".", 1)[1]
            FAULTS.inject("kube.lease", mode=verb, count=1,
                          match={"identity":
                                 identities[rng.randrange(2)]})
            ledger.append({**action.to_dict(),
                           "at_s": round(time.monotonic() - t0, 3)})
        remaining = run_s - (time.monotonic() - t0)
        if remaining > 0:
            time.sleep(remaining)
    finally:
        stop.set()
        for t in threads:
            t.join(5)
        for e in electors:
            e.stop()
        FAULTS.reset()
    verifier.check_fencing(writes, writers=set(identities))
    return {"seed": seed, "schedule": schedule.to_dict()["actions"],
            "ledger": ledger, "status_writes": len(writes)}


# -------------------------------------------------------------- main


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="seeded chaos run + crash-consistency verification")
    ap.add_argument("--seed", type=int, default=None,
                    help="schedule seed (default: random; always "
                         "printed for replay)")
    ap.add_argument("--actions", type=int, default=8,
                    help="serve-phase schedule length")
    ap.add_argument("--horizon", type=float, default=6.0,
                    help="serve-phase schedule horizon (seconds)")
    ap.add_argument("--phases", default="serve,audit,fence",
                    help="comma list of phases to run")
    ap.add_argument("--ledger", default="",
                    help="write the machine-readable run (schedules, "
                         "ledgers, verifier report) to this JSON file")
    args = ap.parse_args(argv)

    seed = args.seed if args.seed is not None \
        else int.from_bytes(os.urandom(4), "big")
    print(f"chaos seed: {seed}  "
          f"(replay: python -m tools.chaos_verify --seed {seed})",
          flush=True)

    phases = [p.strip() for p in args.phases.split(",") if p.strip()]
    verifier = Verifier()
    run: dict = {"seed": seed, "phases": {}}
    t0 = time.monotonic()
    for name in phases:
        print(f"--- phase: {name}", flush=True)
        if name == "serve":
            run["phases"]["serve"] = phase_serve(
                verifier, seed, args.actions, args.horizon)
        elif name == "audit":
            run["phases"]["audit"] = phase_audit(verifier, seed + 1)
        elif name == "fence":
            run["phases"]["fence"] = phase_fence(verifier, seed + 2)
        else:
            print(f"unknown phase {name!r}", file=sys.stderr)
            return 2
        FAULTS.reset()
    # invariant 5 runs once, after every phase tore its plane down
    verifier.check_stale_gauges()
    run["report"] = verifier.report()
    run["wall_s"] = round(time.monotonic() - t0, 2)

    for check in run["report"]["checks"]:
        mark = "ok" if check["ok"] else "VIOLATED"
        print(f"[{mark}] {check['name']} {check['detail']}")
        for v in check["violations"]:
            print(f"       - {v}")
    n = run["report"]["invariant_violations"]
    print(f"chaos seed {seed}: {n} invariant violation(s) in "
          f"{run['wall_s']}s", flush=True)
    if args.ledger:
        with open(args.ledger, "w") as f:
            json.dump(run, f, indent=1, default=str)
        print(f"ledger written to {args.ledger}")
    return 0 if n == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
