"""gauge_teardown — lifecycle-bound SET gauges must zero on teardown.

The PR 13 stale-export bug class: a gauge that is only ever SET (queue
depth, duty cycle, per-worker in-flight, ring fill, burn rates) keeps
exporting its last value after the thing it measures dies — a dead
frontend's in-flight, a stopped engine's burn rate — unless a teardown
path writes zero or unregisters the scrape probe.

Rule: a class (or module) that writes one of the lifecycle gauge
families outside a teardown context must ALSO touch that family inside
one — a method whose name matches the teardown pattern, or a
``finally`` block (the read-loop-finally idiom). Probe registrations
must pair with an unregister the same way.
"""

from __future__ import annotations

import ast

from .core import Finding, Project, dotted, str_const

# metrics.py reporter functions that SET lifecycle-bound gauges
LIFECYCLE_REPORTERS = {
    "report_queue_depth",
    "report_duty_cycle",
    "report_backplane_inflight",
    "report_ring_fill",
    "report_stream_pending",
    "report_respawn_backoff",
    "report_crashloop_breaker",
}

# direct gauge_set(...) first-arg name literals that are lifecycle-bound
# the chaos verifier imports this set at RUNTIME (tools.gklint is on
# the path in CI and the bench): after a schedule tears the plane
# down, every series of every family below must read zero — the
# stale-gauge invariant is the dynamic twin of this static check
LIFECYCLE_GAUGE_NAMES = {
    "gatekeeper_tpu_queue_depth",
    "gatekeeper_tpu_device_duty_cycle",
    "gatekeeper_tpu_backplane_inflight",
    "gatekeeper_tpu_backplane_ring_fill_ratio",
    "gatekeeper_tpu_audit_stream_pending_events",
    "gatekeeper_tpu_slo_burn_rate",
    "gatekeeper_tpu_respawn_backoff_seconds",
    "gatekeeper_tpu_crashloop_breaker",
}

_TEARDOWN_PAT = ("stop", "close", "shutdown", "abort", "teardown",
                 "detach", "drop", "unregister", "fail", "__exit__",
                 "finish")


def _is_teardown_name(name: str) -> bool:
    low = name.lower()
    return any(p in low for p in _TEARDOWN_PAT)


def _family_of(call: ast.Call) -> str:
    """The lifecycle gauge family a call touches, or ''."""
    name = dotted(call.func)
    leaf = name.split(".")[-1]
    if leaf in LIFECYCLE_REPORTERS:
        return leaf
    if leaf == "gauge_set" and call.args:
        lit = str_const(call.args[0])
        if lit in LIFECYCLE_GAUGE_NAMES:
            return lit
    if leaf == "register_saturation_probe" and call.args:
        lit = str_const(call.args[0])
        return f"probe:{lit}" if lit else "probe:?"
    return ""


def _is_release(call: ast.Call) -> str:
    name = dotted(call.func)
    leaf = name.split(".")[-1]
    if leaf == "unregister_saturation_probe":
        lit = str_const(call.args[0]) if call.args else None
        return f"probe:{lit}" if lit else "probe:?"
    return ""


def check(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    for path, sf in project.files.items():
        if path.endswith("control/metrics.py"):
            continue  # the reporter definitions themselves
        scopes: list[tuple[str, list]] = []
        module_body = [n for n in sf.tree.body
                       if not isinstance(n, ast.ClassDef)]
        scopes.append(("<module>", module_body))
        for node in sf.tree.body:
            if isinstance(node, ast.ClassDef):
                scopes.append((node.name, node.body))
        for scope_name, body in scopes:
            writes: dict[str, ast.Call] = {}
            torn: set = set()
            for item in body:
                is_fn = isinstance(item, (ast.FunctionDef,
                                          ast.AsyncFunctionDef))
                fn_teardown = is_fn and _is_teardown_name(item.name)
                finally_nodes: set = set()
                for sub in ast.walk(item):
                    if isinstance(sub, ast.Try):
                        for fnode in sub.finalbody:
                            for inner in ast.walk(fnode):
                                finally_nodes.add(inner)
                for sub in ast.walk(item):
                    if not isinstance(sub, ast.Call):
                        continue
                    fam = _family_of(sub)
                    rel = _is_release(sub)
                    in_teardown = fn_teardown or sub in finally_nodes
                    if rel:
                        torn.add(rel)
                        continue
                    if not fam:
                        continue
                    if in_teardown:
                        torn.add(fam)
                    else:
                        writes.setdefault(fam, sub)
            for fam, call in sorted(writes.items()):
                if fam in torn:
                    continue
                if fam.startswith("probe:") and "probe:?" in torn:
                    continue  # dynamic unregister name covers it
                if sf.allowed(call.lineno, "gauge_teardown"):
                    continue
                what = ("saturation probe registration"
                        if fam.startswith("probe:")
                        else f"SET gauge family `{fam}`")
                fix = ("an unregister_saturation_probe"
                       if fam.startswith("probe:")
                       else "a zeroing write")
                findings.append(Finding(
                    "gauge_teardown", path, call.lineno, scope_name,
                    fam,
                    f"{what} has no matching {fix} on a stop()/"
                    f"teardown path (or finally block) in {scope_name}"
                    " — the last value exports forever after teardown"))
    return findings
