"""gklint — repo-invariant static analysis for gatekeeper_tpu.

One checker module per invariant class the repo's review history keeps
re-fixing by hand (see ISSUE 15 / CHANGES.md):

  * ``block_zone``       — blocking operations reachable from declared
                           no-block entry points (frame reader, batch
                           seal loop, scrape probes)
  * ``gauge_teardown``   — lifecycle-bound SET gauges must zero (or
                           unregister their probe) on a teardown path
                           in the same class
  * ``clock_discipline`` — ``time.time()`` / naive ``datetime.now()``
                           forbidden in duration/deadline arithmetic
  * ``metrics_hygiene``  — ``_total`` counters, ``_seconds``
                           histograms, no interpolated label values,
                           bounded reason/outcome label sets
  * ``jit_discipline``   — every ``jax.jit`` in ``ir/`` rides AotJit;
                           trace-stage literals must be declared in
                           ``control/stages.py``

Run as ``python -m tools.gklint`` (report) or ``--check`` (CI gate
against the committed ``gklint_baseline.json`` ratchet: new findings
fail, and so do stale suppressions — fixed findings must shrink the
baseline in the same PR).

Escape hatch, on the finding's line or the line above::

    # gklint: allow(block-zone) reason=why this is safe

The reason is mandatory; a reasonless allow is itself a finding.
"""

from .core import Finding, Project, load_baseline, run_checkers  # noqa: F401
