"""CLI: python -m tools.gklint [--check | --write-baseline | ...]

Modes:
  (default)          print findings not covered by the baseline
  --check            CI gate: exit 1 on new findings OR stale
                     suppressions (the two-way ratchet)
  --write-baseline   regenerate gklint_baseline.json from the current
                     tree (review the diff — shrinking is progress,
                     growing needs a reason)
  --all              print every finding, baselined or not
  --stages-md        render the README stage table from
                     control/stages.py and exit
  --locktrace-report FILE
                     gate on a locktrace JSONL dump (utils/locktrace
                     written by GATEKEEPER_TPU_LOCKTRACE=1 runs):
                     exit 1 on lock-order cycles / inversions;
                     held-across-blocking events print as advisory
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from .core import Project, load_baseline, ratchet, run_checkers, \
    write_baseline


def _repo_root() -> str:
    here = os.path.dirname(os.path.abspath(__file__))
    return os.path.dirname(os.path.dirname(here))


def locktrace_gate(report_path: str) -> int:
    """Read a locktrace JSONL dump (one finding per line, possibly
    appended by several processes) and fail on cycles/inversions."""
    if not os.path.exists(report_path):
        print(f"gklint: no locktrace dump at {report_path} "
              "(no traced process ran, or none found anything)")
        return 0
    bad = advisory = 0
    with open(report_path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                ent = json.loads(line)
            except ValueError:
                continue
            kind = ent.get("kind")
            if kind in ("cycle", "inversion"):
                bad += 1
                print(f"LOCKTRACE {kind}: {ent.get('detail')}")
            elif kind == "held_across_blocking":
                # advisory: a bounded sleep under a lock is a smell,
                # not a deadlock — report, never gate
                advisory += 1
                print(f"LOCKTRACE advisory held-across-blocking: "
                      f"{ent.get('detail')}")
    if bad:
        print(f"gklint: {bad} locktrace cycle/inversion finding(s) — "
              "potential deadlock under the chaos suite")
        return 1
    print(f"gklint: locktrace clean ({advisory} advisory "
          "held-across-blocking event(s))")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="gklint")
    ap.add_argument("--check", action="store_true")
    ap.add_argument("--write-baseline", action="store_true")
    ap.add_argument("--all", action="store_true")
    ap.add_argument("--stages-md", action="store_true")
    ap.add_argument("--locktrace-report", metavar="FILE")
    ap.add_argument("--root", default=_repo_root())
    ap.add_argument("--baseline", default=None,
                    help="baseline path (default <root>/"
                         "gklint_baseline.json)")
    args = ap.parse_args(argv)

    if args.locktrace_report:
        return locktrace_gate(args.locktrace_report)

    if args.stages_md:
        import runpy

        mod = runpy.run_path(os.path.join(
            args.root, "gatekeeper_tpu/control/stages.py"))
        print(mod["stages_markdown"]())
        return 0

    baseline_path = args.baseline or os.path.join(
        args.root, "gklint_baseline.json")
    project = Project(args.root)
    findings = run_checkers(project)

    if args.write_baseline:
        write_baseline(baseline_path, findings)
        print(f"gklint: wrote {len(findings)} finding(s) to "
              f"{baseline_path}")
        return 0

    if args.all:
        for f in findings:
            print(f.render())
        print(f"gklint: {len(findings)} finding(s) "
              f"({len(project.files)} files analyzed)")
        return 0

    baseline = load_baseline(baseline_path)
    new, stale = ratchet(findings, baseline)
    for line in new:
        print(f"NEW: {line}")
    if args.check:
        for line in stale:
            print(f"STALE SUPPRESSION: {line}")
    if new or (args.check and stale):
        if new:
            print(f"gklint: {len(new)} new finding(s) — fix them or "
                  "allow() them with a reason")
        if args.check and stale:
            print(f"gklint: {len(stale)} stale suppression(s) — the "
                  "findings are fixed, shrink gklint_baseline.json "
                  "(python -m tools.gklint --write-baseline)")
        return 1
    print(f"gklint: clean ({len(findings)} baselined finding(s), "
          f"{len(project.files)} files analyzed)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
