"""CLI: python -m tools.gklint [--check | --write-baseline | ...]

Modes:
  (default)          print findings not covered by the baseline
  --check            CI gate: exit 1 on new findings OR stale
                     suppressions (the two-way ratchet)
  --write-baseline   regenerate gklint_baseline.json from the current
                     tree (review the diff — shrinking is progress,
                     growing needs a reason)
  --all              print every finding, baselined or not
  --stages-md        render the README stage table from
                     control/stages.py and exit
  --locktrace-report FILE
                     gate on a locktrace JSONL dump (utils/locktrace
                     written by GATEKEEPER_TPU_LOCKTRACE=1 runs):
                     exit 1 on lock-order cycles / inversions;
                     held-across-blocking events print as advisory
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Optional

from .core import Project, load_baseline, ratchet, run_checkers, \
    write_baseline


def _repo_root() -> str:
    here = os.path.dirname(os.path.abspath(__file__))
    return os.path.dirname(os.path.dirname(here))


def _gated_lock_sites(root: str) -> set:
    """Allocation sites whose held-across-blocking findings GATE.

    A `# locktrace: gate` comment on a lock's construction line is the
    code declaring "nothing blocking may ever run under me" (e.g. the
    audit _sweep_lock, which every status-write path must exit before
    any kube retry backoff can sleep). Returns {(relpath, lineno)}."""
    sites = set()
    pkg = os.path.join(root, "gatekeeper_tpu")
    for dirpath, _dirs, files in os.walk(pkg):
        for name in files:
            if not name.endswith(".py"):
                continue
            path = os.path.join(dirpath, name)
            try:
                with open(path, encoding="utf-8") as f:
                    for lineno, line in enumerate(f, 1):
                        if "# locktrace: gate" in line:
                            rel = os.path.relpath(path, root)
                            sites.add((rel.replace(os.sep, "/"),
                                       lineno))
            except OSError:
                continue
    return sites


def _site_gated(site: str, gated: set) -> bool:
    """Locktrace sites are `<co_filename>:<lineno>` (absolute or
    relative, depending on how the process was launched); match on
    path SUFFIX + exact line."""
    path, sep, lineno = site.rpartition(":")
    if not sep or not lineno.isdigit():
        return False
    path = path.replace(os.sep, "/")
    n = int(lineno)
    return any(n == gl and (path == gp or path.endswith("/" + gp))
               for gp, gl in gated)


def locktrace_gate(report_path: str, root: Optional[str] = None) -> int:
    """Read a locktrace JSONL dump (one finding per line, possibly
    appended by several processes) and fail on cycles/inversions, plus
    held-across-blocking events under locks marked `# locktrace: gate`
    (every other held-across-blocking event stays advisory)."""
    if not os.path.exists(report_path):
        print(f"gklint: no locktrace dump at {report_path} "
              "(no traced process ran, or none found anything)")
        return 0
    gated_sites = _gated_lock_sites(root or _repo_root())
    bad = gated = advisory = 0
    with open(report_path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                ent = json.loads(line)
            except ValueError:
                continue
            kind = ent.get("kind")
            if kind in ("cycle", "inversion"):
                bad += 1
                print(f"LOCKTRACE {kind}: {ent.get('detail')}")
            elif kind == "held_across_blocking":
                sites = ent.get("sites") or []
                if isinstance(sites, str):
                    sites = [sites]
                if any(_site_gated(s, gated_sites) for s in sites):
                    # the held lock's allocation is marked
                    # `# locktrace: gate`: blocking under it is a
                    # regression, not a smell
                    gated += 1
                    print(f"LOCKTRACE GATED held-across-blocking: "
                          f"{ent.get('detail')}")
                else:
                    # advisory: a bounded sleep under an unmarked lock
                    # is a smell, not a deadlock — report, never gate
                    advisory += 1
                    print(f"LOCKTRACE advisory held-across-blocking: "
                          f"{ent.get('detail')}")
    if bad or gated:
        if bad:
            print(f"gklint: {bad} locktrace cycle/inversion "
                  "finding(s) — potential deadlock under the chaos "
                  "suite")
        if gated:
            print(f"gklint: {gated} held-across-blocking finding(s) "
                  "under gate-marked lock(s) — blocking calls "
                  "regressed under a lock declared blocking-free")
        return 1
    print(f"gklint: locktrace clean ({advisory} advisory "
          "held-across-blocking event(s))")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="gklint")
    ap.add_argument("--check", action="store_true")
    ap.add_argument("--write-baseline", action="store_true")
    ap.add_argument("--all", action="store_true")
    ap.add_argument("--stages-md", action="store_true")
    ap.add_argument("--locktrace-report", metavar="FILE")
    ap.add_argument("--root", default=_repo_root())
    ap.add_argument("--baseline", default=None,
                    help="baseline path (default <root>/"
                         "gklint_baseline.json)")
    args = ap.parse_args(argv)

    if args.locktrace_report:
        return locktrace_gate(args.locktrace_report, root=args.root)

    if args.stages_md:
        import runpy

        mod = runpy.run_path(os.path.join(
            args.root, "gatekeeper_tpu/control/stages.py"))
        print(mod["stages_markdown"]())
        return 0

    baseline_path = args.baseline or os.path.join(
        args.root, "gklint_baseline.json")
    project = Project(args.root)
    findings = run_checkers(project)

    if args.write_baseline:
        write_baseline(baseline_path, findings)
        print(f"gklint: wrote {len(findings)} finding(s) to "
              f"{baseline_path}")
        return 0

    if args.all:
        for f in findings:
            print(f.render())
        print(f"gklint: {len(findings)} finding(s) "
              f"({len(project.files)} files analyzed)")
        return 0

    baseline = load_baseline(baseline_path)
    new, stale = ratchet(findings, baseline)
    for line in new:
        print(f"NEW: {line}")
    if args.check:
        for line in stale:
            print(f"STALE SUPPRESSION: {line}")
    if new or (args.check and stale):
        if new:
            print(f"gklint: {len(new)} new finding(s) — fix them or "
                  "allow() them with a reason")
        if args.check and stale:
            print(f"gklint: {len(stale)} stale suppression(s) — the "
                  "findings are fixed, shrink gklint_baseline.json "
                  "(python -m tools.gklint --write-baseline)")
        return 1
    print(f"gklint: clean ({len(findings)} baselined finding(s), "
          f"{len(project.files)} files analyzed)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
