"""Name-resolution call graph over the analyzed package.

Deliberately conservative: an edge is added only when the callee
resolves — ``self.m()`` to a method of the same class (or a repo base
class), bare names to same-module functions or ``from x import name``
imports, ``mod.fn()`` through module imports, and ``self.attr.m()``
through ``self.attr = ClassName(...)`` assignments seen anywhere in
the class. Unresolvable calls are silently not followed (the checkers
flag *operations*, so an unfollowed edge can only under-report, never
false-positive).

Callables passed as arguments (``Thread(target=fn)``,
``pool.submit(fn)``) are NOT edges: they run on another thread, which
is exactly what the no-block checker must not conflate with the
caller's inline path.
"""

from __future__ import annotations

import ast
from typing import Optional

from .core import Project, dotted


class FuncInfo:
    __slots__ = ("qual", "path", "node", "cls", "name")

    def __init__(self, qual: str, path: str, node, cls: Optional[str]):
        self.qual = qual          # "path::Class.method" / "path::func"
        self.path = path
        self.node = node
        self.cls = cls
        self.name = node.name


class CallGraph:
    def __init__(self, project: Project):
        self.project = project
        self.funcs: dict[str, FuncInfo] = {}
        # path -> {local name -> module path or "path::func"} imports
        self._imports: dict[str, dict[str, str]] = {}
        # "path::Class" -> {attr -> "path::Class2"} for self.attr = C()
        self._attr_types: dict[str, dict[str, str]] = {}
        # "path::Class" -> base "path::Class" chain (single level deep
        # is enough for this codebase)
        self._bases: dict[str, list[str]] = {}
        self._classes: dict[str, ast.ClassDef] = {}
        for path, sf in project.files.items():
            self._index_file(path, sf)
        for path, sf in project.files.items():
            self._index_attr_types(path, sf)

    # ------------------------------------------------------------ index

    def _mod_path(self, module: str) -> Optional[str]:
        """'gatekeeper_tpu.control.metrics' -> its repo-relative path."""
        rel = module.replace(".", "/") + ".py"
        if rel in self.project.files:
            return rel
        rel = module.replace(".", "/") + "/__init__.py"
        return rel if rel in self.project.files else None

    def _index_file(self, path: str, sf) -> None:
        imports: dict[str, str] = {}
        pkg_dir = "/".join(path.split("/")[:-1])
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    mp = self._mod_path(a.name)
                    if mp:
                        imports[a.asname or a.name.split(".")[0]] = mp
            elif isinstance(node, ast.ImportFrom):
                if node.level:
                    base = path.split("/")[:-1]
                    if node.level > 1:
                        base = base[: -(node.level - 1)]
                    prefix = "/".join(base)
                else:
                    prefix = (node.module or "").replace(".", "/")
                mod = node.module or ""
                for a in node.names:
                    # from .mod import name  (module or symbol)
                    if node.level and mod:
                        mp = f"{prefix}/{mod.replace('.', '/')}.py"
                    elif node.level:
                        mp = f"{prefix}/{a.name}.py"
                    else:
                        mp = self._mod_path(mod) or ""
                    local = a.asname or a.name
                    if node.level and not mod and mp in self.project.files:
                        imports[local] = mp  # from . import sibling
                        continue
                    if mp in self.project.files:
                        imports[local] = f"{mp}::{a.name}"
                    else:
                        # from .mod import name where mod is the module
                        mp2 = self._mod_path(
                            f"{mod}") if not node.level else None
                        if mp2:
                            imports[local] = f"{mp2}::{a.name}"
        self._imports[path] = imports
        del pkg_dir

        for node in sf.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{path}::{node.name}"
                self.funcs[qual] = FuncInfo(qual, path, node, None)
            elif isinstance(node, ast.ClassDef):
                cqual = f"{path}::{node.name}"
                self._classes[cqual] = node
                bases = []
                for b in node.bases:
                    bq = self._resolve_class(path, dotted(b))
                    if bq:
                        bases.append(bq)
                self._bases[cqual] = bases
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        qual = f"{path}::{node.name}.{item.name}"
                        self.funcs[qual] = FuncInfo(qual, path, item,
                                                    node.name)

    def _resolve_class(self, path: str, name: str) -> Optional[str]:
        if not name:
            return None
        if "." in name:
            head, _, tail = name.partition(".")
            target = self._imports.get(path, {}).get(head)
            if target and "::" not in target:
                cq = f"{target}::{tail}"
                return cq if cq in self._classes else None
            return None
        cq = f"{path}::{name}"
        if cq in self._classes:
            return cq
        target = self._imports.get(path, {}).get(name)
        if target and "::" in target and target in [
                f"{p}::{c.name}" for p, c in (
                    (q.split("::")[0], cls)
                    for q, cls in self._classes.items())]:
            return target
        if target and target in self._classes:
            return target
        return None

    def _index_attr_types(self, path: str, sf) -> None:
        for node in sf.tree.body:
            if not isinstance(node, ast.ClassDef):
                continue
            cqual = f"{path}::{node.name}"
            attrs: dict[str, str] = {}
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Assign) or len(sub.targets) != 1:
                    continue
                tgt = sub.targets[0]
                if not (isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"
                        and isinstance(sub.value, ast.Call)):
                    continue
                cls = self._resolve_class(path, dotted(sub.value.func))
                if cls:
                    attrs[tgt.attr] = cls
            if attrs:
                self._attr_types[cqual] = attrs

    # ---------------------------------------------------------- resolve

    def _method_of(self, cqual: str, name: str) -> Optional[str]:
        seen = set()
        stack = [cqual]
        while stack:
            cq = stack.pop()
            if cq in seen:
                continue
            seen.add(cq)
            q = f"{cq.split('::')[0]}::{cq.split('::')[1]}.{name}"
            if q in self.funcs:
                return q
            stack.extend(self._bases.get(cq, ()))
        return None

    def resolve_call(self, caller: FuncInfo, call: ast.Call
                     ) -> Optional[str]:
        """Qualname of the callee, or None when unresolvable."""
        f = call.func
        path = caller.path
        if isinstance(f, ast.Name):
            q = f"{path}::{f.id}"
            if q in self.funcs:
                return q
            target = self._imports.get(path, {}).get(f.id)
            if target and "::" in target and target in self.funcs:
                return target
            return None
        if not isinstance(f, ast.Attribute):
            return None
        base = f.value
        if isinstance(base, ast.Name) and base.id == "self" and caller.cls:
            return self._method_of(f"{path}::{caller.cls}", f.attr)
        if isinstance(base, ast.Attribute) and \
                isinstance(base.value, ast.Name) and \
                base.value.id == "self" and caller.cls:
            cls = self._attr_types.get(
                f"{path}::{caller.cls}", {}).get(base.attr)
            if cls:
                return self._method_of(cls, f.attr)
            return None
        if isinstance(base, ast.Name):
            target = self._imports.get(path, {}).get(base.id)
            if target and "::" not in target:
                q = f"{target}::{f.attr}"
                return q if q in self.funcs else None
            # local var of a known class: Name assigned from ClassName()
            cls = self._local_type(caller, base.id)
            if cls:
                return self._method_of(cls, f.attr)
        return None

    def _local_type(self, caller: FuncInfo, name: str) -> Optional[str]:
        for sub in ast.walk(caller.node):
            if isinstance(sub, ast.Assign) and len(sub.targets) == 1 \
                    and isinstance(sub.targets[0], ast.Name) \
                    and sub.targets[0].id == name \
                    and isinstance(sub.value, ast.Call):
                cls = self._resolve_class(caller.path,
                                          dotted(sub.value.func))
                if cls:
                    return cls
        return None

    def calls_in(self, fn: FuncInfo):
        """Call nodes in fn's own body (nested defs excluded — they run
        when called, on whatever thread calls them)."""
        nested = set()
        for sub in ast.walk(fn.node):
            if sub is not fn.node and isinstance(
                    sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
                for inner in ast.walk(sub):
                    nested.add(inner)
        for sub in ast.walk(fn.node):
            if isinstance(sub, ast.Call) and sub not in nested:
                yield sub
